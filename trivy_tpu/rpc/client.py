"""RPC client: remote scan driver + remote cache
(reference pkg/rpc/client/client.go + pkg/cache/remote.go).

RemoteDriver implements the scanner Driver seam over HTTP; RemoteCache
implements the ArtifactCache write interface so analysis results land in
the server's cache. Transport is a persistent keep-alive
http.client.HTTPConnection per thread (fleet lanes each hold their own
socket), so a fleet run pays TCP connect + handshake once per lane
instead of once per scan; a stale keep-alive socket (server closed it
idle) is rebuilt transparently. Both clients accept a comma-separated
URL naming a replica SET: routing then goes through
trivy_tpu/fleet/endpoints.py EndpointSet (client-side load balancing,
per-replica circuit breakers, failover, hedged requests —
docs/fleet.md); a single URL keeps the exact single-server path. Transient failures retry under a
RetryPolicy with decorrelated jitter; 503 responses honor Retry-After;
the ambient per-scan deadline budget (resilience.retry.deadline_scope)
rides the X-Trivy-Deadline header and bounds both the per-request
socket timeout and the total retry loop. Large bodies gzip under the
wire.py negotiation. Fault-injection rules (resilience.faults) are
consulted before every request so degraded-network behavior is
testable deterministically.
"""

from __future__ import annotations

import http.client
import json
import random
import threading

from trivy_tpu.analysis.witness import make_lock
import time
import urllib.error
import urllib.request
from urllib.parse import urlsplit

from trivy_tpu.log import logger
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs import tracing
from trivy_tpu.obs import usage
from trivy_tpu.resilience import faults
from trivy_tpu.resilience.retry import (
    DEADLINE_HEADER,
    DeadlineExceeded,
    RetryPolicy,
    current_deadline,
    parse_retry_after,
)
from trivy_tpu.rpc import columnar as colwire
from trivy_tpu.rpc import wire
from trivy_tpu.rpc.server import CACHE_PREFIX, SCAN_PATH

_log = logger("rpc.client")

DEFAULT_RETRY = RetryPolicy(attempts=3, base_s=0.5, cap_s=10.0)

# fault-injection site for the columnar wire negotiation itself
# (docs/resilience.md): drop renegotiates to JSON, error costs the
# columnar attempt (one retry, then JSON), corrupt flips bytes in the
# outgoing frame so the server's checksum reject drives the resend
WIRE_SITE = "rpc.wire"


class RPCError(Exception):
    pass


class _WireError(RPCError):
    """Internal: an injected columnar wire error; retryable within
    _post_attempts (never escapes it)."""


class RPCUnavailable(RPCError):
    """Transport-level / retries-exhausted failure: the endpoint did
    not produce a definite answer. Distinct from a deterministic 4xx
    RPCError so the fleet EndpointSet knows a failover to another
    replica may still succeed (docs/fleet.md)."""


class RPCBackpressure(RPCUnavailable):
    """Retries exhausted against a replica that was deliberately
    shedding (503 + Retry-After from drain or overload). Still an
    RPCUnavailable — failover to another replica is the right move —
    but the EndpointSet must NOT count it against the breaker: the
    replica answered coherently, so an overloaded-but-healthy fleet
    never cascades into open breakers (docs/fleet.md)."""


class _Conn:
    def __init__(self, url: str, token: str | None = None,
                 custom_headers: dict | None = None, timeout: float = 300.0,
                 retry: RetryPolicy | None = None):
        self.base = url.rstrip("/")
        parts = urlsplit(self.base if "//" in self.base
                         else "http://" + self.base)
        self._https = parts.scheme == "https"
        self._netloc = parts.netloc
        self._path_prefix = parts.path.rstrip("/")
        self.token = token
        self.custom_headers = custom_headers or {}
        self.timeout = timeout
        self.retry = retry or DEFAULT_RETRY
        self._rng = random.Random(self.retry.seed)
        # one persistent keep-alive connection PER THREAD: fleet lanes
        # never share a socket (http.client is not thread-safe), and
        # each lane amortizes its TCP connect across its whole run
        self._tls = threading.local()
        self._all_conns: set = set()
        self._conns_lock = make_lock("rpc.client._conns_lock")
        # sticky capability learned from the first response's
        # X-Trivy-Gzip header: only then are REQUEST bodies gzipped
        # (an old server must never see a gzip request body)
        self._server_gzip = False
        # same ladder for the columnar wire: only after a response
        # carries X-Trivy-Columnar are REQUEST bodies sent columnar
        # (an old server must never see a columnar request body)
        self._server_columnar = False
        # http_proxy/https_proxy/no_proxy targets go through urllib
        # (which implements proxy routing); keep-alive sockets are for
        # direct connections only
        self._via_proxy = self._proxy_configured()
        # a retired conn belongs to an endpoint REMOVED from its fleet
        # set: it refuses new requests so a stale thread-local cannot
        # resurrect the replica (docs/fleet.md)
        self._retired = False

    def _proxy_configured(self) -> bool:
        proxies = urllib.request.getproxies()
        scheme = "https" if self._https else "http"
        if scheme not in proxies:
            return False
        host = self._netloc.rsplit("@", 1)[-1]
        try:
            return not urllib.request.proxy_bypass(host)
        except OSError:
            return True

    # ------------------------------------------------------- transport

    def _connection(self, timeout: float) -> http.client.HTTPConnection:
        c = getattr(self._tls, "conn", None)
        if c is not None and getattr(c, "_ttpu_close_deferred", False):
            # a concurrent close() asked for teardown while this thread
            # was mid-request: honor it now, then hand out a fresh conn
            self._drop_connection()
            c = None
        if c is None:
            cls = (http.client.HTTPSConnection if self._https
                   else http.client.HTTPConnection)
            c = cls(self._netloc, timeout=timeout)
            self._tls.conn = c
        # (re-)register every handout: a thread whose socket close()
        # severed may auto-reopen this conn object, and a later close()
        # must still find it
        with self._conns_lock:
            self._all_conns.add(c)
        c.timeout = timeout
        if c.sock is not None:
            try:
                c.sock.settimeout(timeout)
            except OSError:
                # the socket died under us (closed fd): rebuild fresh
                self._drop_connection()
                return self._connection(timeout)
        return c

    def _drop_connection(self) -> None:
        c = getattr(self._tls, "conn", None)
        if c is not None:
            self._tls.conn = None
            with self._conns_lock:
                self._all_conns.discard(c)
            try:
                c.close()
            except OSError:
                pass

    def close(self) -> None:
        """Close every IDLE thread's keep-alive socket (best effort).
        A pooled connection stays usable: the next request auto-reopens
        and re-registers its socket.

        Pooled _Conns are shared across threads (one socket per
        thread), so a conn currently INSIDE a request on another
        thread must not be torn down under it — closing it there races
        http.client's response read (fp=None mid-read, observed as an
        AttributeError under the capstone bench's fleet). Busy conns
        are marked close-deferred instead; the owning thread finishes
        its round trip and closes on its next handout."""
        with self._conns_lock:
            conns, self._all_conns = list(self._all_conns), set()
        for c in conns:
            if getattr(c, "_ttpu_busy", False):
                c._ttpu_close_deferred = True
                continue
            try:
                c.close()
            except OSError:
                pass
        self._tls.conn = None

    def retire(self) -> None:
        """Endpoint-aware teardown: this conn's endpoint left the
        fleet set. Every thread's keep-alive socket is closed (busy
        ones right after their in-flight round trip via the deferred
        path), and any LATER request on this conn — e.g. from a thread
        still holding it in a thread-local — fails instead of quietly
        reopening a socket to the removed replica."""
        self._retired = True
        self.close()

    def _request_once(self, path: str, body: bytes, headers: dict,
                      timeout: float):
        """One HTTP round trip on this thread's keep-alive connection.
        -> (status, response headers, body bytes). A stale keep-alive
        (the server closed the idle socket between requests) is rebuilt
        and resent ONCE transparently, so the retry policy only ever
        sees real failures; timeouts are never transparently resent
        (the deadline budget owns those)."""
        if self._retired:
            raise RPCUnavailable(
                f"endpoint {self.base} retired (removed from its "
                "endpoint set)")
        if self._via_proxy:
            return self._request_via_urllib(path, body, headers, timeout)
        reused = getattr(self._tls, "conn", None) is not None \
            and getattr(self._tls.conn, "sock", None) is not None
        conn = self._connection(timeout)
        url_path = self._path_prefix + path
        try:
            resp, data = self._roundtrip(conn, url_path, body, headers)
        except TimeoutError:
            self._drop_connection()
            raise
        except (http.client.HTTPException, ConnectionError, OSError):
            self._drop_connection()
            if not reused:
                raise
            conn = self._connection(timeout)
            try:
                resp, data = self._roundtrip(conn, url_path, body,
                                             headers)
            except (http.client.HTTPException, ConnectionError, OSError):
                self._drop_connection()
                raise
        if resp.will_close:
            # the server asked for Connection: close; the next request
            # auto-reopens (http.client auto_open), nothing to do
            pass
        return resp.status, resp.headers, data

    def _roundtrip(self, conn, url_path: str, body: bytes,
                   headers: dict):
        """One request/response on `conn`, marked busy for the
        duration so a concurrent close() of this (pooled, shared)
        _Conn defers teardown instead of yanking the socket out from
        under the in-flight response read."""
        conn._ttpu_busy = True
        try:
            conn.request("POST", url_path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            return resp, data
        finally:
            conn._ttpu_busy = False
            if getattr(conn, "_ttpu_close_deferred", False):
                # the close that was deferred to us: the response is
                # consumed, teardown is safe now
                self._drop_connection()

    def _request_via_urllib(self, path: str, body: bytes, headers: dict,
                            timeout: float):
        """Proxy-routed fallback (no keep-alive): urllib implements the
        http_proxy/https_proxy/no_proxy handling this client must keep
        honoring. Same (status, headers, body) contract."""
        req = urllib.request.Request(
            self.base + path, data=body, headers=headers, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, r.headers, r.read()
        except urllib.error.HTTPError as exc:
            with exc:
                return exc.code, exc.headers, exc.read()

    # ------------------------------------------------------------ post

    @staticmethod
    def _span_meta(base: str) -> dict:
        # a fleet dispatch (hedged / failover retry) carries its
        # attempt identity on the client span too, so the stitched
        # cross-replica trace shows which attempt each round trip
        # belonged to (fleet/telemetry.py)
        meta = {"url": base}
        tag = tracing.current_attempt_tag()
        if tag is not None:
            meta["attempt"] = str(tag[0])
            meta["endpoint"] = str(tag[1])
        return meta

    def post(self, path: str, body: bytes, columnar=None,
             json_only: bool = False) -> bytes:
        # one client span covers the whole retried call; the trace
        # identity rides X-Trivy-Trace so the server's handler span
        # becomes this span's child (docs/observability.md)
        method = path.rsplit("/", 1)[-1]
        with tracing.span(f"rpc.{method}", **self._span_meta(self.base)):
            return self._post_attempts(path, method, body,
                                       columnar=columnar,
                                       json_only=json_only)

    def post_once(self, path: str, body: bytes, columnar=None,
                  json_only: bool = False) -> bytes:
        """Single-attempt post: the fleet EndpointSet drives its own
        failover loop ACROSS endpoints, so the per-endpoint retry loop
        collapses to one attempt (the stale-keep-alive rebuild inside
        _request_once still applies — it is transport plumbing, not a
        retry)."""
        method = path.rsplit("/", 1)[-1]
        with tracing.span(f"rpc.{method}", **self._span_meta(self.base)):
            return self._post_attempts(path, method, body, attempts=1,
                                       columnar=columnar,
                                       json_only=json_only)

    def _post_attempts(self, path: str, method: str, body: bytes,
                       attempts: int | None = None, columnar=None,
                       json_only: bool = False) -> bytes:
        # the extended-fidelity internal encoding is marked so the server
        # can tell it apart from reference Twirp clients on the same paths
        headers = {"Content-Type": "application/json",
                   "X-Trivy-Tpu-Wire": "internal",
                   "Accept-Encoding": "gzip",
                   **self.custom_headers}
        if self.token:
            headers["Trivy-Token"] = self.token
        tracing.inject_headers(headers)
        policy = self.retry
        attempts = policy.attempts if attempts is None else attempts
        deadline = current_deadline()
        delays = policy.delays(self._rng)
        site = faults.rpc_site(path)
        # columnar offer: ``columnar`` is a zero-arg thunk producing the
        # columnar request bytes, evaluated lazily at most once — and
        # only after this conn has learned the server speaks columnar
        # (the X-Trivy-Columnar capability ladder, docs/performance.md)
        offer_columnar = (columnar is not None and colwire.enabled()
                          and not json_only)
        col_bytes: bytes | None = None
        col_fails = 0    # columnar attempts lost to the wire ladder
        wire_extra = 0   # extra attempts granted for columnar->JSON
        last_err: Exception | None = None
        shed = False  # last failure was a deliberate 503 + Retry-After
        attempt = 0
        while attempt < attempts + wire_extra:
            if deadline is not None and deadline.expired:
                raise DeadlineExceeded(
                    f"rpc to {self.base}{path}: deadline of "
                    f"{deadline.budget_s:.3f}s exhausted"
                    + (f" (last error: {last_err})" if last_err else ""),
                    budget_s=deadline.budget_s)
            hdrs = dict(headers)
            if deadline is not None:
                hdrs[DEADLINE_HEADER] = deadline.header_value()
            use_columnar = (offer_columnar and self._server_columnar
                            and col_fails < 2)
            if offer_columnar:
                hdrs["Accept"] = (colwire.CONTENT_TYPE
                                  + ", application/json")
            if use_columnar:
                if col_bytes is None:
                    col_bytes = columnar()
                # frames carry their own per-frame deflate; whole-body
                # gzip would defeat frame-at-a-time decode
                payload = send_body = col_bytes
                hdrs["Content-Type"] = colwire.CONTENT_TYPE
            else:
                payload = send_body = body
                if self._server_gzip and len(body) >= wire.GZIP_MIN_BYTES:
                    send_body = wire.gzip_bytes(body)
                    hdrs["Content-Encoding"] = "gzip"
            # client-side cost vector (no-ops without an ambient usage
            # scope): payload bytes pre-compression, wire bytes as
            # actually sent, accrued per attempt — retries really do
            # re-ship bytes
            usage.add("bytes_out", float(len(payload)))
            usage.add("wire_bytes_out", float(len(send_body)))
            obs_metrics.WIRE_REQUESTS.inc(
                format="columnar" if use_columnar else "json",
                direction="out")
            retry_after: float | None = None
            corrupt = False
            try:
                for rule in faults.fire(site):
                    if rule.action == "delay":
                        policy.sleep(rule.param or 0.0)
                    elif rule.action == "drop":
                        raise urllib.error.URLError(
                            ConnectionRefusedError("injected drop"))
                    elif rule.action == "timeout":
                        raise TimeoutError("injected timeout")
                    elif rule.action == "error":
                        raise faults.InjectedHTTPError(
                            int(rule.param or 503))
                    elif rule.action == "corrupt":
                        corrupt = True
                if offer_columnar:
                    for rule in faults.fire(WIRE_SITE):
                        if rule.action == "delay":
                            policy.sleep(rule.param or 0.0)
                        elif rule.action == "drop" and use_columnar:
                            # the columnar channel dropped mid-flight:
                            # forget the capability and renegotiate —
                            # the retry goes JSON, and the next 2xx
                            # response re-advertises columnar
                            self._server_columnar = False
                            wire_extra = min(wire_extra + 1, 2)
                            obs_metrics.WIRE_FALLBACKS.inc(reason="drop")
                            raise urllib.error.URLError(
                                ConnectionResetError(
                                    "injected columnar drop"))
                        elif rule.action == "error" and use_columnar:
                            # one columnar retry; a second error falls
                            # this call back to JSON for good
                            col_fails += 1
                            wire_extra = min(wire_extra + 1, 2)
                            if col_fails >= 2:
                                obs_metrics.WIRE_FALLBACKS.inc(
                                    reason="error")
                            raise _WireError(
                                "injected columnar wire error")
                        elif rule.action == "corrupt" and use_columnar:
                            # flip bytes in the outgoing frames: the
                            # server's checksum reject (400) drives the
                            # JSON resend below
                            send_body = faults.corrupt_bytes(send_body)
                timeout = self.timeout
                if deadline is not None:
                    # small grace past the budget: a deadline-aware
                    # server sheds AT the deadline and replies 503 +
                    # Retry-After — waiting a moment longer turns a
                    # blind socket timeout into that definite answer
                    timeout = max(0.001, min(
                        timeout, deadline.remaining() + 0.5))
                rt_start = time.perf_counter()
                try:
                    status, rhdrs, raw = self._request_once(
                        path, send_body, hdrs, timeout)
                finally:
                    # per-attempt round-trip latency, errors included;
                    # the ambient rpc.<method> span's trace id rides
                    # along as an OpenMetrics exemplar so a tail bucket
                    # names the exact trace that landed there
                    cur = tracing.current()
                    obs_metrics.RPC_CLIENT_SECONDS.observe(
                        time.perf_counter() - rt_start, method=method,
                        exemplar=cur.trace_id if cur is not None
                        else None)
                if rhdrs.get(wire.GZIP_CAPABLE_HEADER):
                    self._server_gzip = True
                if rhdrs.get(colwire.CAPABLE_HEADER):
                    self._server_columnar = True
                usage.add("wire_bytes_in", float(len(raw)))
                if "gzip" in (rhdrs.get("Content-Encoding")
                              or "").lower():
                    raw = wire.gunzip_bytes(raw)
                usage.add("bytes_in", float(len(raw)))
                if status >= 300:
                    # non-2xx is an error, named by status: 3xx included
                    # (a redirecting ingress is a config problem this
                    # client won't chase) and deterministic like 4xx —
                    # only 5xx retries
                    detail = raw.decode("utf-8", "replace")[:500]
                    if hdrs.get("Content-Encoding") == "gzip" \
                            and not rhdrs.get(wire.GZIP_CAPABLE_HEADER):
                        # ANY error (4xx or 5xx) to our gzip request
                        # from a server NOT advertising gzip capability
                        # is an old/rolled-back replica choking on the
                        # encoding: forget the sticky capability and
                        # let the retry resend plain
                        self._server_gzip = False
                        shed = False
                        last_err = RPCError(
                            f"{status} to gzip request from a server "
                            f"without gzip capability: {detail}")
                    elif use_columnar \
                            and not rhdrs.get(colwire.CAPABLE_HEADER):
                        # same unlearn for the columnar wire: ANY error
                        # to our columnar request from a server NOT
                        # advertising the capability is an old or
                        # rolled-back replica choking on the encoding —
                        # forget the sticky capability and let the
                        # (granted) retry resend JSON
                        self._server_columnar = False
                        wire_extra = min(wire_extra + 1, 2)
                        obs_metrics.WIRE_FALLBACKS.inc(reason="unlearn")
                        shed = False
                        last_err = RPCError(
                            f"{status} to columnar request from a "
                            f"server without columnar capability: "
                            f"{detail}")
                    elif use_columnar and status == 400:
                        # a columnar-capable server rejected our frames
                        # (checksum/truncation — corrupted in transit):
                        # resend this call as JSON
                        col_fails = 2
                        wire_extra = min(wire_extra + 1, 2)
                        obs_metrics.WIRE_FALLBACKS.inc(reason="corrupt")
                        shed = False
                        last_err = RPCError(
                            f"400 columnar frame reject: {detail}")
                    elif status < 500:
                        raise RPCError(f"{status}: {detail}")
                    else:
                        last_err = RPCError(f"{status}: {detail}")
                        # 503 WITH Retry-After is the shed handshake
                        # (drain / overload): the replica is alive and
                        # telling us to come back later
                        shed = (status == 503
                                and rhdrs.get("Retry-After") is not None)
                        if status == 503 and policy.respect_retry_after:
                            retry_after = parse_retry_after(
                                rhdrs.get("Retry-After"))
                else:
                    return faults.corrupt_bytes(raw) if corrupt else raw
            except _WireError as exc:
                shed = False
                last_err = exc
            except faults.InjectedHTTPError as exc:
                if exc.code < 500:
                    raise RPCError(f"{exc.code}: {exc}") from exc
                shed = False
                last_err = RPCError(f"{exc.code}: {exc}")
            except (urllib.error.URLError, http.client.HTTPException,
                    OSError, TimeoutError) as exc:
                shed = False
                last_err = exc
            attempt += 1
            if attempt < attempts + wire_extra:
                delay = next(delays)
                if retry_after is not None:
                    # the server told us when it expects to recover;
                    # never retry earlier than that
                    delay = max(delay, retry_after)
                if deadline is not None and deadline.remaining() <= delay:
                    raise DeadlineExceeded(
                        f"rpc to {self.base}{path}: deadline of "
                        f"{deadline.budget_s:.3f}s leaves no room to retry "
                        f"(last error: {last_err})",
                        budget_s=deadline.budget_s)
                obs_metrics.RETRY_ATTEMPTS.inc(method=method)
                policy.sleep(delay)
        if shed:
            raise RPCBackpressure(
                f"rpc to {self.base}{path} shed after {attempts} "
                f"attempts: {last_err}")
        raise RPCUnavailable(
            f"rpc to {self.base}{path} failed after {attempts} "
            f"attempts: {last_err}")


# process-wide EndpointSet pool keyed by (urls, token) for default-
# configured clients: the CLI builds a fresh RemoteDriver + RemoteCache
# per artifact (fleet runs: per lane-slot), and without sharing, each
# would open its own sockets — the pool makes "TCP connect once per
# lane, not once per scan" actually hold. A single-URL set routes
# through its one _Conn byte-identically to the pre-fleet client; a
# comma-separated URL becomes a replica set with client-side LB,
# failover, and hedging (trivy_tpu/fleet/endpoints.py). Custom retry
# policies or headers opt out (tests and special callers keep private
# connections).
_CONN_POOL: dict[tuple, object] = {}
_CONN_POOL_LOCK = make_lock("rpc.client._CONN_POOL_LOCK")


def _pooled_set(url: str, token: str | None,
                custom_headers: dict | None,
                retry: RetryPolicy | None):
    from trivy_tpu.fleet.endpoints import EndpointSet, split_urls

    urls = tuple(u.rstrip("/") for u in split_urls(url))
    if retry is not None or custom_headers:
        return EndpointSet(list(urls), token, custom_headers,
                           retry=retry)
    key = (urls, token)
    with _CONN_POOL_LOCK:
        c = _CONN_POOL.get(key)
        if c is None:
            c = _CONN_POOL[key] = EndpointSet(list(urls), token)
        return c


class RemoteDriver:
    """Driver implementation that ships the scan to a server
    (reference pkg/rpc/client/client.go:48-73). `url` may name a whole
    replica set (comma-separated) — requests then load-balance with
    failover and hedged tail-latency dispatch (docs/fleet.md)."""

    def __init__(self, url: str, token: str | None = None,
                 custom_headers: dict | None = None,
                 retry: RetryPolicy | None = None):
        self.conn = _pooled_set(url, token, custom_headers, retry)

    def scan(self, target, artifact_key, blob_keys, options):
        body = wire.scan_request(target, artifact_key, blob_keys, options)
        raw = self.conn.post(SCAN_PATH, body, columnar=lambda:
                             colwire.encode_scan_request(
                                 target, artifact_key, blob_keys,
                                 options))
        if colwire.is_columnar(raw):
            try:
                return colwire.decode_scan_response(raw)
            except colwire.WireFormatError as exc:
                # a columnar response that fails its frame checksums
                # (torn/corrupted in transit): refetch once as JSON
                obs_metrics.WIRE_FALLBACKS.inc(reason="corrupt")
                _log.warn("columnar scan response rejected; "
                          "refetching as JSON", err=str(exc))
                raw = self.conn.post(SCAN_PATH, body, json_only=True)
        return wire.decode_scan_response(raw)

    def close(self) -> None:
        self.conn.close()


class RemoteCache:
    """ArtifactCache over RPC (reference pkg/cache/remote.go:27): analysis
    blobs are written into the SERVER's cache; reads happen server-side."""

    def __init__(self, url: str, token: str | None = None,
                 custom_headers: dict | None = None,
                 retry: RetryPolicy | None = None):
        self.conn = _pooled_set(url, token, custom_headers, retry)

    def put_artifact(self, artifact_id: str, info) -> None:
        self.conn.post(CACHE_PREFIX + "PutArtifact", wire.encode(
            {"artifact_id": artifact_id, "artifact_info": info}
        ))

    def put_blob(self, blob_id: str, blob) -> None:
        self.conn.post(CACHE_PREFIX + "PutBlob", wire.encode(
            {"diff_id": blob_id, "blob_info": blob}
        ), columnar=lambda: colwire.encode_put_blob(blob_id, blob))

    def missing_blobs(self, artifact_id: str, blob_ids: list[str]):
        body = wire.encode(
            {"artifact_id": artifact_id, "blob_ids": blob_ids})
        raw = self.conn.post(
            CACHE_PREFIX + "MissingBlobs", body,
            columnar=lambda: colwire.encode_missing_blobs(
                artifact_id, blob_ids))
        if colwire.is_columnar(raw):
            try:
                return colwire.decode_missing_response(raw)
            except colwire.WireFormatError as exc:
                obs_metrics.WIRE_FALLBACKS.inc(reason="corrupt")
                _log.warn("columnar MissingBlobs response rejected; "
                          "refetching as JSON", err=str(exc))
                raw = self.conn.post(CACHE_PREFIX + "MissingBlobs",
                                     body, json_only=True)
        doc = json.loads(raw)
        return doc.get("missing_artifact", True), \
            doc.get("missing_blob_ids", []) or []

    def delete_blobs(self, blob_ids: list[str]) -> None:
        self.conn.post(CACHE_PREFIX + "DeleteBlobs",
                       wire.encode({"blob_ids": blob_ids}))

    # LocalArtifactCache reads never happen client-side in server mode
    def get_artifact(self, artifact_id: str) -> dict:
        return {}

    def get_blob(self, blob_id: str) -> dict:
        return {}

    def close(self) -> None:
        self.conn.close()
