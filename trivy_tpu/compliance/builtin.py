"""Builtin compliance specs (reference: trivy-checks specs/compliance
bundle, loaded by pkg/compliance/spec/compliance.go:86-120).

Each spec maps its controls onto the check IDs this framework's
misconfiguration engine implements (AVD-DS-* dockerfile checks,
AVD-KSV-* kubernetes workload checks) plus the custom severity-filter
IDs (VULN-*/SECRET-*, reference pkg/compliance/spec/custom.go)."""

DOCKER_CIS = """\
spec:
  id: docker-cis-1.6.0
  title: CIS Docker Community Edition Benchmark v1.6.0
  description: CIS Docker Community Edition Benchmark
  version: "1.6.0"
  platform: docker
  type: cis
  relatedResources:
    - https://www.cisecurity.org/benchmark/docker
  controls:
    - id: "4.1"
      name: Ensure a user for the container has been created
      description: Create a non-root user for the container in the
        Dockerfile for the container image.
      checks:
        - id: AVD-DS-0002
      severity: HIGH
    - id: "4.2"
      name: Ensure that containers use only trusted base images
      description: Base images should be reviewed; scan images for
        critical vulnerabilities.
      checks:
        - id: VULN-CRITICAL
      severity: CRITICAL
    - id: "4.4"
      name: Ensure images are scanned and rebuilt to include security patches
      description: Images should be scanned frequently; high severity
        vulnerabilities indicate missing patches.
      checks:
        - id: VULN-HIGH
      severity: HIGH
    - id: "4.6"
      name: Ensure that HEALTHCHECK instructions have been added
      description: Add the HEALTHCHECK instruction to Dockerfiles.
      checks:
        - id: AVD-DS-0026
      severity: LOW
    - id: "4.7"
      name: Ensure update instructions are not used alone in the Dockerfile
      description: Do not use update instructions alone; combine with
        install in a single RUN.
      checks:
        - id: AVD-DS-0017
      severity: HIGH
    - id: "4.8"
      name: Ensure setuid and setgid permissions are removed
      description: Remove setuid/setgid permissions in the images.
      defaultStatus: FAIL
      severity: MEDIUM
    - id: "4.9"
      name: Ensure that COPY is used instead of ADD
      description: Use COPY instead of ADD in Dockerfiles.
      checks:
        - id: AVD-DS-0005
      severity: LOW
    - id: "4.10"
      name: Ensure secrets are not stored in Dockerfiles
      description: Do not store secrets in Dockerfiles.
      checks:
        - id: SECRET-CRITICAL
      severity: CRITICAL
    - id: "5.8"
      name: Ensure privileged ports are not mapped
      description: The container should not expose privileged ports (<1024).
      checks:
        - id: AVD-DS-0004
      severity: MEDIUM
"""

K8S_NSA = """\
spec:
  id: k8s-nsa-1.0
  title: National Security Agency - Kubernetes Hardening Guidance v1.0
  description: Kubernetes Hardening Guidance by NSA and CISA
  version: "1.0"
  platform: k8s
  type: nsa
  relatedResources:
    - https://www.nsa.gov/Press-Room/News-Highlights/Article/Article/2716980/
  controls:
    - id: "1.0"
      name: Non-root containers
      description: Check that container is not running as root
      checks:
        - id: AVD-KSV-0012
      severity: MEDIUM
    - id: "1.1"
      name: Immutable container file systems
      description: Check that container root file system is immutable
      checks:
        - id: AVD-KSV-0014
      severity: LOW
    - id: "1.2"
      name: Preventing privileged containers
      description: Controls whether Pods can run privileged containers
      checks:
        - id: AVD-KSV-0017
      severity: HIGH
    - id: "1.3"
      name: Share containers process namespaces
      description: Controls whether containers can share process namespaces
      checks:
        - id: AVD-KSV-0008
      severity: HIGH
    - id: "1.4"
      name: Share host process namespaces
      description: Controls whether share host process namespaces
      checks:
        - id: AVD-KSV-0010
      severity: HIGH
    - id: "1.5"
      name: Use the host network
      description: Controls whether containers can use the host network
      checks:
        - id: AVD-KSV-0009
      severity: HIGH
    - id: "1.6"
      name: Run with root privileges or with root group membership
      description: Controls whether container applications can run with
        root privileges or with root group membership
      checks:
        - id: AVD-KSV-0029
      severity: LOW
    - id: "1.7"
      name: Restricts escalation to root privileges
      description: Control check restrictions escalation to root privileges
      checks:
        - id: AVD-KSV-0001
      severity: MEDIUM
    - id: "1.8"
      name: Sets the SELinux context of the container
      description: Control checks if pod sets the SELinux context of the container
      checks:
        - id: AVD-KSV-0025
      severity: MEDIUM
    - id: "1.9"
      name: Restrict a container's access to resources with AppArmor
      description: Control checks the restriction of containers access to
        resources with AppArmor
      checks:
        - id: AVD-KSV-0002
      severity: MEDIUM
    - id: "1.10"
      name: Sets the seccomp profile used to sandbox containers
      description: Control checks the sets the seccomp profile used to
        sandbox containers
      checks:
        - id: AVD-KSV-0030
      severity: LOW
    - id: "1.11"
      name: Protecting Pod service account tokens
      description: Control check whether disable secret token been mount
      checks:
        - id: AVD-KSV-0036
      severity: MEDIUM
    - id: "1.12"
      name: Namespace kube-system should not be used by users
      description: Control check whether Namespace kube-system is not be used by users
      checks:
        - id: AVD-KSV-0037
      severity: MEDIUM
    - id: "2.0"
      name: Vulnerability scanning
      description: Scan workload images for critical vulnerabilities
      checks:
        - id: VULN-CRITICAL
      severity: CRITICAL
"""

K8S_PSS_BASELINE = """\
spec:
  id: k8s-pss-baseline-0.1
  title: Kubernetes Pod Security Standards - Baseline
  description: Kubernetes Pod Security Standards - Baseline profile
  version: "0.1"
  platform: k8s
  type: pss
  relatedResources:
    - https://kubernetes.io/docs/concepts/security/pod-security-standards/
  controls:
    - id: "1"
      name: Host Processes
      description: Windows pods offer the ability to run HostProcess containers
      checks:
        - id: AVD-KSV-0103
      severity: HIGH
    - id: "2"
      name: Host Namespaces (PID)
      description: Sharing the host namespaces must be disallowed
      checks:
        - id: AVD-KSV-0010
      severity: HIGH
    - id: "3"
      name: Host Namespaces (IPC)
      description: Sharing the host IPC namespace must be disallowed
      checks:
        - id: AVD-KSV-0008
      severity: HIGH
    - id: "4"
      name: Host Namespaces (network)
      description: Sharing the host network namespace must be disallowed
      checks:
        - id: AVD-KSV-0009
      severity: HIGH
    - id: "5"
      name: Privileged Containers
      description: Privileged Pods disable most security mechanisms and
        must be disallowed
      checks:
        - id: AVD-KSV-0017
      severity: HIGH
    - id: "6"
      name: HostPath Volumes
      description: HostPath volumes must be forbidden
      checks:
        - id: AVD-KSV-0023
      severity: MEDIUM
    - id: "7"
      name: Host Ports
      description: HostPorts should be disallowed entirely or restricted
      checks:
        - id: AVD-KSV-0024
      severity: HIGH
"""

K8S_PSS_RESTRICTED = """\
spec:
  id: k8s-pss-restricted-0.1
  title: Kubernetes Pod Security Standards - Restricted
  description: Kubernetes Pod Security Standards - Restricted profile
  version: "0.1"
  platform: k8s
  type: pss
  relatedResources:
    - https://kubernetes.io/docs/concepts/security/pod-security-standards/
  controls:
    - id: "1"
      name: Privileged Containers
      description: Privileged Pods disable most security mechanisms
      checks:
        - id: AVD-KSV-0017
      severity: HIGH
    - id: "2"
      name: Privilege Escalation
      description: Privilege escalation must not be allowed
      checks:
        - id: AVD-KSV-0001
      severity: MEDIUM
    - id: "3"
      name: Running as Non-root
      description: Containers must be required to run as non-root users
      checks:
        - id: AVD-KSV-0012
      severity: MEDIUM
    - id: "4"
      name: Read-only root filesystem
      description: Containers should use a read-only root filesystem
      checks:
        - id: AVD-KSV-0014
      severity: LOW
    - id: "5"
      name: Capabilities
      description: Containers must drop ALL capabilities
      checks:
        - id: AVD-KSV-0003
      severity: LOW
    - id: "6"
      name: Host Namespaces
      description: Sharing host namespaces must be disallowed
      checks:
        - id: AVD-KSV-0008
        - id: AVD-KSV-0009
        - id: AVD-KSV-0010
      severity: HIGH
"""

K8S_CIS = """\
spec:
  id: k8s-cis-1.23
  title: CIS Kubernetes Benchmark v1.23
  description: CIS Kubernetes Benchmarks
  version: "1.23"
  platform: k8s
  type: cis
  relatedResources:
    - https://www.cisecurity.org/benchmark/kubernetes
  controls:
    - id: 1.2.1
      name: Ensure that the --anonymous-auth argument is set to false
      description: Disable anonymous requests to the API server.
      checks:
        - id: AVD-KCV-0001
      severity: MEDIUM
    - id: 1.2.7
      name: Ensure that the --authorization-mode argument is not set to
        AlwaysAllow
      description: Do not always authorize all requests.
      checks:
        - id: AVD-KCV-0007
      severity: CRITICAL
    - id: 1.2.9
      name: Ensure that the --authorization-mode argument includes RBAC
      description: Turn on Role Based Access Control.
      checks:
        - id: AVD-KCV-0009
      severity: HIGH
    - id: 1.2.16
      name: Ensure that the --insecure-port argument is set to 0
      description: Do not bind the apiserver to an insecure port.
      checks:
        - id: AVD-KCV-0016
      severity: HIGH
    - id: 1.2.18
      name: Ensure that the --profiling argument is set to false
      description: Disable apiserver profiling.
      checks:
        - id: AVD-KCV-0018
      severity: LOW
    - id: 1.3.1
      name: Ensure controller-manager uses per-controller credentials
      description: Use individual service account credentials for each
        controller.
      checks:
        - id: AVD-KCV-0027
      severity: MEDIUM
    - id: 2.1
      name: Ensure that etcd requires client certificates
      description: Enable etcd client certificate authentication.
      checks:
        - id: AVD-KCV-0042
      severity: HIGH
    - id: 2.3
      name: Ensure that the --auto-tls argument is not set to true
      description: Do not use self-signed certificates for etcd TLS.
      checks:
        - id: AVD-KCV-0043
      severity: MEDIUM
    - id: 4.1.1
      name: Ensure kubelet service file permissions are 644 or more
        restrictive
      description: Node collector checks the kubelet service file mode.
      checks:
        - id: AVD-KCV-0067
      severity: HIGH
    - id: 4.1.5
      name: Ensure kubelet.conf permissions are 644 or more restrictive
      description: Node collector checks kubelet.conf file mode.
      checks:
        - id: AVD-KCV-0069
      severity: HIGH
    - id: 4.1.6
      name: Ensure kubelet.conf ownership is root:root
      description: Node collector checks kubelet.conf ownership.
      checks:
        - id: AVD-KCV-0070
      severity: HIGH
    - id: 4.2.1
      name: Ensure that the --anonymous-auth argument is set to false
        (kubelet)
      description: Disable anonymous requests to the kubelet.
      checks:
        - id: AVD-KCV-0077
      severity: CRITICAL
    - id: 4.2.2
      name: Ensure that the kubelet --authorization-mode is not
        AlwaysAllow
      description: Do not allow all requests to the kubelet.
      checks:
        - id: AVD-KCV-0078
      severity: CRITICAL
    - id: 4.2.4
      name: Ensure that the --read-only-port argument is set to 0
      description: Disable the kubelet read-only port.
      checks:
        - id: AVD-KCV-0080
      severity: HIGH
    - id: 4.2.6
      name: Ensure that the --protect-kernel-defaults argument is true
      description: Protect tuned kernel parameters from overriding.
      checks:
        - id: AVD-KCV-0082
      severity: HIGH
    - id: 5.1.1
      name: Ensure that the cluster-admin role is only used where
        required
      description: Avoid binding cluster-admin broadly.
      checks:
        - id: AVD-KSV-0051
      severity: HIGH
    - id: 5.2.2
      name: Minimize the admission of privileged containers
      description: Do not run privileged containers.
      checks:
        - id: AVD-KSV-0017
      severity: HIGH
    - id: 5.2.5
      name: Minimize the admission of containers wishing to share the
        host network namespace
      description: Do not use hostNetwork.
      checks:
        - id: AVD-KSV-0009
      severity: HIGH
"""

EKS_CIS = """\
spec:
  id: eks-cis-1.4
  title: AWS EKS CIS Foundations v1.4
  description: AWS EKS CIS Foundations
  version: "1.4"
  platform: eks
  type: cis
  relatedResources:
    - https://www.cisecurity.org/benchmark/kubernetes
  controls:
    - id: 3.1.1
      name: Ensure kubeconfig file permissions are 644 or more
        restrictive
      description: Node collector checks worker kubeconfig file mode.
      checks:
        - id: AVD-KCV-0073
      severity: HIGH
    - id: 3.1.2
      name: Ensure kubelet kubeconfig ownership is root:root
      description: Node collector checks worker kubeconfig ownership.
      checks:
        - id: AVD-KCV-0074
      severity: HIGH
    - id: 3.2.1
      name: Ensure that the kubelet --anonymous-auth is false
      description: Disable anonymous kubelet requests.
      checks:
        - id: AVD-KCV-0077
      severity: CRITICAL
    - id: 3.2.4
      name: Ensure that the --read-only-port is disabled
      description: Disable the kubelet read-only port.
      checks:
        - id: AVD-KCV-0080
      severity: HIGH
    - id: 3.2.6
      name: Ensure that the --make-iptables-util-chains argument is true
      description: Let the kubelet manage iptables.
      checks:
        - id: AVD-KCV-0083
      severity: HIGH
    - id: 4.1.1
      name: Ensure that the cluster-admin role is only used where
        required
      description: Avoid binding cluster-admin broadly.
      checks:
        - id: AVD-KSV-0051
      severity: HIGH
    - id: 4.2.1
      name: Minimize the admission of privileged containers
      description: Do not run privileged containers.
      checks:
        - id: AVD-KSV-0017
      severity: HIGH
    - id: 5.4.2
      name: Ensure clusters are created with private endpoint enabled
        and public access disabled
      description: EKS cluster endpoint should not be public.
      checks:
        - id: AVD-AWS-0040
      severity: CRITICAL
"""

RKE2_CIS = """\
spec:
  id: rke2-cis-1.24
  title: RKE2 CIS Benchmark v1.24
  description: CIS benchmark controls for RKE2 clusters
  version: "1.24"
  platform: rke2
  type: cis
  relatedResources:
    - https://www.cisecurity.org/benchmark/kubernetes
  controls:
    - id: 1.2.1
      name: Ensure that the --anonymous-auth argument is set to false
      description: Disable anonymous requests to the API server.
      checks:
        - id: AVD-KCV-0001
      severity: MEDIUM
    - id: 1.2.7
      name: Ensure that the --authorization-mode argument is not set to
        AlwaysAllow
      description: Do not always authorize all requests.
      checks:
        - id: AVD-KCV-0007
      severity: CRITICAL
    - id: 2.1
      name: Ensure that etcd requires client certificates
      description: Enable etcd client certificate authentication.
      checks:
        - id: AVD-KCV-0042
      severity: HIGH
    - id: 4.2.1
      name: Ensure that the kubelet --anonymous-auth is false
      description: Disable anonymous kubelet requests.
      checks:
        - id: AVD-KCV-0077
      severity: CRITICAL
    - id: 4.2.6
      name: Ensure that the --protect-kernel-defaults argument is true
      description: Protect tuned kernel parameters from overriding.
      checks:
        - id: AVD-KCV-0082
      severity: HIGH
    - id: 5.2.2
      name: Minimize the admission of privileged containers
      description: Do not run privileged containers.
      checks:
        - id: AVD-KSV-0017
      severity: HIGH
"""

AWS_CIS_14 = """\
spec:
  id: aws-cis-1.4
  title: AWS CIS Foundations Benchmark v1.4
  description: AWS CIS Foundations (IaC surface)
  version: "1.4"
  platform: aws
  type: cis
  relatedResources:
    - https://www.cisecurity.org/benchmark/amazon_web_services
  controls:
    - id: 2.1.3
      name: Ensure MFA Delete is enabled on S3 buckets
      description: Versioning protects against accidental deletion.
      checks:
        - id: AVD-AWS-0090
      severity: MEDIUM
    - id: 2.1.5
      name: Ensure S3 buckets block public access
      description: Block public access at the bucket level.
      checks:
        - id: AVD-AWS-0086
      severity: HIGH
    - id: 2.2.1
      name: Ensure EBS volume encryption is enabled
      description: Encrypt EBS volumes at rest.
      checks:
        - id: AVD-AWS-0026
      severity: HIGH
    - id: 2.3.1
      name: Ensure RDS storage is encrypted
      description: Encrypt RDS instances at rest.
      checks:
        - id: AVD-AWS-0080
      severity: HIGH
    - id: 3.1
      name: Ensure CloudTrail is enabled in all regions
      description: Multi-region trails capture global activity.
      checks:
        - id: AVD-AWS-0014
      severity: MEDIUM
    - id: 3.2
      name: Ensure CloudTrail log file validation is enabled
      description: Log validation detects tampering.
      checks:
        - id: AVD-AWS-0016
      severity: HIGH
    - id: 3.7
      name: Ensure CloudTrail logs are encrypted with KMS CMKs
      description: Encrypt trails with customer-managed keys.
      checks:
        - id: AVD-AWS-0015
      severity: HIGH
    - id: 5.2
      name: Ensure no security groups allow ingress from 0.0.0.0/0 to
        administrative ports
      description: Restrict remote administration ingress.
      checks:
        - id: AVD-AWS-0107
      severity: CRITICAL
"""

AWS_CIS_12 = """\
spec:
  id: aws-cis-1.2
  title: AWS CIS Foundations Benchmark v1.2
  description: AWS CIS Foundations (IaC surface)
  version: "1.2"
  platform: aws
  type: cis
  relatedResources:
    - https://www.cisecurity.org/benchmark/amazon_web_services
  controls:
    - id: 2.1
      name: Ensure CloudTrail is enabled in all regions
      description: Multi-region trails capture global activity.
      checks:
        - id: AVD-AWS-0014
      severity: MEDIUM
    - id: 2.4
      name: Ensure CloudTrail log file validation is enabled
      description: Log validation detects tampering.
      checks:
        - id: AVD-AWS-0016
      severity: HIGH
    - id: 2.7
      name: Ensure CloudTrail logs are encrypted with KMS CMKs
      description: Encrypt trails with customer-managed keys.
      checks:
        - id: AVD-AWS-0015
      severity: HIGH
    - id: 4.1
      name: Ensure no security groups allow ingress from 0.0.0.0/0 to
        port 22
      description: Restrict SSH ingress.
      checks:
        - id: AVD-AWS-0107
      severity: CRITICAL
    - id: 4.3
      name: Ensure the default security group restricts all traffic
      description: Default security groups should deny traffic.
      checks:
        - id: AVD-AWS-0104
      severity: HIGH
"""

BUILTIN_SPECS: dict[str, str] = {
    "docker-cis-1.6.0": DOCKER_CIS,
    "k8s-nsa-1.0": K8S_NSA,
    "k8s-cis-1.23": K8S_CIS,
    "k8s-pss-baseline-0.1": K8S_PSS_BASELINE,
    "k8s-pss-restricted-0.1": K8S_PSS_RESTRICTED,
    "eks-cis-1.4": EKS_CIS,
    "rke2-cis-1.24": RKE2_CIS,
    "aws-cis-1.4": AWS_CIS_14,
    "aws-cis-1.2": AWS_CIS_12,
}
