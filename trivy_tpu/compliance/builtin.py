"""Builtin compliance specs (reference: trivy-checks specs/compliance
bundle, loaded by pkg/compliance/spec/compliance.go:86-120).

Each spec maps its controls onto the check IDs this framework's
misconfiguration engine implements (AVD-DS-* dockerfile checks,
AVD-KSV-* kubernetes workload checks) plus the custom severity-filter
IDs (VULN-*/SECRET-*, reference pkg/compliance/spec/custom.go)."""

DOCKER_CIS = """\
spec:
  id: docker-cis-1.6.0
  title: CIS Docker Community Edition Benchmark v1.6.0
  description: CIS Docker Community Edition Benchmark
  version: "1.6.0"
  platform: docker
  type: cis
  relatedResources:
    - https://www.cisecurity.org/benchmark/docker
  controls:
    - id: "4.1"
      name: Ensure a user for the container has been created
      description: Create a non-root user for the container in the
        Dockerfile for the container image.
      checks:
        - id: AVD-DS-0002
      severity: HIGH
    - id: "4.2"
      name: Ensure that containers use only trusted base images
      description: Base images should be reviewed; scan images for
        critical vulnerabilities.
      checks:
        - id: VULN-CRITICAL
      severity: CRITICAL
    - id: "4.4"
      name: Ensure images are scanned and rebuilt to include security patches
      description: Images should be scanned frequently; high severity
        vulnerabilities indicate missing patches.
      checks:
        - id: VULN-HIGH
      severity: HIGH
    - id: "4.6"
      name: Ensure that HEALTHCHECK instructions have been added
      description: Add the HEALTHCHECK instruction to Dockerfiles.
      checks:
        - id: AVD-DS-0026
      severity: LOW
    - id: "4.7"
      name: Ensure update instructions are not used alone in the Dockerfile
      description: Do not use update instructions alone; combine with
        install in a single RUN.
      checks:
        - id: AVD-DS-0017
      severity: HIGH
    - id: "4.8"
      name: Ensure setuid and setgid permissions are removed
      description: Remove setuid/setgid permissions in the images.
      defaultStatus: FAIL
      severity: MEDIUM
    - id: "4.9"
      name: Ensure that COPY is used instead of ADD
      description: Use COPY instead of ADD in Dockerfiles.
      checks:
        - id: AVD-DS-0005
      severity: LOW
    - id: "4.10"
      name: Ensure secrets are not stored in Dockerfiles
      description: Do not store secrets in Dockerfiles.
      checks:
        - id: SECRET-CRITICAL
      severity: CRITICAL
    - id: "5.8"
      name: Ensure privileged ports are not mapped
      description: The container should not expose privileged ports (<1024).
      checks:
        - id: AVD-DS-0004
      severity: MEDIUM
"""

K8S_NSA = """\
spec:
  id: k8s-nsa-1.0
  title: National Security Agency - Kubernetes Hardening Guidance v1.0
  description: Kubernetes Hardening Guidance by NSA and CISA
  version: "1.0"
  platform: k8s
  type: nsa
  relatedResources:
    - https://www.nsa.gov/Press-Room/News-Highlights/Article/Article/2716980/
  controls:
    - id: "1.0"
      name: Non-root containers
      description: Check that container is not running as root
      checks:
        - id: AVD-KSV-0012
      severity: MEDIUM
    - id: "1.1"
      name: Immutable container file systems
      description: Check that container root file system is immutable
      checks:
        - id: AVD-KSV-0014
      severity: LOW
    - id: "1.2"
      name: Preventing privileged containers
      description: Controls whether Pods can run privileged containers
      checks:
        - id: AVD-KSV-0017
      severity: HIGH
    - id: "1.3"
      name: Share containers process namespaces
      description: Controls whether containers can share process namespaces
      checks:
        - id: AVD-KSV-0008
      severity: HIGH
    - id: "1.4"
      name: Share host process namespaces
      description: Controls whether share host process namespaces
      checks:
        - id: AVD-KSV-0010
      severity: HIGH
    - id: "1.5"
      name: Use the host network
      description: Controls whether containers can use the host network
      checks:
        - id: AVD-KSV-0009
      severity: HIGH
    - id: "1.6"
      name: Run with root privileges or with root group membership
      description: Controls whether container applications can run with
        root privileges or with root group membership
      checks:
        - id: AVD-KSV-0029
      severity: LOW
    - id: "1.7"
      name: Restricts escalation to root privileges
      description: Control check restrictions escalation to root privileges
      checks:
        - id: AVD-KSV-0001
      severity: MEDIUM
    - id: "1.8"
      name: Sets the SELinux context of the container
      description: Control checks if pod sets the SELinux context of the container
      checks:
        - id: AVD-KSV-0025
      severity: MEDIUM
    - id: "1.9"
      name: Restrict a container's access to resources with AppArmor
      description: Control checks the restriction of containers access to
        resources with AppArmor
      checks:
        - id: AVD-KSV-0002
      severity: MEDIUM
    - id: "1.10"
      name: Sets the seccomp profile used to sandbox containers
      description: Control checks the sets the seccomp profile used to
        sandbox containers
      checks:
        - id: AVD-KSV-0030
      severity: LOW
    - id: "1.11"
      name: Protecting Pod service account tokens
      description: Control check whether disable secret token been mount
      checks:
        - id: AVD-KSV-0036
      severity: MEDIUM
    - id: "1.12"
      name: Namespace kube-system should not be used by users
      description: Control check whether Namespace kube-system is not be used by users
      checks:
        - id: AVD-KSV-0037
      severity: MEDIUM
    - id: "2.0"
      name: Vulnerability scanning
      description: Scan workload images for critical vulnerabilities
      checks:
        - id: VULN-CRITICAL
      severity: CRITICAL
"""

K8S_PSS_BASELINE = """\
spec:
  id: k8s-pss-baseline-0.1
  title: Kubernetes Pod Security Standards - Baseline
  description: Kubernetes Pod Security Standards - Baseline profile
  version: "0.1"
  platform: k8s
  type: pss
  relatedResources:
    - https://kubernetes.io/docs/concepts/security/pod-security-standards/
  controls:
    - id: "1"
      name: Host Processes
      description: Windows pods offer the ability to run HostProcess containers
      checks:
        - id: AVD-KSV-0103
      severity: HIGH
    - id: "2"
      name: Host Namespaces (PID)
      description: Sharing the host namespaces must be disallowed
      checks:
        - id: AVD-KSV-0010
      severity: HIGH
    - id: "3"
      name: Host Namespaces (IPC)
      description: Sharing the host IPC namespace must be disallowed
      checks:
        - id: AVD-KSV-0008
      severity: HIGH
    - id: "4"
      name: Host Namespaces (network)
      description: Sharing the host network namespace must be disallowed
      checks:
        - id: AVD-KSV-0009
      severity: HIGH
    - id: "5"
      name: Privileged Containers
      description: Privileged Pods disable most security mechanisms and
        must be disallowed
      checks:
        - id: AVD-KSV-0017
      severity: HIGH
    - id: "6"
      name: HostPath Volumes
      description: HostPath volumes must be forbidden
      checks:
        - id: AVD-KSV-0023
      severity: MEDIUM
    - id: "7"
      name: Host Ports
      description: HostPorts should be disallowed entirely or restricted
      checks:
        - id: AVD-KSV-0024
      severity: HIGH
"""

K8S_PSS_RESTRICTED = """\
spec:
  id: k8s-pss-restricted-0.1
  title: Kubernetes Pod Security Standards - Restricted
  description: Kubernetes Pod Security Standards - Restricted profile
  version: "0.1"
  platform: k8s
  type: pss
  relatedResources:
    - https://kubernetes.io/docs/concepts/security/pod-security-standards/
  controls:
    - id: "1"
      name: Privileged Containers
      description: Privileged Pods disable most security mechanisms
      checks:
        - id: AVD-KSV-0017
      severity: HIGH
    - id: "2"
      name: Privilege Escalation
      description: Privilege escalation must not be allowed
      checks:
        - id: AVD-KSV-0001
      severity: MEDIUM
    - id: "3"
      name: Running as Non-root
      description: Containers must be required to run as non-root users
      checks:
        - id: AVD-KSV-0012
      severity: MEDIUM
    - id: "4"
      name: Read-only root filesystem
      description: Containers should use a read-only root filesystem
      checks:
        - id: AVD-KSV-0014
      severity: LOW
    - id: "5"
      name: Capabilities
      description: Containers must drop ALL capabilities
      checks:
        - id: AVD-KSV-0003
      severity: LOW
    - id: "6"
      name: Host Namespaces
      description: Sharing host namespaces must be disallowed
      checks:
        - id: AVD-KSV-0008
        - id: AVD-KSV-0009
        - id: AVD-KSV-0010
      severity: HIGH
"""

BUILTIN_SPECS: dict[str, str] = {
    "docker-cis-1.6.0": DOCKER_CIS,
    "k8s-nsa-1.0": K8S_NSA,
    "k8s-pss-baseline-0.1": K8S_PSS_BASELINE,
    "k8s-pss-restricted-0.1": K8S_PSS_RESTRICTED,
}
