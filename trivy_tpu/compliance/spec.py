"""Compliance spec model and loading (reference
pkg/compliance/spec/compliance.go + pkg/iac/types/compliance.go).

A spec is a YAML document `spec: {id, title, version, controls: [...]}`;
each control maps to scanner check IDs (AVD-* → misconfig, CVE-*/DLA-* →
vuln) or to custom severity-filter IDs (VULN-CRITICAL, SECRET-HIGH, …).
`--compliance <name>` loads a builtin spec; `--compliance @path` loads a
user spec from disk (compliance.go:86-120)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import yaml

FAIL = "FAIL"
PASS = "PASS"
WARN = "WARN"


@dataclass
class SpecCheck:
    id: str


@dataclass
class Control:
    id: str
    name: str = ""
    description: str = ""
    checks: list[SpecCheck] = field(default_factory=list)
    severity: str = "UNKNOWN"
    default_status: str = ""  # control with no checks: PASS/FAIL verdict


@dataclass
class Spec:
    id: str = ""
    title: str = ""
    description: str = ""
    version: str = ""
    platform: str = ""
    type: str = ""
    related_resources: list[str] = field(default_factory=list)
    controls: list[Control] = field(default_factory=list)


class SpecError(ValueError):
    pass


def scanner_by_check_id(check_id: str) -> str:
    """check-ID prefix → scanner (reference compliance.go:59-73)."""
    low = check_id.lower()
    if low.startswith(("cve-", "dla-", "vuln-")):
        return "vuln"
    if low.startswith("avd-"):
        return "misconfig"
    if low.startswith("secret-"):
        return "secret"
    return "unknown"


@dataclass
class ComplianceSpec:
    spec: Spec

    def scanners(self) -> list[str]:
        out = []
        for control in self.spec.controls:
            for check in control.checks:
                s = scanner_by_check_id(check.id)
                if s == "unknown":
                    raise SpecError(f"unsupported check ID: {check.id}")
                if s not in out:
                    out.append(s)
        return out

    def check_ids(self) -> dict[str, list[str]]:
        out: dict[str, list[str]] = {}
        for control in self.spec.controls:
            for check in control.checks:
                out.setdefault(scanner_by_check_id(check.id), []).append(check.id)
        return out


def _parse_spec(doc: dict) -> ComplianceSpec:
    s = doc.get("spec") or {}
    controls = []
    for c in s.get("controls") or []:
        controls.append(Control(
            id=str(c.get("id", "")),
            name=c.get("name", ""),
            description=c.get("description", ""),
            checks=[SpecCheck(id=str(ch.get("id", "")))
                    for ch in (c.get("checks") or [])],
            severity=c.get("severity", "UNKNOWN"),
            default_status=c.get("defaultStatus", ""),
        ))
    return ComplianceSpec(Spec(
        id=s.get("id", ""),
        title=s.get("title", ""),
        description=s.get("description", ""),
        version=str(s.get("version", "")),
        platform=s.get("platform", ""),
        type=s.get("type", ""),
        related_resources=list(s.get("relatedResources") or []),
        controls=controls,
    ))


def get_compliance_spec(name_or_path: str) -> ComplianceSpec:
    """Builtin spec by name, or `@/path/to/spec.yaml` from disk."""
    if not name_or_path:
        raise SpecError("empty compliance spec name")
    if name_or_path.startswith("@"):
        path = name_or_path[1:]
        with open(path, "rb") as f:
            return _parse_spec(yaml.safe_load(f) or {})
    from trivy_tpu.compliance.builtin import BUILTIN_SPECS

    raw = BUILTIN_SPECS.get(name_or_path)
    if raw is None:
        raise SpecError(
            f"unknown compliance spec {name_or_path!r} "
            f"(builtin: {', '.join(sorted(BUILTIN_SPECS))}; "
            f"use @path for a custom spec)")
    return _parse_spec(yaml.safe_load(raw) or {})


def exists(name_or_path: str) -> bool:
    if name_or_path.startswith("@"):
        return os.path.exists(name_or_path[1:])
    from trivy_tpu.compliance.builtin import BUILTIN_SPECS
    return name_or_path in BUILTIN_SPECS
