"""Compliance report building and writing (reference
pkg/compliance/spec/mapper.go, pkg/compliance/report/{report,json,
table,summary}.go).

Scan results are mapped per check ID (vuln ID, misconfig AVD ID, or
custom severity filter), aggregated per spec control, and rendered as
`all` (full evidence) or `summary` (pass/fail counts per control)."""

from __future__ import annotations

import json
import sys
from dataclasses import dataclass, field

from trivy_tpu.compliance.spec import FAIL, ComplianceSpec
from trivy_tpu.types.report import Result


@dataclass
class ControlCheckResult:
    id: str
    name: str = ""
    description: str = ""
    severity: str = ""
    default_status: str = ""
    results: list[Result] = field(default_factory=list)

    @property
    def total_fail(self) -> int:
        """Failure count for the summary view (reference
        report/summary.go): every finding attached to a control is a
        failure; a check-less control fails iff DefaultStatus=FAIL."""
        if not self.results:
            return 1 if self.default_status == FAIL else 0
        n = 0
        for r in self.results:
            n += len(r.vulnerabilities) + len(r.secrets)
            n += sum(1 for m in r.misconfigurations if m.status != "PASS")
        return n


@dataclass
class ComplianceReport:
    id: str = ""
    title: str = ""
    description: str = ""
    version: str = ""
    related_resources: list[str] = field(default_factory=list)
    results: list[ControlCheckResult] = field(default_factory=list)

    @property
    def empty(self) -> bool:
        return not self.results


def _map_result_to_check_ids(result: Result,
                             check_ids: dict[str, list[str]]) -> dict[str, list[Result]]:
    """One scan Result → {check_id: [filtered Results]}
    (reference spec/mapper.go:10-43 + custom.go)."""
    out: dict[str, list[Result]] = {}
    vuln_ids = set(check_ids.get("vuln", []))
    misconf_ids = set(check_ids.get("misconfig", []))
    secret_ids = set(check_ids.get("secret", []))

    for v in result.vulnerabilities:
        if v.vulnerability_id in vuln_ids:
            out.setdefault(v.vulnerability_id, []).append(Result(
                target=result.target, result_class=result.result_class,
                type=result.type, vulnerabilities=[v]))
    for m in result.misconfigurations:
        if m.avd_id in misconf_ids:
            out.setdefault(m.avd_id, []).append(Result(
                target=result.target, result_class=result.result_class,
                type=result.type, misconfigurations=[m]))

    # custom severity-filter IDs (reference spec/custom.go:12-17)
    for cid in vuln_ids:
        if cid.upper().startswith("VULN-"):
            sev = cid.split("-", 1)[1].upper()
            hits = [v for v in result.vulnerabilities
                    if str(v.severity) == sev]
            if hits:
                out.setdefault(cid, []).append(Result(
                    target=result.target, result_class=result.result_class,
                    type=result.type, vulnerabilities=hits))
    for cid in secret_ids:
        if cid.upper().startswith("SECRET-"):
            sev = cid.split("-", 1)[1].upper()
            hits = [s for s in result.secrets if s.severity == sev]
            if hits:
                out.setdefault(cid, []).append(Result(
                    target=result.target, result_class=result.result_class,
                    type=result.type, secrets=hits))
    return out


def build_compliance_report(results: list[Result],
                            cs: ComplianceSpec) -> ComplianceReport:
    check_ids = cs.check_ids()
    by_check: dict[str, list[Result]] = {}
    for result in results:
        for cid, rs in _map_result_to_check_ids(result, check_ids).items():
            by_check.setdefault(cid, []).extend(rs)

    out = ComplianceReport(
        id=cs.spec.id, title=cs.spec.title, description=cs.spec.description,
        version=cs.spec.version, related_resources=cs.spec.related_resources,
    )
    for control in cs.spec.controls:
        rs: list[Result] = []
        for check in control.checks:
            rs.extend(by_check.get(check.id, []))
        out.results.append(ControlCheckResult(
            id=control.id, name=control.name,
            description=control.description, severity=control.severity,
            default_status=control.default_status, results=rs,
        ))
    return out


# ------------------------------------------------------------- writers


def _report_dict(rep: ComplianceReport) -> dict:
    return {
        "ID": rep.id,
        "Title": rep.title,
        "Description": rep.description,
        "Version": rep.version,
        "RelatedResources": rep.related_resources,
        "Results": [
            {
                "ID": c.id,
                "Name": c.name,
                "Description": c.description,
                **({"DefaultStatus": c.default_status}
                   if c.default_status else {}),
                "Severity": c.severity,
                "Results": [r.to_dict() for r in c.results] or None,
            }
            for c in rep.results
        ],
    }


def _summary_dict(rep: ComplianceReport) -> dict:
    return {
        "SchemaVersion": 2,
        "ID": rep.id,
        "Title": rep.title,
        "SummaryControls": [
            {"ID": c.id, "Name": c.name, "Severity": c.severity,
             "TotalFail": c.total_fail}
            for c in rep.results
        ],
    }


def write_compliance_report(rep: ComplianceReport, fmt: str = "table",
                            report: str = "summary", output=None) -> None:
    """fmt: json|table; report: all|summary
    (reference compliance/report/report.go:66-92)."""
    out = output or sys.stdout
    if fmt == "json":
        doc = _report_dict(rep) if report == "all" else _summary_dict(rep)
        out.write(json.dumps(doc, indent=2, default=str) + "\n")
        return
    if rep.empty:
        return
    # table summary (reference report/table.go + summary.go)
    title = f"Summary Report for compliance: {rep.title}"
    rows = [(c.id, c.severity, c.name, str(c.total_fail))
            for c in rep.results]
    headers = ("ID", "Severity", "Control Name", "Failed")
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    sep = "─" * (sum(widths) + 3 * len(widths) + 1)
    out.write(title + "\n" + sep + "\n")
    out.write(" | ".join(h.ljust(w) for h, w in zip(headers, widths)) + "\n")
    out.write(sep + "\n")
    for r in rows:
        out.write(" | ".join(v.ljust(w) for v, w in zip(r, widths)) + "\n")
    out.write(sep + "\n")
