"""Terraform evaluation: expressions, core functions, locals/variables,
count/for_each expansion, and module calls (reference pkg/iac/terraform +
pkg/iac/scanners/terraform — rebuilt as a compact fixpoint evaluator
instead of the reference's full HCL graph machinery).

The evaluator consumes the Block IR from iac.parsers.hcl. Expressions the
parser kept opaque (`Expr`) are evaluated against a module scope built
from variable defaults + caller inputs, locals, resources, data blocks,
and child-module outputs. Anything unresolvable (computed attributes like
`arn`, providers we don't model, unsupported syntax) evaluates to UNKNOWN
and propagates — a check sees the original opaque Expr rather than a
wrong literal, so evaluation can only add signal, never corrupt it.

Evaluation runs a bounded number of passes over locals/modules until the
scope stops changing (the reference orders a reference graph; a fixpoint
over the small per-module scope reaches the same result without the
graph plumbing).
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field

from trivy_tpu.iac.parsers.hcl import (
    Attribute,
    Block,
    Expr,
    parse_hcl,
    parse_tf_json,
)
from trivy_tpu.log import logger

_log = logger("terraform")

MAX_PASSES = 8
MAX_MODULE_DEPTH = 6
MAX_EXPANSION = 256  # count/for_each clone cap per block


class _Unknown:
    """Unresolvable value; any operation on it stays unknown."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "UNKNOWN"

    def __bool__(self):
        return False


UNKNOWN = _Unknown()


def _is_unknown(v) -> bool:
    return v is UNKNOWN


# ------------------------------------------------------------ functions


def _fn_lookup(m, key, default=UNKNOWN):
    if _is_unknown(m) or not isinstance(m, dict):
        return UNKNOWN
    return m.get(key, default)


def _fn_format(fmt, *args):
    if _is_unknown(fmt) or any(_is_unknown(a) for a in args):
        return UNKNOWN
    out = []
    i = 0
    ai = 0
    s = str(fmt)
    while i < len(s):
        ch = s[i]
        if ch == "%" and i + 1 < len(s):
            spec = s[i + 1]
            if spec == "%":
                out.append("%")
            elif spec in "sdvq":
                a = args[ai] if ai < len(args) else ""
                ai += 1
                if spec == "q":
                    out.append(json.dumps(_to_str(a)))
                elif spec == "d":
                    try:
                        out.append(str(int(a)))
                    except (TypeError, ValueError):
                        return UNKNOWN
                else:
                    out.append(_to_str(a))
            else:
                out.append(ch + spec)
            i += 2
            continue
        out.append(ch)
        i += 1
    return "".join(out)


def _to_str(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return ""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


class TfSet(list):
    """List subclass marking a terraform set value: iteration contexts
    that distinguish sets from lists (dynamic-block for_each exposes
    key == value for sets but key == index for lists/tuples) check for
    this marker. Everywhere else it behaves as the plain list the rest
    of the evaluator expects."""


def _guard(fn):
    """Wrap a function so UNKNOWN arguments yield UNKNOWN."""

    def wrapped(*args):
        if any(_is_unknown(a) for a in args):
            return UNKNOWN
        try:
            return fn(*args)
        except Exception:
            return UNKNOWN

    return wrapped


FUNCTIONS: dict[str, object] = {
    "lower": _guard(lambda s: str(s).lower()),
    "upper": _guard(lambda s: str(s).upper()),
    "title": _guard(lambda s: str(s).title()),
    "trimspace": _guard(lambda s: str(s).strip()),
    "trimprefix": _guard(lambda s, p: str(s).removeprefix(str(p))),
    "trimsuffix": _guard(lambda s, p: str(s).removesuffix(str(p))),
    "trim": _guard(lambda s, cut: str(s).strip(str(cut))),
    "replace": _guard(lambda s, a, b: str(s).replace(str(a), str(b))),
    "split": _guard(lambda sep, s: str(s).split(str(sep))),
    "join": _guard(lambda sep, xs: str(sep).join(_to_str(x) for x in xs)),
    "substr": _guard(lambda s, off, n: str(s)[int(off):]
                     if int(n) < 0 else str(s)[int(off):int(off) + int(n)]),
    "format": _fn_format,
    "length": _guard(len),
    "concat": _guard(lambda *ls: [x for sub in ls for x in sub]),
    "contains": _guard(lambda xs, v: v in xs),
    "element": _guard(lambda xs, i: xs[int(i) % len(xs)]),
    "index": _guard(lambda xs, v: list(xs).index(v)),
    "keys": _guard(lambda m: sorted(m.keys())),
    "values": _guard(lambda m: [m[k] for k in sorted(m.keys())]),
    "lookup": _fn_lookup,
    "merge": _guard(lambda *ms: {k: v for m in ms if isinstance(m, dict)
                                 for k, v in m.items()}),
    "flatten": _guard(lambda xs: _flatten(xs)),
    "distinct": _guard(lambda xs: list(dict.fromkeys(xs))),
    "compact": _guard(lambda xs: [x for x in xs if x not in ("", None)]),
    "coalesce": lambda *xs: next(
        (x for x in xs if not _is_unknown(x) and x not in (None, "")),
        UNKNOWN),
    "coalescelist": lambda *xs: next(
        (x for x in xs if not _is_unknown(x) and x), UNKNOWN),
    "tostring": _guard(_to_str),
    "tonumber": _guard(lambda v: float(v) if "." in str(v) else int(v)),
    "tobool": _guard(lambda v: v if isinstance(v, bool)
                     else str(v).lower() == "true"),
    "tolist": _guard(list),
    "toset": _guard(lambda xs: TfSet(dict.fromkeys(xs))),
    "max": _guard(max),
    "min": _guard(min),
    "abs": _guard(abs),
    "ceil": _guard(lambda v: -(-int(v) // 1) if float(v).is_integer()
                   else int(float(v)) + 1),
    "floor": _guard(lambda v: int(float(v) // 1)),
    "jsonencode": _guard(lambda v: json.dumps(v, separators=(",", ":"))),
    "jsondecode": _guard(lambda s: json.loads(s)),
    "base64encode": _guard(
        lambda s: __import__("base64").b64encode(
            str(s).encode()).decode()),
    "base64decode": _guard(
        lambda s: __import__("base64").b64decode(str(s)).decode()),
    "startswith": _guard(lambda s, p: str(s).startswith(str(p))),
    "endswith": _guard(lambda s, p: str(s).endswith(str(p))),
}


def _flatten(xs):
    out = []
    for x in xs:
        if isinstance(x, list):
            out.extend(_flatten(x))
        else:
            out.append(x)
    return out


# ------------------------------------------------------ expression eval

_EXPR_TOKEN = re.compile(r"""
    (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<number>\d+(?:\.\d+)?)
  | (?P<op>==|!=|<=|>=|&&|\|\||[-+*/%<>!?:()\[\]{},.=])
  | (?P<ident>[A-Za-z_][A-Za-z0-9_-]*)
  | (?P<ws>\s+)
""", re.X)


def _lex(text: str) -> list[tuple[str, str]]:
    toks = []
    pos = 0
    while pos < len(text):
        m = _EXPR_TOKEN.match(text, pos)
        if not m:
            raise ValueError(f"bad token at {text[pos:pos+10]!r}")
        if m.lastgroup != "ws":
            toks.append((m.lastgroup, m.group(0)))
        pos = m.end()
    toks.append(("eof", ""))
    return toks


_BINARY = {
    "||": 1, "&&": 2,
    "==": 3, "!=": 3,
    "<": 4, ">": 4, "<=": 4, ">=": 4,
    "+": 5, "-": 5,
    "*": 6, "/": 6, "%": 6,
}


class _ExprParser:
    def __init__(self, toks, scope: "Scope"):
        self.toks = toks
        self.i = 0
        self.scope = scope

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text):
        t = self.next()
        if t[1] != text:
            raise ValueError(f"expected {text!r}, got {t[1]!r}")

    def parse(self, min_prec=0):
        left = self.parse_unary()
        while True:
            kind, text = self.peek()
            if text == "?" and min_prec == 0:
                self.next()
                then = self.parse()
                self.expect(":")
                other = self.parse()
                cond = left
                if _is_unknown(cond):
                    return UNKNOWN
                return then if _truthy(cond) else other
            prec = _BINARY.get(text)
            if prec is None or prec < min_prec:
                return left
            self.next()
            right = self.parse(prec + 1)
            left = _binop(text, left, right)

    def parse_unary(self):
        kind, text = self.peek()
        if text == "!":
            self.next()
            v = self.parse_unary()
            return UNKNOWN if _is_unknown(v) else not _truthy(v)
        if text == "-":
            self.next()
            v = self.parse_unary()
            try:
                return UNKNOWN if _is_unknown(v) else -v
            except TypeError:
                return UNKNOWN
        return self.parse_postfix()

    def parse_postfix(self):
        v = self.parse_primary()
        while True:
            kind, text = self.peek()
            if text == ".":
                self.next()
                attr = self.next()[1]
                v = _access(v, attr)
            elif text == "[":
                self.next()
                idx = self.parse()
                self.expect("]")
                v = _access(v, idx)
            else:
                return v

    def parse_primary(self):
        kind, text = self.next()
        if kind == "string":
            raw = text[1:-1]
            return _interp(raw, self.scope)
        if kind == "number":
            return float(text) if "." in text else int(text)
        if text == "(":
            v = self.parse()
            self.expect(")")
            return v
        if text == "[":
            items = []
            while self.peek()[1] != "]":
                items.append(self.parse())
                if self.peek()[1] == ",":
                    self.next()
            self.next()
            return UNKNOWN if any(_is_unknown(i) for i in items) else items
        if text == "{":
            obj = {}
            unknown = False
            while self.peek()[1] != "}":
                # naked identifier keys are literal strings in HCL
                if self.peek()[0] == "ident" and \
                        self.toks[self.i + 1][1] in (":", "="):
                    k = self.next()[1]
                else:
                    k = self.parse()
                if self.peek()[1] in (":", "="):
                    self.next()
                val = self.parse()
                if _is_unknown(k):
                    unknown = True
                else:
                    obj[_to_str(k)] = val
                if self.peek()[1] == ",":
                    self.next()
            self.next()
            return UNKNOWN if unknown else obj
        if kind == "ident":
            if text == "true":
                return True
            if text == "false":
                return False
            if text == "null":
                return None
            if self.peek()[1] == "(":
                return self.call(text)
            return self.reference(text)
        raise ValueError(f"unexpected {text!r}")

    def call(self, name):
        self.expect("(")
        args = []
        while self.peek()[1] != ")":
            args.append(self.parse())
            if self.peek()[1] == ",":
                self.next()
        self.next()
        if name == "try":
            return next((a for a in args if not _is_unknown(a)), UNKNOWN)
        if name == "can":
            return UNKNOWN if all(_is_unknown(a) for a in args) else True
        fn = FUNCTIONS.get(name)
        if fn is None:
            return UNKNOWN
        try:
            return fn(*args)
        except Exception:
            return UNKNOWN

    def reference(self, head):
        """Resolve a traversal starting at `head`; postfix handles the
        remaining .attr/[idx] parts, so only the root namespace is read
        here — except multi-part roots (var.x, resource refs) which need
        the following segments."""
        parts = [head]
        while self.peek()[1] == "." and \
                self.toks[self.i + 1][0] == "ident":
            # consume the traversal greedily; _access on the resolved
            # object would lose resource/namespace semantics
            self.next()
            parts.append(self.next()[1])
        v = self.scope.resolve(parts)
        return v


def _truthy(v) -> bool:
    if isinstance(v, str):
        return v == "true"
    return bool(v)


def _binop(op, a, b):
    if _is_unknown(a) or _is_unknown(b):
        return UNKNOWN
    try:
        if op == "||":
            return _truthy(a) or _truthy(b)
        if op == "&&":
            return _truthy(a) and _truthy(b)
        if op == "==":
            return a == b
        if op == "!=":
            return a != b
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return a / b
        if op == "%":
            return a % b
        if op == "<":
            return a < b
        if op == ">":
            return a > b
        if op == "<=":
            return a <= b
        if op == ">=":
            return a >= b
    except TypeError:
        return UNKNOWN
    return UNKNOWN


def _access(v, key):
    if _is_unknown(v) or _is_unknown(key):
        return UNKNOWN
    if isinstance(v, dict):
        return v.get(key, UNKNOWN)
    if isinstance(v, list):
        try:
            return v[int(key)]
        except (ValueError, IndexError, TypeError):
            return UNKNOWN
    if isinstance(v, Block):
        if isinstance(key, (int, float)) and not isinstance(key, bool):
            # res.name[N]: the registry holds the pre-expansion
            # prototype; any instance shares its literal attrs
            return v
        out = v.get(key, UNKNOWN)
        return out
    return UNKNOWN


_INTERP_RX = re.compile(r"\$\{([^{}]*)\}")


def _interp(raw: str, scope: "Scope"):
    """String with ${...} interpolations -> value. A string that is one
    single interpolation returns the inner value unconverted."""
    raw = raw.replace('\\"', '"')
    m = _INTERP_RX.fullmatch(raw)
    if m:
        return eval_expr(m.group(1), scope)
    unknown = False

    def sub(mm):
        nonlocal unknown
        v = eval_expr(mm.group(1), scope)
        if _is_unknown(v):
            unknown = True
            return ""
        return _to_str(v)

    out = _INTERP_RX.sub(sub, raw)
    return UNKNOWN if unknown else out


def eval_expr(text: str, scope: "Scope"):
    """Evaluate one expression string; UNKNOWN when unsupported."""
    try:
        toks = _lex(text)
        p = _ExprParser(toks, scope)
        v = p.parse()
        if p.peek()[0] != "eof":
            return UNKNOWN
        return v
    except Exception:
        return UNKNOWN


# ------------------------------------------------------------- scope


@dataclass
class Scope:
    variables: dict = field(default_factory=dict)
    locals: dict = field(default_factory=dict)
    modules: dict = field(default_factory=dict)  # name -> outputs dict
    resources: dict = field(default_factory=dict)  # "type.name" -> Block
    data: dict = field(default_factory=dict)  # "type.name" -> Block
    each: tuple | None = None  # (key, value)
    count_index: int | None = None
    # dynamic-block iterators in scope: name -> (key, value). The name
    # defaults to the dynamic block's label, overridable via `iterator`
    # (reference: hcl dynblock expansion in pkg/iac/scanners/terraform)
    iterators: dict = field(default_factory=dict)

    def resolve(self, parts: list[str]):
        head = parts[0]
        if head == "var":
            if len(parts) < 2:
                return UNKNOWN
            return _walk(self.variables.get(parts[1], UNKNOWN), parts[2:])
        if head == "local":
            if len(parts) < 2:
                return UNKNOWN
            return _walk(self.locals.get(parts[1], UNKNOWN), parts[2:])
        if head == "module":
            if len(parts) < 3:
                return UNKNOWN
            outs = self.modules.get(parts[1], UNKNOWN)
            return _walk(outs, parts[2:])
        if head == "each":
            if self.each is None or len(parts) < 2:
                return UNKNOWN
            return _walk(self.each[0] if parts[1] == "key"
                         else self.each[1] if parts[1] == "value"
                         else UNKNOWN, parts[2:])
        if head == "count":
            if self.count_index is None or parts[1:2] != ["index"]:
                return UNKNOWN
            return self.count_index
        if head in self.iterators:
            if len(parts) < 2:
                return UNKNOWN
            k, v = self.iterators[head]
            return _walk(k if parts[1] == "key"
                         else v if parts[1] == "value"
                         else UNKNOWN, parts[2:])
        if head == "data":
            if len(parts) < 3:
                return UNKNOWN
            blk = self.data.get(f"{parts[1]}.{parts[2]}")
            return _block_attr(blk, parts[3:], self)
        # resource reference: TYPE.NAME[.attr...]
        if len(parts) >= 2:
            blk = self.resources.get(f"{head}.{parts[1]}")
            return _block_attr(blk, parts[2:], self)
        return UNKNOWN


def _walk(v, rest):
    for r in rest:
        v = _access(v, r)
    return v


def _block_attr(blk, rest, scope):
    if blk is None:
        return UNKNOWN
    if not rest:
        return blk
    v = blk.get(rest[0], UNKNOWN)
    if isinstance(v, Expr):
        v = eval_expr(v.text, scope)
    return _walk(v, rest[1:])


# -------------------------------------------------------- module eval


@dataclass
class EvaluatedModule:
    """Evaluated blocks of one module tree, with per-block source paths."""

    blocks: list[Block]  # resource/data blocks, expanded + evaluated
    outputs: dict


class ModuleLoader:
    """Resolves module `source` directories against an in-memory file
    map {relpath: bytes} (the post-analyzer's virtual FS). Parsed blocks
    are cached per path — module_dirs and every (re-)evaluation share one
    parse per file. Cached blocks are treated as immutable (evaluation
    always copies before mutating)."""

    def __init__(self, files: dict[str, bytes]):
        self.files = files
        self._parsed: dict[str, list[Block]] = {}

    def parse_files(self, files: dict[str, bytes]) -> list[Block]:
        blocks: list[Block] = []
        for path in sorted(files):
            cached = self._parsed.get(path)
            if cached is None:
                cached = _parse_one(path, files[path])
                self._parsed[path] = cached
            blocks.extend(cached)
        return blocks

    def tf_files(self, dirname: str) -> dict[str, bytes]:
        out = {}
        prefix = dirname.rstrip("/") + "/" if dirname not in ("", ".") else ""
        for path, content in self.files.items():
            if not path.startswith(prefix):
                continue
            rel = path[len(prefix):]
            if "/" in rel:
                continue
            if rel.endswith((".tf", ".tf.json")):
                out[path] = content
        return out

    def has_dir(self, dirname: str) -> bool:
        return bool(self.tf_files(dirname))


def _parse_one(path: str, content: bytes) -> list[Block]:
    parse = parse_tf_json if path.endswith(".tf.json") else parse_hcl
    try:
        parsed = parse(content)
    except Exception as exc:
        _log.debug("tf parse failed", path=path, err=str(exc))
        return []
    for b in parsed:
        b.src_path = path
    return parsed


def _eval_value(v, scope: Scope):
    if isinstance(v, Expr):
        out = eval_expr(v.text, scope)
        return v if _is_unknown(out) else out  # keep opaque, never wrong
    if isinstance(v, str) and "${" in v:
        out = _interp(v, scope)
        return v if _is_unknown(out) else out
    if isinstance(v, list):
        return [_eval_value(x, scope) for x in v]
    if isinstance(v, dict):
        return {k: _eval_value(x, scope) for k, x in v.items()}
    return v


def _eval_block(blk: Block, scope: Scope) -> Block:
    out = Block(type=blk.type, labels=list(blk.labels),
                start_line=blk.start_line, end_line=blk.end_line)
    out.src_path = getattr(blk, "src_path", "")
    for name, attr in blk.attrs.items():
        out.attrs[name] = Attribute(name, _eval_value(attr.value, scope),
                                    attr.line)
    kids: list[Block] = []
    for b in blk.blocks:
        if b.type == "dynamic" and len(b.labels) == 1:
            kids.extend(_expand_dynamic(b, scope))
        else:
            kids.append(_eval_block(b, scope))
    out.blocks = kids
    return out


def _expand_dynamic(b: Block, scope: Scope) -> list[Block]:
    """`dynamic "L" { for_each = ...; content { ... } }` -> one block of
    type L per collection element, with the iterator (label or the
    `iterator` attr) resolving .key/.value inside content (reference:
    hcl dynblock expansion used by pkg/iac/scanners/terraform). An
    unresolvable for_each yields ONE instance whose iterator refs stay
    unknown — checks stay silent rather than wrong, matching the
    evaluator's general unresolved-value policy."""
    content = b.child("content")
    if content is None:
        return []
    label = b.labels[0]
    it_attr = b.attrs.get("iterator")
    it_name = label
    if it_attr is not None:
        v = it_attr.value
        # a bare identifier parses as an Expr; its text is the name
        it_name = v if isinstance(v, str) else (
            v.text if isinstance(v, Expr) else label)
    coll = UNKNOWN
    if "for_each" in b.attrs:
        coll = _eval_value(b.attrs["for_each"].value, scope)
    if isinstance(coll, dict):
        items = list(coll.items())
    elif isinstance(coll, TfSet):
        items = [(x, x) for x in coll]  # set: key == value (hcl dynblock)
    elif isinstance(coll, (list, tuple)):
        items = list(enumerate(coll))  # list/tuple: key == index
    else:
        items = None  # unknown
    proto = Block(type=label, labels=[], attrs=content.attrs,
                  blocks=content.blocks, start_line=b.start_line,
                  end_line=b.end_line)
    proto.src_path = getattr(b, "src_path", "")
    if items is None:
        return [_eval_block(proto, scope)]
    out = []
    for k, v in items[:MAX_EXPANSION]:
        s = Scope(**{**scope.__dict__,
                     "iterators": {**scope.iterators, it_name: (k, v)}})
        out.append(_eval_block(proto, s))
    return out


def _expand(blk: Block, scope: Scope) -> list[tuple[Block, Scope]]:
    """count / for_each expansion -> [(clone, scope-with-iterator)]."""
    count_attr = blk.attrs.get("count")
    each_attr = blk.attrs.get("for_each")
    if count_attr is not None:
        n = _eval_value(count_attr.value, scope)
        if isinstance(n, bool) or not isinstance(n, (int, float)):
            return [(blk, scope)]
        n = min(int(n), MAX_EXPANSION)
        out = []
        for i in range(n):
            s = Scope(**{**scope.__dict__, "count_index": i})
            out.append((blk, s))
        return out
    if each_attr is not None:
        coll = _eval_value(each_attr.value, scope)
        items: list[tuple] = []
        if isinstance(coll, dict):
            items = list(coll.items())
        elif isinstance(coll, list):
            items = [(x, x) for x in coll]
        else:
            return [(blk, scope)]
        out = []
        for k, v in items[:MAX_EXPANSION]:
            s = Scope(**{**scope.__dict__, "each": (k, v)})
            out.append((blk, s))
        return out
    return [(blk, scope)]


def evaluate_module(files: dict[str, bytes], dirname: str,
                    loader: ModuleLoader, inputs: dict | None = None,
                    depth: int = 0) -> EvaluatedModule:
    """Evaluate the module rooted at `dirname` (its *.tf files must be in
    `files`), resolving child modules through `loader`."""
    blocks = loader.parse_files(files)
    scope = Scope()

    # variables: caller inputs override defaults
    inputs = inputs or {}
    for b in blocks:
        if b.type == "variable" and b.labels:
            name = b.labels[0]
            if name in inputs:
                scope.variables[name] = inputs[name]
            else:
                d = b.get("default", UNKNOWN)
                scope.variables[name] = (
                    UNKNOWN if isinstance(d, Expr) else d)

    # resource/data registry for references
    for b in blocks:
        if b.type == "resource" and len(b.labels) >= 2:
            scope.resources[f"{b.labels[0]}.{b.labels[1]}"] = b
        elif b.type == "data" and len(b.labels) >= 2:
            scope.data[f"{b.labels[0]}.{b.labels[1]}"] = b

    # fixpoint over locals + module outputs (reference orders the graph;
    # bounded repetition converges for acyclic references). Child modules
    # are keyed by module NAME: when inputs resolve further on a later
    # pass the child is re-evaluated and REPLACES the stale evaluation —
    # accumulating both would duplicate every child resource.
    child_cache: dict[str, tuple[str, EvaluatedModule]] = {}
    for _pass in range(MAX_PASSES):
        changed = False
        for b in blocks:
            if b.type == "locals":
                for name, attr in b.attrs.items():
                    v = _eval_value(attr.value, scope)
                    if not isinstance(v, Expr) and \
                            scope.locals.get(name, UNKNOWN) != v:
                        scope.locals[name] = v
                        changed = True
        if depth < MAX_MODULE_DEPTH:
            for b in blocks:
                if b.type != "module" or not b.labels:
                    continue
                name = b.labels[0]
                src = b.get("source")
                if not isinstance(src, str) or not src.startswith("."):
                    continue  # registry/git modules are not on disk
                mod_dir = os.path.normpath(os.path.join(dirname, src))
                if not loader.has_dir(mod_dir):
                    continue
                mod_inputs = {}
                for k, attr in b.attrs.items():
                    if k in ("source", "version", "count", "for_each",
                             "providers", "depends_on"):
                        continue
                    v = _eval_value(attr.value, scope)
                    mod_inputs[k] = UNKNOWN if isinstance(v, Expr) else v
                inputs_key = json.dumps(
                    {k: repr(v) for k, v in sorted(mod_inputs.items())})
                prev = child_cache.get(name)
                if prev is not None and prev[0] == inputs_key:
                    continue
                child = evaluate_module(
                    loader.tf_files(mod_dir), mod_dir, loader,
                    inputs=mod_inputs, depth=depth + 1)
                child_cache[name] = (inputs_key, child)
                scope.modules[name] = child.outputs
                changed = True
        if not changed:
            break
    child_blocks = []
    for name, (_k, c) in child_cache.items():
        for blk in c.blocks:
            # stamp the module-instance path (fresh per evaluation —
            # c.blocks are this child evaluation's own clones), so two
            # instantiations of one source dir stay distinguishable
            blk.module_id = f"{name}.{blk.module_id}" \
                if blk.module_id else name
            child_blocks.append(blk)

    # outputs
    outputs: dict = {}
    for b in blocks:
        if b.type == "output" and b.labels:
            v = _eval_value(b.attrs["value"].value, scope) \
                if "value" in b.attrs else UNKNOWN
            outputs[b.labels[0]] = UNKNOWN if isinstance(v, Expr) else v

    # expand + evaluate resource/data blocks
    out_blocks: list[Block] = []
    for b in blocks:
        if b.type not in ("resource", "data"):
            continue
        for clone, s in _expand(b, scope):
            out_blocks.append(_eval_block(clone, s))
    out_blocks.extend(child_blocks)
    return EvaluatedModule(blocks=out_blocks, outputs=outputs)


def module_dirs(files: dict[str, bytes],
                loader: ModuleLoader | None = None) -> list[str]:
    """Root terraform module directories in a file map: dirs containing
    *.tf files that are not referenced as a `source` of another dir."""
    dirs = sorted({os.path.dirname(p) for p in files
                   if p.endswith((".tf", ".tf.json"))})
    if loader is None:
        loader = ModuleLoader(files)
    referenced: set[str] = set()
    for d in dirs:
        for b in loader.parse_files(loader.tf_files(d)):
            if b.type == "module":
                src = b.get("source")
                if isinstance(src, str) and src.startswith("."):
                    referenced.add(os.path.normpath(os.path.join(d, src)))
    return [d for d in dirs if d not in referenced]
