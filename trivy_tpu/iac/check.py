"""Check model + registry (reference pkg/iac/rego metadata + rules
registry, pkg/iac/scan.Rule — Rego policies re-expressed as Python
predicates over the parsed IR)."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Cause:
    """One failing location."""

    message: str = ""
    resource: str = ""
    start_line: int = 0
    end_line: int = 0


@dataclass
class Check:
    id: str = ""            # DS002 / KSV001 / AVD-AWS-0086 ...
    avd_id: str = ""
    title: str = ""
    description: str = ""
    resolution: str = ""
    severity: str = "MEDIUM"
    file_types: tuple = ()  # detection types this check applies to
    provider: str = ""      # dockerfile/kubernetes/aws/...
    service: str = ""
    url: str = ""
    namespace: str = "builtin"  # top-level gates evaluation (engine.py)
    deprecated: bool = False
    # fn(ctx) -> list[Cause]; empty list = pass
    fn: object = None

    def run(self, ctx) -> list[Cause]:
        return self.fn(ctx) or []


_REGISTRY: dict[str, Check] = {}


def register(check: Check) -> Check:
    prev = _REGISTRY.get(check.id)
    if prev is not None and prev.fn is not check.fn:
        raise ValueError(
            f"duplicate check id {check.id!r}: already registered "
            f"as {prev.title!r}")
    _REGISTRY[check.id] = check
    return check


def checks_for(file_type: str) -> list[Check]:
    _load_builtins()
    return sorted(
        (c for c in _REGISTRY.values() if file_type in c.file_types),
        key=lambda c: c.id,
    )


def all_checks() -> list[Check]:
    _load_builtins()
    return sorted(_REGISTRY.values(), key=lambda c: c.id)


_loaded = False
_load_lock = __import__("threading").Lock()


def _load_builtins():
    global _loaded
    if _loaded:
        return
    with _load_lock:  # parallel scan workers race the first load
        if _loaded:
            return
        from trivy_tpu.iac.checks import (  # noqa: F401
            aws_ext,
            azure,
            azure_ext,
            cloud,
            docker,
            gcp,
            gcp_ext,
            kubernetes,
            providers_misc,
        )
        _loaded = True


def check(id: str, title: str, *, severity="MEDIUM", file_types=(),
          avd_id="", description="", resolution="", provider="",
          service="", url=""):
    """Decorator: @check("DS002", "...") def f(ctx) -> list[Cause]."""

    def wrap(fn):
        register(Check(
            id=id, avd_id=avd_id or id, title=title,
            description=description or title, resolution=resolution,
            severity=severity, file_types=tuple(file_types),
            provider=provider, service=service,
            url=url or f"https://avd.aquasec.com/misconfig/{id.lower()}",
            fn=fn,
        ))
        return fn

    return wrap
