"""Azure ARM template expression evaluator.

The reference resolves `[...]` expressions in ARM templates before
scanning: an expression tree (pkg/iac/scanners/azure/expressions/
{lex,node}.go) is evaluated against the deployment's parameters and
variables with ~100 template functions (pkg/iac/scanners/azure/
functions/*.go, resolver/resolver.go). Without this, a template that
routes `supportsHttpsTrafficOnly` through `[parameters('x')]` scans as
an opaque string and every azure check stays silent.

This module is the tpu-repo equivalent: parse the expression grammar
(single-quoted strings with '' escapes, nested calls, `.prop` and
`[idx]` access), evaluate against a Deployment (parameter values /
defaultValues, lazily-resolved variables, copyIndex context), expand
resource `copy` loops, drop `condition: false` resources, and flatten
nested Microsoft.Resources/deployments (azure/arm/parser, deployment.go).

Unresolvable expressions (unknown functions like reference()/list*(),
parameters with no value or defaultValue) resolve to None — the
adapters' "unknown" marker — matching the reference's KindUnresolvable
semantics (resolver.go:36-40): checks stay silent rather than
false-positive on a value the scanner cannot know.
"""

from __future__ import annotations

import copy as _copy
import hashlib
import json
import re


class ArmError(Exception):
    pass


class _UnresolvedType:
    __slots__ = ()

    def __repr__(self):
        return "UNRESOLVED"

    def __bool__(self):
        return False


UNRESOLVED = _UnresolvedType()

_MAX_DEPLOYMENT_DEPTH = 8


# ------------------------------------------------------------ expression


def is_expression(v) -> bool:
    """ARM: a string wrapped in [ ] is an expression; `[[` escapes a
    literal bracket (azure/arm/parser/template.go)."""
    return (isinstance(v, str) and v.startswith("[") and v.endswith("]")
            and not v.startswith("[["))


_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<num>-?\d+(\.\d+)?)
  | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<punct>[(),.\[\]])
""", re.X)


def _lex(code: str) -> list[tuple[str, object]]:
    toks: list[tuple[str, object]] = []
    i, n = 0, len(code)
    while i < n:
        c = code[i]
        if c == "'":
            j, out = i + 1, []
            while j < n:
                if code[j] == "'":
                    if j + 1 < n and code[j + 1] == "'":   # '' escape
                        out.append("'")
                        j += 2
                        continue
                    break
                out.append(code[j])
                j += 1
            if j >= n:
                raise ArmError(f"unterminated string in {code!r}")
            toks.append(("str", "".join(out)))
            i = j + 1
            continue
        m = _TOKEN_RE.match(code, i)
        if not m:
            raise ArmError(f"bad character {c!r} in {code!r}")
        if m.lastgroup == "num":
            text = m.group("num")
            toks.append(("num", float(text) if "." in text
                         else int(text)))
        elif m.lastgroup == "name":
            toks.append(("name", m.group("name")))
        elif m.lastgroup == "punct":
            toks.append(("punct", m.group("punct")))
        i = m.end()
    toks.append(("eof", ""))
    return toks


class _ExprParser:
    """expr := (call | literal) postfix*; call := name '(' args ')';
    postfix := '.' name | '[' expr ']' (expressions/node.go shapes)."""

    def __init__(self, toks):
        self.toks = toks
        self.i = 0

    def _peek(self):
        return self.toks[self.i]

    def _next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def parse(self):
        e = self._expr()
        if self._peek()[0] != "eof":
            raise ArmError(f"trailing tokens at {self._peek()!r}")
        return e

    def _expr(self):
        kind, val = self._peek()
        if kind in ("str", "num"):
            self._next()
            node = ("lit", val)
        elif kind == "name":
            self._next()
            if self._peek() == ("punct", "("):
                self._next()
                args = []
                if self._peek() != ("punct", ")"):
                    while True:
                        args.append(self._expr())
                        if self._peek() == ("punct", ","):
                            self._next()
                            continue
                        break
                if self._next() != ("punct", ")"):
                    raise ArmError(f"expected ) in call {val}")
                node = ("call", val, args)
            else:
                # bare name: ARM only allows function calls; treat a
                # bare identifier as unresolvable
                node = ("lit", UNRESOLVED)
        else:
            raise ArmError(f"unexpected token {self._peek()!r}")
        while True:
            if self._peek() == ("punct", "."):
                self._next()
                k, v = self._next()
                if k != "name":
                    raise ArmError("expected property name after .")
                node = ("dot", node, v)
            elif self._peek() == ("punct", "["):
                self._next()
                idx = self._expr()
                if self._next() != ("punct", "]"):
                    raise ArmError("expected ] after index")
                node = ("idx", node, idx)
            else:
                return node


def parse_expression(code: str):
    return _ExprParser(_lex(code)).parse()


# ------------------------------------------------------------ deployment


class Deployment:
    """Resolution context: parameter values (supplied > defaultValue),
    lazily-memoized variables, copy-loop indices."""

    def __init__(self, template: dict, parameter_values: dict | None =
                 None):
        self.template = template or {}
        self._param_defs = self.template.get("parameters") or {}
        self._param_values = dict(parameter_values or {})
        self._var_defs = self.template.get("variables") or {}
        self._var_memo: dict = {}
        self._resolving: set = set()
        self.copy_indices: dict[str, int] = {}

    def parameter(self, name):
        key = "p:" + name
        if key in self._resolving:      # parameter cycle
            return UNRESOLVED
        self._resolving.add(key)
        try:
            if name in self._param_values:
                return resolve_value(self._param_values[name], self)
            d = self._param_defs.get(name)
            if isinstance(d, dict) and "defaultValue" in d:
                return resolve_value(d["defaultValue"], self)
            return UNRESOLVED
        finally:
            self._resolving.discard(key)

    def variable(self, name):
        if name in self._var_memo:
            return self._var_memo[name]
        if name not in self._var_defs:
            return UNRESOLVED
        if "v:" + name in self._resolving:      # variable cycle
            return UNRESOLVED
        self._resolving.add("v:" + name)
        try:
            v = resolve_value(self._var_defs[name], self)
        finally:
            self._resolving.discard("v:" + name)
        self._var_memo[name] = v
        return v

    def copy_index(self, name: str | None, offset: int = 0):
        if name is None:
            if len(self.copy_indices) != 1:
                cur = self.copy_indices.get("")
                if cur is None:
                    return UNRESOLVED
                return cur + offset
            return next(iter(self.copy_indices.values())) + offset
        idx = self.copy_indices.get(name)
        return UNRESOLVED if idx is None else idx + offset


# ------------------------------------------------------------- functions


def _want_str(args):
    return all(isinstance(a, str) for a in args)


def _concat(*args):
    if args and all(isinstance(a, list) for a in args):
        out = []
        for a in args:
            out.extend(a)
        return out
    return "".join(_to_str(a) for a in args)


def _to_str(v):
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    if isinstance(v, (dict, list)):
        return json.dumps(v, separators=(",", ":"))
    return str(v)


def _format(fmt, *args):
    if not isinstance(fmt, str):
        return UNRESOLVED
    out = fmt
    for i, a in enumerate(args):
        out = out.replace("{%d}" % i, _to_str(a))
    return out


def _equals(a, b):
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    return a == b


def _empty(x):
    if x is None:
        return True
    if isinstance(x, (str, list, dict)):
        return len(x) == 0
    return False


def _contains(coll, item):
    if isinstance(coll, str):
        return _to_str(item) in coll
    if isinstance(coll, list):
        return item in coll
    if isinstance(coll, dict):
        return item in coll
    return False


def _split(s, d):
    if not isinstance(s, str):
        return UNRESOLVED
    if isinstance(d, list):
        if not d:
            return [s]
        for other in d[1:]:     # split once on ANY delimiter
            s = s.replace(other, d[0])
        return s.split(d[0])
    return s.split(d)


def _length(x):
    if isinstance(x, (str, list, dict)):
        return len(x)
    return UNRESOLVED


def _unique_string(*args):
    # deterministic 13-hex-char digest of the joined inputs
    # (functions/unique_string.go)
    joined = "".join(_to_str(a) for a in args)
    return hashlib.sha256(joined.encode()).hexdigest()[:13]


def _guid(*args):
    h = hashlib.sha256("-".join(_to_str(a) for a in args).encode())
    d = h.hexdigest()
    return f"{d[0:8]}-{d[8:12]}-{d[12:16]}-{d[16:20]}-{d[20:32]}"


def _resource_id(*args):
    # reference joins every arg with "/" (functions/resource.go:7-20)
    if len(args) < 2:
        return UNRESOLVED
    return "".join("/" + _to_str(a) for a in args)


def _resource_group():
    return {
        "id": "/subscriptions/00000000-0000-0000-0000-000000000000"
              "/resourceGroups/PlaceHolderResourceGroup",
        "name": "Placeholder Resource Group",
        "type": "Microsoft.Resources/resourceGroups",
        "location": "westus",
        "tags": {},
        "properties": {"provisioningState": "Succeeded"},
    }


def _subscription():
    return {
        "id": "/subscriptions/00000000-0000-0000-0000-000000000000",
        "subscriptionId": "00000000-0000-0000-0000-000000000000",
        "tenantId": "00000000-0000-0000-0000-000000000000",
        "displayName": "Placeholder Subscription",
    }


def _coalesce(*xs):
    """Left-to-right: first definite non-null wins; UNRESOLVED only
    when an unknown is hit before any definite value."""
    for x in xs:
        if x is UNRESOLVED:
            return UNRESOLVED
        if x is not None:
            return x
    return None


def _int2(f):
    def g(*args):
        nums = []
        for a in args:
            if isinstance(a, bool) or not isinstance(a, (int, float)):
                return UNRESOLVED
            nums.append(a)
        try:
            return f(*nums)
        except ZeroDivisionError:
            return UNRESOLVED
    return g


def _union(*args):
    if all(isinstance(a, dict) for a in args):
        out: dict = {}
        for a in args:
            out.update(a)
        return out
    if all(isinstance(a, list) for a in args):
        out_l: list = []
        for a in args:
            for x in a:
                if x not in out_l:
                    out_l.append(x)
        return out_l
    return UNRESOLVED


def _intersection(*args):
    if all(isinstance(a, list) for a in args) and args:
        out = [x for x in args[0] if all(x in a for a in args[1:])]
        return out
    if all(isinstance(a, dict) for a in args) and args:
        keys = set(args[0])
        for a in args[1:]:
            keys &= set(a)
        return {k: args[0][k] for k in args[0] if k in keys}
    return UNRESOLVED


def _items(obj):
    if not isinstance(obj, dict):
        return UNRESOLVED
    return [{"key": k, "value": obj[k]} for k in sorted(obj)]


def _to_int(x):
    try:
        return int(x)
    except (TypeError, ValueError):
        return UNRESOLVED


def _to_bool(x):
    if isinstance(x, bool):
        return x
    if isinstance(x, str):
        return x.lower() == "true"
    if isinstance(x, (int, float)):
        return x != 0
    return UNRESOLVED


# name -> (fn, needs_deployment)
_FUNCS: dict = {
    "concat": _concat,
    "format": _format,
    "toLower": lambda s: s.lower() if isinstance(s, str) else UNRESOLVED,
    "toUpper": lambda s: s.upper() if isinstance(s, str) else UNRESOLVED,
    "replace": lambda s, a, b: s.replace(a, b) if _want_str((s, a, b))
    else UNRESOLVED,
    "trim": lambda s: s.strip() if isinstance(s, str) else UNRESOLVED,
    "substring": lambda s, off, ln=None: (
        s[off:] if ln is None else s[off:off + ln]) if isinstance(
            s, str) else UNRESOLVED,
    "split": _split,
    "join": lambda arr, d: d.join(_to_str(x) for x in arr)
    if isinstance(arr, list) else UNRESOLVED,
    "startsWith": lambda s, p: s.startswith(p) if _want_str((s, p))
    else UNRESOLVED,
    "endsWith": lambda s, p: s.endswith(p) if _want_str((s, p))
    else UNRESOLVED,
    "indexOf": lambda s, x: s.find(x) if _want_str((s, x))
    else UNRESOLVED,
    "lastIndexOf": lambda s, x: s.rfind(x) if _want_str((s, x))
    else UNRESOLVED,
    "padLeft": lambda s, w, c=" ": _to_str(s).rjust(w, c),
    "string": _to_str,
    "int": _to_int,
    "float": lambda x: float(x) if not isinstance(x, (dict, list))
    else UNRESOLVED,
    "bool": _to_bool,
    "length": _length,
    "empty": _empty,
    "contains": _contains,
    "equals": _equals,
    "not": lambda b: (not b) if isinstance(b, bool) else UNRESOLVED,
    "and": lambda *bs: (False if any(b is False for b in bs) else
                        UNRESOLVED if any(b is UNRESOLVED for b in bs)
                        else all(b is True for b in bs)),
    "or": lambda *bs: (True if any(b is True for b in bs) else
                       UNRESOLVED if any(b is UNRESOLVED for b in bs)
                       else False),
    "if": lambda c, t, f: (UNRESOLVED if c is UNRESOLVED else
                           t if c is True else f),
    "coalesce": _coalesce,
    "add": _int2(lambda a, b: a + b),
    "sub": _int2(lambda a, b: a - b),
    "mul": _int2(lambda a, b: a * b),
    "div": _int2(lambda a, b: a // b if isinstance(a, int) and
                 isinstance(b, int) else a / b),
    "mod": _int2(lambda a, b: a % b),
    "min": _int2(min),
    "max": _int2(max),
    "range": lambda start, count: list(range(start, start + count))
    if isinstance(start, int) and isinstance(count, int)
    else UNRESOLVED,
    "array": lambda x: x if isinstance(x, list) else [x],
    "createArray": lambda *xs: list(xs),
    "createObject": lambda *xs: {xs[i]: xs[i + 1]
                                 for i in range(0, len(xs) - 1, 2)},
    "items": _items,
    "first": lambda x: (x[0] if x else UNRESOLVED) if isinstance(
        x, (list, str)) else UNRESOLVED,
    "last": lambda x: (x[-1] if x else UNRESOLVED) if isinstance(
        x, (list, str)) else UNRESOLVED,
    "take": lambda x, n: x[:n] if isinstance(x, (list, str))
    else UNRESOLVED,
    "skip": lambda x, n: x[n:] if isinstance(x, (list, str))
    else UNRESOLVED,
    "union": _union,
    "intersection": _intersection,
    "uniqueString": _unique_string,
    "guid": _guid,
    "base64": lambda s: __import__("base64").b64encode(
        s.encode()).decode() if isinstance(s, str) else UNRESOLVED,
    "base64ToString": lambda s: __import__("base64").b64decode(
        s).decode() if isinstance(s, str) else UNRESOLVED,
    "base64ToJson": lambda s: json.loads(__import__(
        "base64").b64decode(s)) if isinstance(s, str) else UNRESOLVED,
    "dataUri": lambda s: "data:text/plain;charset=utf8;base64," +
    __import__("base64").b64encode(_to_str(s).encode()).decode(),
    "json": lambda s: json.loads(s) if isinstance(s, str)
    else UNRESOLVED,
    "true": lambda: True,
    "false": lambda: False,
    "null": lambda: None,
    "resourceId": _resource_id,
    "subscriptionResourceId": _resource_id,
    "tenantResourceId": _resource_id,
    "extensionResourceId": _resource_id,
    "resourceGroup": _resource_group,
    "subscription": _subscription,
    "tenant": lambda: {"tenantId":
                       "00000000-0000-0000-0000-000000000000"},
    "deployment": lambda: {"name": "placeholder-deployment",
                           "properties": {}},
    "environment": lambda *a: UNRESOLVED,
    "managementGroup": lambda *a: UNRESOLVED,
    # runtime-only: cannot be known at scan time
    "reference": lambda *a: UNRESOLVED,
    "list": lambda *a: UNRESOLVED,
    "listKeys": lambda *a: UNRESOLVED,
    "listSecrets": lambda *a: UNRESOLVED,
    "newGuid": lambda *a: UNRESOLVED,
    "utcNow": lambda *a: UNRESOLVED,
    "pickZones": lambda *a: UNRESOLVED,
}

_DEPLOYMENT_FUNCS = {"parameters", "variables", "copyIndex"}


def _eval(node, dep: Deployment):
    kind = node[0]
    if kind == "lit":
        return node[1]
    if kind == "dot":
        base = _eval(node[1], dep)
        if isinstance(base, dict) and node[2] in base:
            return resolve_value(base[node[2]], dep)
        return UNRESOLVED
    if kind == "idx":
        base = _eval(node[1], dep)
        idx = _eval(node[2], dep)
        if isinstance(base, list) and isinstance(idx, int) and not \
                isinstance(idx, bool) and 0 <= idx < len(base):
            return resolve_value(base[idx], dep)
        if isinstance(base, dict) and isinstance(idx, str) and \
                idx in base:
            return resolve_value(base[idx], dep)
        return UNRESOLVED
    # call
    name, arg_nodes = node[1], node[2]
    args = [_eval(a, dep) for a in arg_nodes]
    if name == "parameters":
        return dep.parameter(args[0]) if args and isinstance(
            args[0], str) else UNRESOLVED
    if name == "variables":
        return dep.variable(args[0]) if args and isinstance(
            args[0], str) else UNRESOLVED
    if name == "copyIndex":
        if not args:
            return dep.copy_index(None)
        if isinstance(args[0], str):
            return dep.copy_index(args[0], args[1] if len(args) > 1
                                  else 0)
        return dep.copy_index(None, args[0] if isinstance(args[0], int)
                              else 0)
    fn = _FUNCS.get(name)
    if fn is None:
        return UNRESOLVED
    if name not in ("if", "coalesce", "and", "or") and any(
            a is UNRESOLVED for a in args):
        return UNRESOLVED
    try:
        return fn(*args)
    except Exception:
        return UNRESOLVED


def evaluate_expression(code: str, dep: Deployment):
    """Evaluate the inside of one `[...]` expression string."""
    try:
        return _eval(parse_expression(code), dep)
    except ArmError:
        return UNRESOLVED


def resolve_value(v, dep: Deployment):
    """Recursively resolve a template value: expression strings
    evaluate, `[[` unescapes, containers recurse."""
    if is_expression(v):
        # evaluate exactly once: parameters()/variables()/property
        # access resolve their own raw template subtrees, so the result
        # is final — a computed "[x]" string must NOT be re-parsed
        return evaluate_expression(v[1:-1], dep)
    if isinstance(v, str) and v.startswith("[["):
        return v[1:]
    if isinstance(v, dict):
        return {k: resolve_value(x, dep) for k, x in v.items()}
    if isinstance(v, list):
        return [resolve_value(x, dep) for x in v]
    return v


# --------------------------------------------------------------- template


def _strip_unresolved(v):
    if v is UNRESOLVED:
        return None
    if isinstance(v, dict):
        return {k: _strip_unresolved(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_strip_unresolved(x) for x in v]
    return v


def _expand_resource(res: dict, dep: Deployment, depth: int) -> list:
    """One raw resource -> resolved resource(s): copy loops expand,
    false conditions drop, nested deployments flatten."""
    copy_spec = res.get("copy")
    if isinstance(copy_spec, dict):
        name = str(copy_spec.get("name", ""))
        count = resolve_value(copy_spec.get("count", 1), dep)
        if not isinstance(count, int) or isinstance(count, bool) or \
                count < 0:
            count = 1
        out = []
        body = {k: v for k, v in res.items() if k != "copy"}
        for i in range(min(count, 256)):
            dep.copy_indices[name] = i
            dep.copy_indices[""] = i
            out.extend(_expand_resource(body, dep, depth))
        dep.copy_indices.pop(name, None)
        dep.copy_indices.pop("", None)
        return out

    if "condition" in res:
        cond = resolve_value(res["condition"], dep)
        if cond is False:
            return []

    rtype = res.get("type")
    if rtype == "Microsoft.Resources/deployments" and \
            depth < _MAX_DEPLOYMENT_DEPTH:
        props = res.get("properties") or {}
        inner = props.get("template")
        if isinstance(inner, dict):
            raw_params = resolve_value(props.get("parameters") or {},
                                       dep)
            inner_values = {
                k: v.get("value") for k, v in raw_params.items()
                if isinstance(v, dict)
            } if isinstance(raw_params, dict) else {}
            return _evaluate_resources(inner, inner_values, depth + 1)

    return [resolve_value(res, dep)]


def _evaluate_resources(template: dict, parameter_values: dict | None,
                        depth: int) -> list:
    dep = Deployment(template, parameter_values)
    out = []
    for res in template.get("resources") or []:
        if isinstance(res, dict):
            out.extend(_expand_resource(res, dep, depth))
    return out


def evaluate_template(doc: dict,
                      parameter_values: dict | None = None) -> dict:
    """Resolve every expression in an ARM template document. Returns a
    new document whose `resources` are fully resolved (copy loops
    expanded, nested deployments hoisted inline, unresolvable values
    as None)."""
    doc = _copy.deepcopy(doc) if doc else {}
    resources = _evaluate_resources(doc, parameter_values, 0)
    doc["resources"] = [_strip_unresolved(r) for r in resources]
    return doc
