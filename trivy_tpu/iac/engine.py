"""User-extensible check engine — the functional equivalent of the
reference's OPA/Rego scanner (pkg/iac/rego/scanner.go:92-314, load.go):
checks are *data*, loaded at scan time from the embedded builtin bundle
plus user-supplied paths (--config-check), gated by namespaces
(--check-namespaces), with optional data documents (--config-data).

Two user check formats (instead of Rego modules):

1. Python check module (``*.py``)::

       __check__ = {
           "id": "USR-001", "title": "...", "severity": "HIGH",
           "type": "kubernetes",          # or "selector": [..types..]
           "namespace": "user.something", # default "user"
       }

       def deny(input, data=None):
           # return [] to pass, or messages / dicts to fail
           return [{"message": "...", "resource": "...",
                    "start_line": 1, "end_line": 2}]

2. Declarative YAML check (``*.yaml``/``*.yml``) — a small condition
   DSL over the same input document::

       id: USR-002
       title: hostNetwork must not be used
       severity: HIGH
       type: kubernetes
       deny:
         - path: spec.hostNetwork
           equals: true
           message: hostNetwork is enabled

   Conditions support dotted paths with ``[*]`` list wildcards and the
   operators equals / not_equals / exists / contains / regex / in /
   gt / gte / lt / lte / starts_with / ends_with, composable with
   ``all:`` / ``any:`` lists.

The *input document* mirrors the reference's Rego ``input`` per source
type (dockerfile: Stages/Commands; kubernetes: the resource document;
terraform/cloudformation/arm: a canonical Resources list) — see
``input_doc``.
"""

from __future__ import annotations

import importlib.util
import os
import re
import threading

from trivy_tpu.analysis.witness import make_lock

import yaml

from trivy_tpu.iac.check import Cause, Check
from trivy_tpu.log import logger

_log = logger("checkengine")

# reference pkg/iac/rego/load.go:18 — namespaces always evaluated
BUILTIN_NAMESPACES = frozenset({"builtin", "defsec", "appshield"})

_SOURCE_TYPES = frozenset({
    "dockerfile", "kubernetes", "terraform", "cloudformation",
    "terraformplan", "azure-arm", "helm", "yaml", "json", "cloud",
})


class CheckLoadError(Exception):
    pass


# --------------------------------------------------------------- inputs


def input_doc(ctx) -> dict:
    """Uniform JSON-like document a check's conditions/deny() run over,
    per source type (the Rego ``input`` equivalent)."""
    kind = type(ctx).__name__
    if kind == "DockerfileCtx":
        df = ctx.dockerfile
        return {
            "Stages": [
                {
                    "Name": st.name or st.base,
                    "Base": st.base,
                    "StartLine": st.start_line,
                    "Commands": [
                        _dockerfile_command(i, idx, ctx.path)
                        for i in st.instructions
                    ],
                }
                for idx, st in enumerate(df.stages)
            ],
        }
    if kind == "K8sCtx":
        return ctx.resource
    if kind == "CloudCtx":
        return {
            "Resources": [
                {
                    "Type": r.type,
                    "Name": r.name,
                    "Values": r.attrs,
                    "StartLine": r.start_line,
                    "EndLine": r.end_line,
                }
                for r in ctx.cloud_resources
            ],
        }
    return {}


def _dockerfile_command(i, stage_idx: int, path: str) -> dict:
    """One Command in the reference's Rego input schema
    (pkg/iac/providers/dockerfile/dockerfile.go:30-44 — Value is
    []string: exec-form args split, shell-form run/cmd/entrypoint kept
    as one string, other instructions whitespace-tokenized)."""
    cmd = i.cmd.lower()
    value_src = i.value
    sub = ""
    if cmd in ("healthcheck", "onbuild"):
        head, _, rest = i.value.strip().partition(" ")
        if head and head.upper() in (
                "CMD", "NONE", "RUN", "COPY", "ADD", "ENTRYPOINT"):
            sub, value_src = head.lower(), rest
    arr = i.json_array() if value_src is i.value else None
    if arr is None and value_src.strip().startswith("["):
        import json as _json

        try:
            parsed = _json.loads(value_src.strip())
            arr = [str(a) for a in parsed] if isinstance(parsed, list) \
                else None
        except ValueError:
            arr = None
    if arr is not None:
        value, is_json = arr, True
    elif cmd in ("run", "cmd", "entrypoint") or sub:
        value, is_json = ([value_src] if value_src else []), False
    else:
        value, is_json = value_src.split(), False
    return {
        "Cmd": cmd,
        "SubCmd": sub,
        "Value": value,
        "JSON": is_json,
        "Original": " ".join(
            [i.cmd] + list(i.flags) + ([i.value] if i.value else [])),
        "Flags": list(i.flags),
        "Stage": stage_idx,
        "Path": path,
        "StartLine": i.start_line,
        "EndLine": i.end_line,
    }


# ----------------------------------------------------------- path walk


def resolve_path(doc, path: str) -> list:
    """Resolve a dotted path against a nested dict/list document.
    ``[*]`` fans out over list elements; ``[N]`` indexes. Returns every
    value the path reaches (possibly empty)."""
    parts = [p for p in path.split(".") if p]
    current = [doc]
    for part in parts:
        m = re.match(r"^([^\[\]]*)((?:\[[^\]]*\])*)$", part)
        if not m:
            return []
        key, idxs = m.group(1), re.findall(r"\[([^\]]*)\]", m.group(2))
        nxt = []
        for node in current:
            vals = [node]
            if key:
                vals = [node[key]] if isinstance(node, dict) and key in node \
                    else []
            for ix in idxs:
                fanned = []
                for v in vals:
                    if not isinstance(v, list):
                        continue
                    if ix == "*":
                        fanned.extend(v)
                    else:
                        try:
                            fanned.append(v[int(ix)])
                        except (ValueError, IndexError):
                            pass
                vals = fanned
            nxt.extend(vals)
        current = nxt
        if not current:
            return []
    return current


# ------------------------------------------------------------- YAML DSL


_OPS = {
    "equals": lambda v, arg: v == arg,
    "not_equals": lambda v, arg: v != arg,
    "contains": lambda v, arg: (arg in v) if isinstance(
        v, (str, list, dict)) else False,
    "regex": lambda v, arg: isinstance(v, str)
    and re.search(arg, v) is not None,
    "in": lambda v, arg: v in (arg or []),
    "gt": lambda v, arg: _num(v) is not None and _num(v) > arg,
    "gte": lambda v, arg: _num(v) is not None and _num(v) >= arg,
    "lt": lambda v, arg: _num(v) is not None and _num(v) < arg,
    "lte": lambda v, arg: _num(v) is not None and _num(v) <= arg,
    "starts_with": lambda v, arg: isinstance(v, str) and v.startswith(arg),
    "ends_with": lambda v, arg: isinstance(v, str) and v.endswith(arg),
}


def _num(v):
    if isinstance(v, bool):
        return None
    if isinstance(v, (int, float)):
        return v
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def _eval_condition(cond: dict, doc) -> bool:
    if "all" in cond:
        return all(_eval_condition(c, doc) for c in cond["all"])
    if "any" in cond:
        return any(_eval_condition(c, doc) for c in cond["any"])
    if "not" in cond:
        return not _eval_condition(cond["not"], doc)
    path = cond.get("path", "")
    values = resolve_path(doc, path)
    if "exists" in cond:
        return bool(values) == bool(cond["exists"])
    for op, fn in _OPS.items():
        if op in cond:
            return any(fn(v, cond[op]) for v in values)
    raise CheckLoadError(f"condition has no operator: {cond!r}")


def _dsl_fn(spec: dict):
    deny = spec.get("deny") or []
    if not isinstance(deny, list):
        raise CheckLoadError("deny: must be a list of conditions")
    for cond in deny:
        _validate_condition(cond)

    def fn(ctx) -> list[Cause]:
        doc = input_doc(ctx)
        causes: list[Cause] = []
        for cond in deny:
            if _eval_condition(cond, doc):
                causes.append(Cause(
                    message=cond.get("message", spec.get("title", "")),
                    resource=_doc_resource(doc),
                ))
        return causes

    return fn


def _validate_condition(cond) -> None:
    if not isinstance(cond, dict):
        raise CheckLoadError(f"condition must be a mapping: {cond!r}")
    for junction in ("all", "any"):
        if junction in cond:
            for sub in cond[junction]:
                _validate_condition(sub)
            return
    if "not" in cond:
        _validate_condition(cond["not"])
        return
    if "exists" in cond:
        return
    if not any(op in cond for op in _OPS):
        raise CheckLoadError(f"condition has no operator: {cond!r}")


def _doc_resource(doc) -> str:
    if isinstance(doc, dict):
        md = doc.get("metadata")
        if isinstance(md, dict) and md.get("name"):
            return str(md["name"])
    return ""


# --------------------------------------------------------------- loaders


def _selectors(meta: dict) -> tuple:
    sel = meta.get("selector") or meta.get("type") or ()
    if isinstance(sel, str):
        sel = (sel,)
    sel = tuple(sel)
    bad = [s for s in sel if s not in _SOURCE_TYPES]
    if bad:
        raise CheckLoadError(f"unknown source type(s) {bad}")
    # "cloud" fans out to every cloud-IR format
    if "cloud" in sel:
        sel = tuple(s for s in sel if s != "cloud") + (
            "terraform", "cloudformation", "terraformplan", "azure-arm")
    return sel


def _mk_check(meta: dict, fn, origin: str) -> Check:
    cid = meta.get("id")
    if not cid:
        raise CheckLoadError(f"{origin}: check has no id")
    if not meta.get("title"):
        raise CheckLoadError(f"{origin}: check {cid} has no title")
    sel = _selectors(meta)
    if not sel:
        raise CheckLoadError(
            f"{origin}: check {cid} declares no type/selector")
    sev = str(meta.get("severity", "MEDIUM")).upper()
    if sev not in ("CRITICAL", "HIGH", "MEDIUM", "LOW", "UNKNOWN"):
        raise CheckLoadError(f"{origin}: bad severity {sev!r}")
    return Check(
        id=cid, avd_id=meta.get("avd_id", cid), title=meta["title"],
        description=meta.get("description", meta["title"]),
        resolution=meta.get("resolution", ""), severity=sev,
        file_types=sel, provider=meta.get("provider", "user"),
        service=meta.get("service", ""), url=meta.get("url", ""),
        namespace=meta.get("namespace", "user"),
        deprecated=bool(meta.get("deprecated", False)),
        fn=fn,
    )


def load_python_check(path: str, data: dict | None = None) -> list[Check]:
    name = "trivy_tpu_user_check_" + re.sub(
        r"\W", "_", os.path.abspath(path))
    spec = importlib.util.spec_from_file_location(name, path)
    if spec is None or spec.loader is None:
        raise CheckLoadError(f"cannot load {path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    meta = getattr(mod, "__check__", None)
    if not isinstance(meta, dict):
        raise CheckLoadError(f"{path}: missing __check__ metadata dict")
    deny = getattr(mod, "deny", None)
    if not callable(deny):
        raise CheckLoadError(f"{path}: missing deny(input) function")

    import inspect

    wants_data = "data" in inspect.signature(deny).parameters

    def fn(ctx) -> list[Cause]:
        doc = input_doc(ctx)
        raw = deny(doc, data=data) if wants_data else deny(doc)
        causes = []
        for r in raw or []:
            if isinstance(r, Cause):
                causes.append(r)
            elif isinstance(r, dict):
                causes.append(Cause(
                    message=r.get("message", ""),
                    resource=r.get("resource", _doc_resource(doc)),
                    start_line=int(r.get("start_line", 0)),
                    end_line=int(r.get("end_line", 0)),
                ))
            else:
                causes.append(Cause(message=str(r),
                                    resource=_doc_resource(doc)))
        return causes

    return [_mk_check(meta, fn, path)]


def load_yaml_check(path: str) -> list[Check]:
    with open(path, "rb") as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    out = []
    for spec in docs:
        if not isinstance(spec, dict):
            raise CheckLoadError(f"{path}: check document is not a mapping")
        # tolerate a wrapping `check:` key
        spec = spec.get("check", spec)
        out.append(_mk_check(spec, _dsl_fn(spec), path))
    return out


def load_check_path(path: str, data: dict | None = None,
                    allow_python: bool = True) -> list[Check]:
    """Load one file or recursively a directory of check files
    (reference rego load.go LoadPoliciesFromDirs).

    allow_python=False refuses ``*.py`` checks — used for downloaded
    bundles, which are data-only: executing fetched code would be far
    beyond what the reference's sandboxed Rego bundles can do."""
    if os.path.isdir(path):
        out = []
        rego_paths = []     # rego modules in one dir load together so
        for root, _dirs, names in os.walk(path):    # imports resolve
            for n in sorted(names):
                if n.startswith("."):
                    continue
                if n.endswith(".py") and not allow_python:
                    _log.warn("ignoring python check in data-only bundle",
                              path=os.path.join(root, n))
                    continue
                if n.endswith(".rego"):
                    if not n.endswith("_test.rego"):
                        rego_paths.append(os.path.join(root, n))
                    continue
                if n.endswith((".py", ".yaml", ".yml")):
                    out.extend(load_check_path(
                        os.path.join(root, n), data, allow_python))
        if rego_paths:
            out.extend(_load_rego(rego_paths, data))
        return out
    if path.endswith(".rego"):
        return _load_rego([path], data)
    if path.endswith(".py"):
        if not allow_python:
            raise CheckLoadError(
                f"python checks are not allowed from bundles: {path}")
        return load_python_check(path, data)
    if path.endswith((".yaml", ".yml")):
        return load_yaml_check(path)
    raise CheckLoadError(f"unsupported check file type: {path}")


def _load_rego(paths: list[str], data: dict | None) -> list[Check]:
    from trivy_tpu.iac.rego import RegoError, load_rego_checks

    try:
        return load_rego_checks(paths, data)
    except RegoError as e:
        raise CheckLoadError(str(e))


def load_data_paths(paths: list[str]) -> dict:
    """--config-data: recursively merge YAML/JSON documents into one
    data dict available to Python checks (reference rego data loading)."""
    data: dict = {}
    for p in paths or []:
        files = []
        if os.path.isdir(p):
            for root, _d, names in os.walk(p):
                files.extend(os.path.join(root, n) for n in sorted(names)
                             if n.endswith((".yaml", ".yml", ".json")))
        else:
            files.append(p)
        for f in files:
            try:
                with open(f, "rb") as fh:
                    doc = yaml.safe_load(fh)
            except Exception as e:
                raise CheckLoadError(f"bad data file {f}: {e}")
            if isinstance(doc, dict):
                data.update(doc)
    return data


# --------------------------------------------------------------- engine


class CheckSet:
    """The resolved set of checks for a scan: embedded builtins plus
    user checks from --config-check paths, filtered by enabled
    namespaces (reference scanner.go:193-196 topLevel gate)."""

    def __init__(self, check_paths: list[str] | None = None,
                 namespaces: list[str] | None = None,
                 data_paths: list[str] | None = None,
                 include_deprecated: bool = False,
                 bundle_paths: list[str] | None = None):
        self.namespaces = BUILTIN_NAMESPACES | set(namespaces or ())
        self.include_deprecated = include_deprecated
        data = load_data_paths(data_paths or [])
        self.user_checks: list[Check] = []
        for p in check_paths or []:
            loaded = load_check_path(p, data)
            _log.info("loaded checks", path=p, count=len(loaded))
            self.user_checks.extend(loaded)
        for p in bundle_paths or []:
            loaded = load_check_path(p, data, allow_python=False)
            _log.info("loaded bundle checks", path=p, count=len(loaded))
            self.user_checks.extend(loaded)

    def _enabled(self, chk: Check) -> bool:
        if chk.namespace.split(".")[0] not in self.namespaces:
            return False
        if chk.deprecated and not self.include_deprecated:
            return False
        return True

    def checks_for(self, file_type: str) -> list[Check]:
        from trivy_tpu.iac.check import checks_for as builtin_for

        out = list(builtin_for(file_type))
        out.extend(c for c in self.user_checks
                   if file_type in c.file_types and self._enabled(c))
        return out


_default = CheckSet()
_active: CheckSet = _default
_lock = make_lock("iac.engine._lock")


def configure(check_paths: list[str] | None = None,
              namespaces: list[str] | None = None,
              data_paths: list[str] | None = None,
              include_deprecated: bool = False,
              bundle_paths: list[str] | None = None) -> CheckSet:
    """Install the scan-wide CheckSet (called once from the CLI runner
    before analyzers fan out)."""
    global _active
    cs = CheckSet(check_paths, namespaces, data_paths, include_deprecated,
                  bundle_paths)
    with _lock:
        _active = cs
    return cs


def reset() -> None:
    global _active
    with _lock:
        _active = _default


def active() -> CheckSet:
    return _active
