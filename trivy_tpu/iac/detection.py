"""Config file-type detection (reference pkg/iac/detection/detect.go:
extension hints + content sniffing)."""

from __future__ import annotations

import json
import os
import re

# file types (reference pkg/misconf/scanner.go:40-52 type map)
DOCKERFILE = "dockerfile"
KUBERNETES = "kubernetes"
CLOUDFORMATION = "cloudformation"
TERRAFORM = "terraform"
TERRAFORM_PLAN = "terraformplan"
HELM = "helm"
YAML = "yaml"
JSON = "json"
AZURE_ARM = "azure-arm"

_DOCKERFILE_NAME = re.compile(
    r"(^|\.)(dockerfile|containerfile)(\.|$)", re.I
)
_K8S_KINDS_HINT = ("apiVersion", "kind")
_DOCKER_INSTRUCTION = re.compile(
    r"^\s*(FROM|ARG)\s+\S", re.I | re.M
)


def detect(path: str, content: bytes) -> str | None:
    """-> file type or None if not a config file we scan."""
    name = os.path.basename(path)
    lower = name.lower()

    if _DOCKERFILE_NAME.search(lower):
        return DOCKERFILE
    if lower.endswith((".tf", ".tf.json")):
        return TERRAFORM
    if lower.endswith(".tfvars"):
        return None  # inputs, not resources
    if lower in ("chart.yaml",) or _is_helm_template(path):
        return HELM
    if lower.endswith((".yaml", ".yml")):
        return _detect_yaml(content)
    if lower.endswith(".json"):
        return _detect_json(content)
    return None


def _is_helm_template(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return "templates" in parts and path.lower().endswith(
        (".yaml", ".yml", ".tpl")
    )


def _detect_yaml(content: bytes) -> str | None:
    text = content.decode("utf-8", "replace")
    if "AWSTemplateFormatVersion" in text or (
        "Resources:" in text and re.search(r"^\s+Type:\s*['\"]?AWS::",
                                           text, re.M)
    ):
        return CLOUDFORMATION
    head = text[:4096]
    if all(re.search(rf"^{k}\s*:", head, re.M) for k in _K8S_KINDS_HINT):
        return KUBERNETES
    return YAML


def _detect_json(content: bytes) -> str | None:
    try:
        doc = json.loads(content)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(doc, dict):
        return JSON
    if "AWSTemplateFormatVersion" in doc or _cfn_resources(doc):
        return CLOUDFORMATION
    if doc.get("$schema", "").find("deploymentTemplate.json") >= 0:
        return AZURE_ARM
    if "apiVersion" in doc and "kind" in doc:
        return KUBERNETES
    if "terraform_version" in doc and "planned_values" in doc:
        return TERRAFORM_PLAN
    return JSON


def _cfn_resources(doc: dict) -> bool:
    res = doc.get("Resources")
    if not isinstance(res, dict):
        return False
    return any(
        isinstance(r, dict) and str(r.get("Type", "")).startswith("AWS::")
        for r in res.values()
    )
