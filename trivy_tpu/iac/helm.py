"""Helm chart rendering (reference pkg/iac/scanners/helm: renders charts
through the helm engine, then scans the output as kubernetes YAML).

This is a self-contained Go-template-subset engine: actions, pipelines,
if/else/with/range/define/include, the sprig helpers charts actually use
(default, quote, indent/nindent, toYaml, trunc, trimSuffix, printf, eq,
...). Anything unresolvable renders as the empty string — same spirit as
the reference's lenient scanning mode, where a value that can't be
resolved must not kill the scan."""

from __future__ import annotations

import base64
import json
import os
import re
from dataclasses import dataclass, field

import yaml

# ------------------------------------------------------------ AST


@dataclass
class _Text:
    text: str


@dataclass
class _Action:
    expr: str


@dataclass
class _Block:
    kind: str                   # if / with / range / define
    expr: str
    body: list = field(default_factory=list)
    # for if: list of (expr|None, body) else-if chains; for others: else body
    branches: list = field(default_factory=list)


_TOKEN_RX = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.S)


def _tokenize(src: str) -> list:
    """-> [('text', str) | ('action', str)] with {{- -}} trimming applied."""
    out = []
    pos = 0
    pending_trim = False
    for m in _TOKEN_RX.finditer(src):
        text = src[pos:m.start()]
        if pending_trim:
            text = text.lstrip()
        if m.group(1) == "-":
            text = text.rstrip()
        if text:
            out.append(("text", text))
        out.append(("action", m.group(2)))
        pending_trim = m.group(3) == "-"
        pos = m.end()
    tail = src[pos:]
    if pending_trim:
        tail = tail.lstrip()
    if tail:
        out.append(("text", tail))
    return out


class TemplateError(Exception):
    pass


def _parse(tokens: list, pos: int = 0, in_block: bool = False):
    """-> (nodes, next_pos, terminator) where terminator is 'end'/'else'/
    ('else if', expr) or None at EOF."""
    nodes: list = []
    while pos < len(tokens):
        kind, val = tokens[pos]
        pos += 1
        if kind == "text":
            nodes.append(_Text(val))
            continue
        word = val.split(None, 1)[0] if val.split() else ""
        rest = val.split(None, 1)[1] if len(val.split(None, 1)) > 1 else ""
        if word == "end":
            if not in_block:
                raise TemplateError("unexpected end")
            return nodes, pos, "end"
        if word == "else":
            if not in_block:
                raise TemplateError("unexpected else")
            if rest.startswith("if"):
                return nodes, pos, ("elseif", rest[2:].strip())
            return nodes, pos, "else"
        if word in ("if", "with", "range", "define", "block"):
            blk = _Block(kind="define" if word == "block" else word,
                         expr=rest.strip().strip('"')
                         if word in ("define", "block") else rest)
            body, pos, term = _parse(tokens, pos, True)
            blk.body = body
            while term not in ("end", None):
                if term == "else":
                    els, pos, term2 = _parse(tokens, pos, True)
                    blk.branches.append((None, els))
                    term = term2
                else:  # ('elseif', expr)
                    els, pos, term2 = _parse(tokens, pos, True)
                    blk.branches.append((term[1], els))
                    term = term2
            nodes.append(blk)
            continue
        if word == "template":
            # {{ template "name" ctx }} == include without pipelining
            nodes.append(_Action(f"include {rest}"))
            continue
        if word in ("/*", "comment"):  # comments {{/* ... */}}
            continue
        if val.startswith("/*"):
            continue
        nodes.append(_Action(val))
    return nodes, pos, None


# ------------------------------------------------------------ expressions


_WORD_RX = re.compile(
    r'"(?:[^"\\]|\\.)*"|`[^`]*`|\((?:[^()]|\([^()]*\))*\)|[^\s|]+'
)


def _truthy(v) -> bool:
    if v is None or v is False:
        return False
    if isinstance(v, (int, float)) and v == 0:
        return False
    if isinstance(v, (str, list, dict, tuple)) and len(v) == 0:
        return False
    return True


class _Engine:
    def __init__(self, defines: dict[str, list], root_ctx: dict):
        self.defines = defines
        self.root = root_ctx

    # -------------------------------------------------- render

    def render(self, nodes: list, dot, vars_: dict | None = None) -> str:
        vars_ = dict(vars_ or {})
        out = []
        for n in nodes:
            if isinstance(n, _Text):
                out.append(n.text)
            elif isinstance(n, _Action):
                expr = n.expr
                if expr.startswith("$") and ":=" in expr:
                    name, _, rhs = expr.partition(":=")
                    vars_[name.strip().lstrip("$")] = self.eval(
                        rhs.strip(), dot, vars_)
                    continue
                v = self.eval(expr, dot, vars_)
                out.append(self._fmt(v))
            elif isinstance(n, _Block):
                out.append(self._render_block(n, dot, vars_))
        return "".join(out)

    def _render_block(self, blk: _Block, dot, vars_: dict) -> str:
        if blk.kind == "define":
            self.defines[blk.expr] = blk.body
            return ""
        if blk.kind == "if":
            if _truthy(self.eval(blk.expr, dot, vars_)):
                return self.render(blk.body, dot, vars_)
            for cond, body in blk.branches:
                if cond is None or _truthy(self.eval(cond, dot, vars_)):
                    return self.render(body, dot, vars_)
            return ""
        if blk.kind == "with":
            v = self.eval(blk.expr, dot, vars_)
            if _truthy(v):
                return self.render(blk.body, v, vars_)
            for cond, body in blk.branches:
                if cond is None:
                    return self.render(body, dot, vars_)
            return ""
        if blk.kind == "range":
            expr = blk.expr
            kv_names: list[str] = []
            if ":=" in expr:
                names, _, expr = expr.partition(":=")
                kv_names = [x.strip().lstrip("$")
                            for x in names.split(",")]
            coll = self.eval(expr.strip(), dot, vars_)
            chunks = []
            if isinstance(coll, dict):
                items = list(coll.items())
            elif isinstance(coll, (list, tuple)):
                items = list(enumerate(coll))
            else:
                items = []
            for k, v in items:
                inner = dict(vars_)
                if len(kv_names) == 2:
                    inner[kv_names[0]], inner[kv_names[1]] = k, v
                elif len(kv_names) == 1:
                    inner[kv_names[0]] = v
                chunks.append(self.render(blk.body, v, inner))
            if not items:
                for cond, body in blk.branches:
                    if cond is None:
                        return self.render(body, dot, vars_)
            return "".join(chunks)
        return ""

    @staticmethod
    def _fmt(v) -> str:
        if v is None:
            return ""
        if v is True:
            return "true"
        if v is False:
            return "false"
        if isinstance(v, float) and v.is_integer():
            return str(int(v))
        return str(v)

    # -------------------------------------------------- eval

    def eval(self, expr: str, dot, vars_: dict):
        try:
            segments = self._split_pipeline(expr)
            value = _NOVAL
            for seg in segments:
                value = self._eval_command(seg, dot, vars_, value)
            return None if value is _NOVAL else value
        except Exception:
            return None

    @staticmethod
    def _split_pipeline(expr: str) -> list[str]:
        out, depth, cur, q = [], 0, [], None
        for ch in expr:
            if q:
                cur.append(ch)
                if ch == q:
                    q = None
                continue
            if ch in "\"`":
                q = ch
                cur.append(ch)
            elif ch == "(":
                depth += 1
                cur.append(ch)
            elif ch == ")":
                depth -= 1
                cur.append(ch)
            elif ch == "|" and depth == 0:
                out.append("".join(cur).strip())
                cur = []
            else:
                cur.append(ch)
        out.append("".join(cur).strip())
        return [s for s in out if s]

    def _eval_command(self, seg: str, dot, vars_: dict, piped):
        words = _WORD_RX.findall(seg)
        if not words:
            return piped
        head, args = words[0], words[1:]
        if head in _FUNCS or head == "include":
            vals = [self._eval_primary(a, dot, vars_) for a in args]
            if piped is not _NOVAL:
                vals.append(piped)
            if head == "include":
                return self._include(vals)
            return _FUNCS[head](self, vals)
        # plain value (possibly with index-style path); pipe ignores extras
        return self._eval_primary(head, dot, vars_)

    def _include(self, vals):
        if len(vals) < 1:
            return ""
        name = vals[0]
        ctx = vals[1] if len(vals) > 1 else self.root
        body = self.defines.get(str(name))
        if body is None:
            return ""
        return self.render(body, ctx)

    def _eval_primary(self, tok: str, dot, vars_: dict):
        if tok.startswith("(") and tok.endswith(")"):
            return self.eval(tok[1:-1], dot, vars_)
        if tok.startswith('"') and tok.endswith('"'):
            return tok[1:-1].replace('\\"', '"').replace("\\\\", "\\") \
                .replace("\\n", "\n").replace("\\t", "\t")
        if tok.startswith("`") and tok.endswith("`"):
            return tok[1:-1]
        if tok in ("true", "false"):
            return tok == "true"
        if tok in ("nil", "null"):
            return None
        if re.fullmatch(r"-?\d+", tok):
            return int(tok)
        if re.fullmatch(r"-?\d+\.\d+", tok):
            return float(tok)
        if tok == ".":
            return dot
        if tok == "$":
            return self.root
        if tok.startswith("$"):
            path = tok[1:].split(".")
            base = vars_.get(path[0], self.root if path[0] == "" else None)
            return _walk(base, [p for p in path[1:] if p])
        if tok.startswith("."):
            parts = [p for p in tok[1:].split(".") if p]
            # .Values/.Chart/.Release resolve from the root context even
            # when dot is rebound (helm always exposes them via $, and
            # charts overwhelmingly use the absolute spelling)
            if parts and parts[0] in ("Values", "Chart", "Release",
                                      "Capabilities", "Template", "Files"):
                return _walk(self.root, parts)
            return _walk(dot, parts)
        return None


_NOVAL = object()


def _walk(base, parts: list[str]):
    cur = base
    for p in parts:
        if isinstance(cur, dict):
            cur = cur.get(p)
        elif isinstance(cur, (list, tuple)) and p.isdigit():
            i = int(p)
            cur = cur[i] if i < len(cur) else None
        else:
            return None
    return cur


# ------------------------------------------------------------ functions


def _to_yaml(v) -> str:
    return yaml.safe_dump(v, default_flow_style=False).rstrip("\n") \
        if v is not None else ""


def _indent(n, s) -> str:
    pad = " " * int(n)
    return "\n".join(pad + line for line in str(s).splitlines())


def _go_printf(fmt, *args) -> str:
    py = re.sub(r"%[-+ #0-9.]*[vs]", "%s", str(fmt))
    py = re.sub(r"%[-+ #0-9.]*d", "%d", py)
    try:
        return py % tuple(args)
    except TypeError:
        return str(fmt)


_FUNCS = {
    "default": lambda e, a: a[1] if len(a) > 1 and _truthy(a[1]) else a[0],
    "quote": lambda e, a: '"%s"' % _Engine._fmt(a[0]) if a else '""',
    "squote": lambda e, a: "'%s'" % _Engine._fmt(a[0]) if a else "''",
    "upper": lambda e, a: str(a[0]).upper(),
    "lower": lambda e, a: str(a[0]).lower(),
    "title": lambda e, a: str(a[0]).title(),
    "trim": lambda e, a: str(a[0]).strip(),
    "trimSuffix": lambda e, a: str(a[1]).removesuffix(str(a[0])),
    "trimPrefix": lambda e, a: str(a[1]).removeprefix(str(a[0])),
    "trunc": lambda e, a: str(a[1])[: int(a[0])] if int(a[0]) >= 0
    else str(a[1])[int(a[0]):],
    "replace": lambda e, a: str(a[2]).replace(str(a[0]), str(a[1])),
    "contains": lambda e, a: str(a[0]) in str(a[1]),
    "hasPrefix": lambda e, a: str(a[1]).startswith(str(a[0])),
    "hasSuffix": lambda e, a: str(a[1]).endswith(str(a[0])),
    "indent": lambda e, a: _indent(a[0], a[1]),
    "nindent": lambda e, a: "\n" + _indent(a[0], a[1]),
    "toYaml": lambda e, a: _to_yaml(a[0]),
    "toJson": lambda e, a: json.dumps(a[0]),
    "fromYaml": lambda e, a: yaml.safe_load(str(a[0])) or {},
    "b64enc": lambda e, a: base64.b64encode(str(a[0]).encode()).decode(),
    "b64dec": lambda e, a: base64.b64decode(str(a[0])).decode("utf-8",
                                                              "replace"),
    "required": lambda e, a: a[1] if len(a) > 1 else None,
    "coalesce": lambda e, a: next((x for x in a if _truthy(x)), None),
    "ternary": lambda e, a: a[0] if _truthy(a[2]) else a[1],
    "empty": lambda e, a: not _truthy(a[0]),
    "not": lambda e, a: not _truthy(a[0]),
    "and": lambda e, a: next((x for x in a if not _truthy(x)), a[-1] if a
                             else None),
    "or": lambda e, a: next((x for x in a if _truthy(x)), a[-1] if a
                            else None),
    "eq": lambda e, a: all(x == a[0] for x in a[1:]),
    "ne": lambda e, a: len(a) > 1 and a[0] != a[1],
    "lt": lambda e, a: a[0] < a[1],
    "le": lambda e, a: a[0] <= a[1],
    "gt": lambda e, a: a[0] > a[1],
    "ge": lambda e, a: a[0] >= a[1],
    "add": lambda e, a: sum(int(x) for x in a),
    "sub": lambda e, a: int(a[0]) - int(a[1]),
    "mul": lambda e, a: int(a[0]) * int(a[1]),
    "div": lambda e, a: int(a[0]) // int(a[1]) if int(a[1]) else 0,
    "len": lambda e, a: len(a[0]) if a[0] is not None else 0,
    "list": lambda e, a: list(a),
    "dict": lambda e, a: {str(a[i]): a[i + 1]
                          for i in range(0, len(a) - 1, 2)},
    "get": lambda e, a: (a[0] or {}).get(str(a[1])),
    "hasKey": lambda e, a: isinstance(a[0], dict) and str(a[1]) in a[0],
    "keys": lambda e, a: list((a[0] or {}).keys()),
    "first": lambda e, a: a[0][0] if a[0] else None,
    "last": lambda e, a: a[0][-1] if a[0] else None,
    "join": lambda e, a: str(a[0]).join(str(x) for x in (a[1] or [])),
    "split": lambda e, a: dict(enumerate(str(a[1]).split(str(a[0])))),
    "splitList": lambda e, a: str(a[1]).split(str(a[0])),
    "printf": lambda e, a: _go_printf(*a),
    "print": lambda e, a: "".join(_Engine._fmt(x) for x in a),
    "lookup": lambda e, a: {},
    "tpl": lambda e, a: e.render(
        _parse(_tokenize(str(a[0])))[0],
        a[1] if len(a) > 1 else e.root),
    "int": lambda e, a: int(float(a[0])) if a and a[0] is not None else 0,
    "toString": lambda e, a: _Engine._fmt(a[0]),
    "kindIs": lambda e, a: {"map": dict, "slice": list, "string": str,
                            "bool": bool, "int": int}.get(
        str(a[0]), object) is type(a[1]),
    "semverCompare": lambda e, a: True,
    "include": None,  # handled specially (needs engine recursion)
}
del _FUNCS["include"]


# ------------------------------------------------------------ chart API


DEFAULT_RELEASE = {
    "Name": "release-name", "Namespace": "default", "Service": "Helm",
    "IsInstall": True, "IsUpgrade": False, "Revision": 1,
}


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def find_chart_roots(paths) -> list[str]:
    """Every directory containing a Chart.yaml. Each chart (including
    charts/ subcharts and unrelated nested charts) renders independently:
    render_chart only consumes a root's own templates/, so there is no
    double-rendering. Independent rendering of subcharts approximates
    helm's parent-merged values with the subchart's own values.yaml."""
    return sorted(
        os.path.dirname(p) for p in paths
        if os.path.basename(p) == "Chart.yaml"
    )


def render_chart(files: dict[str, bytes],
                 value_overrides: dict | None = None,
                 ) -> list[tuple[str, bytes]]:
    """files: chart-root-relative path -> content. Returns
    [(template_path, rendered_yaml_bytes)] for scannable outputs."""
    chart_meta = {}
    if "Chart.yaml" in files:
        try:
            chart_meta = yaml.safe_load(files["Chart.yaml"]) or {}
        except yaml.YAMLError:
            chart_meta = {}
    values = {}
    if "values.yaml" in files:
        try:
            values = yaml.safe_load(files["values.yaml"]) or {}
        except yaml.YAMLError:
            values = {}
    if value_overrides:
        values = _deep_merge(values, value_overrides)

    root_ctx = {
        "Values": values,
        "Chart": {
            "Name": chart_meta.get("name", ""),
            "Version": chart_meta.get("version", ""),
            "AppVersion": chart_meta.get("appVersion", ""),
            "Description": chart_meta.get("description", ""),
        },
        "Release": dict(DEFAULT_RELEASE),
        "Capabilities": {
            "KubeVersion": {"Version": "v1.29.0", "Major": "1",
                            "Minor": "29"},
            "APIVersions": [],
        },
        "Template": {"Name": "", "BasePath": "templates"},
    }

    engine = _Engine(defines={}, root_ctx=root_ctx)
    template_files = {
        p: c for p, c in files.items()
        if p.startswith("templates/") and p.endswith((".yaml", ".yml",
                                                      ".tpl", ".txt"))
    }
    # pass 1: collect defines from every template (helpers first)
    parsed: dict[str, list] = {}
    for p in sorted(template_files,
                    key=lambda x: (not os.path.basename(x).startswith("_"),
                                   x)):
        try:
            nodes, _, _ = _parse(_tokenize(
                template_files[p].decode("utf-8", "replace")))
        except TemplateError:
            continue
        parsed[p] = nodes
        engine.render([n for n in nodes if isinstance(n, _Block)
                       and n.kind == "define"], root_ctx)

    out = []
    for p, nodes in sorted(parsed.items()):
        base = os.path.basename(p)
        if base.startswith("_") or base == "NOTES.txt":
            continue
        root_ctx["Template"]["Name"] = p
        try:
            text = engine.render(nodes, root_ctx)
        except Exception:
            continue
        if text.strip():
            out.append((p, text.encode()))
    return out
