"""#trivy:ignore comment handling (reference pkg/iac/ignore/parse.go).

A comment `#trivy:ignore:<rule-id>` (also `//` and `/* */` styles)
suppresses findings of that rule on the following line, or on the same
line when trailing. `trivy:ignore:*` suppresses everything.
"""

from __future__ import annotations

import re

_IGNORE = re.compile(
    r"(?:#|//|/\*)\s*trivy:ignore:(\S+)", re.I
)


def parse_ignores(content: bytes) -> dict[int, set[str]]:
    """-> {line_number: {rule_id,...}} — the lines these ignores cover."""
    out: dict[int, set[str]] = {}
    for n, line in enumerate(
        content.decode("utf-8", "replace").splitlines(), start=1
    ):
        for m in _IGNORE.finditer(line):
            rule = m.group(1).strip()
            if rule.endswith("*/"):  # '/* trivy:ignore:x */' close marker
                rule = rule[:-2].strip()
            before = line[:m.start()].strip()
            target = n if before else n + 1  # trailing vs standalone
            out.setdefault(target, set()).add(rule)
    return out


def is_ignored(ignores: dict[int, set[str]], rule_id: str, avd_id: str,
               start_line: int, end_line: int = 0) -> bool:
    end = max(end_line, start_line)
    for line in range(start_line, end + 1):
        rules = ignores.get(line)
        if not rules:
            continue
        if "*" in rules or rule_id in rules or (avd_id and avd_id in rules):
            return True
    return False
