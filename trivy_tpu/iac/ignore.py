"""`trivy:ignore` / `tfsec:ignore` comment handling (reference
pkg/iac/ignore/parse.go + rule.go, exercised by
pkg/iac/scanners/terraform/ignore_test.go).

Supported forms, in `#`, `//` and `/* */` comment styles:

- trailing on a line: suppresses findings on that line
- standalone: attaches to the next code line; consecutive comment-only
  lines stack onto the same code line (a blank line breaks the chain)
- `ignore:*` suppresses every rule; otherwise the segment names a rule
  id / AVD id
- `ignore:<rule>[path.to.attr=value]` — parameterized: only suppress
  when the resolved resource attribute matches (unresolvable parameter
  -> the ignore is inactive)
- `ignore:<rule>:exp:2022-01-02` — expires at end of that date; an
  invalid date deactivates the ignore
- `ignore:<rule>:ws:name` — only in the named terraform workspace
  (supports * globs)
"""

from __future__ import annotations

import datetime
import fnmatch
import re
from dataclasses import dataclass, field

_COMMENT_START = re.compile(r"#|//|/\*")
_MARK = re.compile(r"(?:trivy|tfsec):ignore:(\S+)", re.I)
_COMMENT_ONLY = re.compile(r"^\s*(#|//|/\*)")


@dataclass
class IgnoreRule:
    rule: str = "*"
    target_line: int = 0
    params: dict = field(default_factory=dict)  # attr path -> wanted str
    exp: datetime.date | None = None
    exp_invalid: bool = False
    workspace: str | None = None


def _parse_segments(spec: str) -> IgnoreRule | None:
    """`<rule>[k=v]:exp:DATE:ws:NAME` -> IgnoreRule."""
    if spec.endswith("*/"):     # '/* trivy:ignore:x */' close marker
        spec = spec[:-2].rstrip()
    rule = spec
    params: dict = {}
    m = re.match(r"^([^:\[\]]+)\[([^\]]*)\](.*)$", spec)
    rest = ""
    if m:
        rule, rest = m.group(1), m.group(3)
        for kv in m.group(2).split(","):
            k, _, v = kv.partition("=")
            if k.strip():
                params[k.strip()] = v.strip()
    else:
        rule, _, rest = spec.partition(":")
        rest = ":" + rest if rest else ""
    out = IgnoreRule(rule=rule, params=params)
    segs = [s for s in rest.split(":") if s != ""]
    i = 0
    while i < len(segs):
        key = segs[i].lower()
        if key == "exp" and i + 1 < len(segs):
            try:
                out.exp = datetime.date.fromisoformat(segs[i + 1])
            except ValueError:
                out.exp_invalid = True
            i += 2
        elif key == "ws" and i + 1 < len(segs):
            out.workspace = segs[i + 1]
            i += 2
        else:
            i += 1      # unknown segment: tolerate
    return out


def parse_ignores(content: bytes) -> list[IgnoreRule]:
    lines = content.decode("utf-8", "replace").splitlines()
    out: list[IgnoreRule] = []
    for n, line in enumerate(lines, start=1):
        cm = _COMMENT_START.search(line)
        if not cm:
            continue
        # everything after the comment marker may stack several
        # `trivy:ignore:` / `tfsec:ignore:` directives on one line
        offset = cm.start()
        for m in _MARK.finditer(line[offset:]):
            rec = _parse_segments(m.group(1).strip())
            if rec is None:
                continue
            before = line[:offset].strip()
            if before:                          # trailing a code line
                rec.target_line = n
            else:       # standalone: chain through stacked comments to
                j = n + 1                       # the next code line
                while j <= len(lines) and \
                        _COMMENT_ONLY.match(lines[j - 1]):
                    j += 1
                if j > len(lines) or not lines[j - 1].strip():
                    continue                    # blank breaks the chain
                rec.target_line = j
            out.append(rec)
    return out


def _param_matches(params: dict, attrs) -> bool:
    for path, want in params.items():
        node = attrs
        for part in path.split("."):
            if isinstance(node, dict):
                if part in node:
                    node = node[part]
                    continue
                # tolerate flattened keys (versioning.enabled vs
                # versioning_enabled in normalized adapters)
                flat = path.replace(".", "_")
                if flat in attrs:
                    node = attrs[flat]
                    break
                return False
            return False
        got = node
        if isinstance(got, bool):
            got_s = "true" if got else "false"
        elif got is None:
            return False
        else:
            got_s = str(got)
        if got_s != str(want):
            return False
    return True


def is_ignored(ignores: list[IgnoreRule], rule_id: str, avd_id: str,
               start_line: int, end_line: int = 0,
               resource_start: int = 0, attrs: dict | None = None,
               workspace: str = "default",
               today: datetime.date | None = None) -> bool:
    end = max(end_line, start_line)
    for rec in ignores:
        if rec.rule != "*" and rec.rule != rule_id and \
                rec.rule != avd_id:
            continue
        if not (start_line <= rec.target_line <= end or
                (resource_start and rec.target_line == resource_start)):
            continue
        if rec.exp_invalid:
            continue
        if rec.exp is not None:
            now = today or datetime.date.today()
            if now > rec.exp:
                continue
        if rec.workspace is not None and not fnmatch.fnmatch(
                workspace, rec.workspace):
            continue
        if rec.params:
            if attrs is None or not _param_matches(rec.params, attrs):
                continue
        return True
    return False
