"""Line-annotated YAML/JSON config parsing for kubernetes,
cloudformation, and generic yaml/json (reference
pkg/iac/scanners/{kubernetes,cloudformation,yaml,json}/parser).

Mappings carry hidden __line__/__end_line__ keys; CloudFormation
short-form intrinsics (!Ref, !Sub, !GetAtt, ...) are normalized to their
Fn:: long forms so checks see one shape.
"""

from __future__ import annotations

import json
import re

import yaml

LINE_KEY = "__line__"
END_LINE_KEY = "__end_line__"


class _LineLoader(yaml.SafeLoader):
    pass


def _construct_mapping(loader, node, deep=False):
    mapping = yaml.SafeLoader.construct_mapping(loader, node, deep=deep)
    mapping[LINE_KEY] = node.start_mark.line + 1
    mapping[END_LINE_KEY] = node.end_mark.line + 1
    return mapping


_LineLoader.add_constructor(
    yaml.resolver.BaseResolver.DEFAULT_MAPPING_TAG, _construct_mapping
)


# CloudFormation short-form intrinsics -> long form
_INTRINSICS = (
    "Ref", "Sub", "GetAtt", "Join", "Select", "Split", "FindInMap",
    "Base64", "Cidr", "ImportValue", "GetAZs", "If", "Equals", "Not",
    "And", "Or", "Condition",
)


def _intrinsic(name):
    key = "Ref" if name == "Ref" else f"Fn::{name}"

    def construct(loader, node):
        if isinstance(node, yaml.ScalarNode):
            val = loader.construct_scalar(node)
            if name == "GetAtt" and isinstance(val, str):
                val = val.split(".", 1)
            return {key: val}
        if isinstance(node, yaml.SequenceNode):
            return {key: loader.construct_sequence(node, deep=True)}
        return {key: yaml.SafeLoader.construct_mapping(loader, node,
                                                       deep=True)}

    return construct


for _n in _INTRINSICS:
    _LineLoader.add_constructor(f"!{_n}", _intrinsic(_n))


def strip_lines(obj):
    """Deep-copy without the hidden line keys."""
    if isinstance(obj, dict):
        return {k: strip_lines(v) for k, v in obj.items()
                if k not in (LINE_KEY, END_LINE_KEY)}
    if isinstance(obj, list):
        return [strip_lines(v) for v in obj]
    return obj


def get_line(obj, default: int = 0) -> int:
    if isinstance(obj, dict):
        return obj.get(LINE_KEY, default)
    return default


def get_end_line(obj, default: int = 0) -> int:
    if isinstance(obj, dict):
        return obj.get(END_LINE_KEY, default)
    return default


def parse_yaml_docs(content: bytes) -> list[dict]:
    """Multi-document YAML -> list of line-annotated mappings."""
    text = content.decode("utf-8", "replace")
    docs = []
    try:
        for doc in yaml.load_all(text, Loader=_LineLoader):
            if isinstance(doc, dict):
                docs.append(doc)
    except yaml.YAMLError:
        return []
    return docs


def _annotate_json(obj, line: int = 1):
    # json.loads has no line info; approximate with the document start
    if isinstance(obj, dict):
        out = {k: _annotate_json(v, line) for k, v in obj.items()}
        out.setdefault(LINE_KEY, line)
        out.setdefault(END_LINE_KEY, line)
        return out
    if isinstance(obj, list):
        return [_annotate_json(v, line) for v in obj]
    return obj


def parse_config(content: bytes, file_type_hint: str = "yaml") -> list[dict]:
    """-> list of documents (k8s resources / CFN template / raw config)."""
    text = content.decode("utf-8", "replace").lstrip()
    if text.startswith("{") or file_type_hint == "json":
        try:
            doc = json.loads(text)
        except ValueError:
            return []
        if isinstance(doc, list):
            return [_annotate_json(d) for d in doc if isinstance(d, dict)]
        return [_annotate_json(doc)] if isinstance(doc, dict) else []
    return parse_yaml_docs(content)


# ------------------------------------------------------------ kubernetes


_K8S_WORKLOAD_KINDS = (
    "Pod", "Deployment", "StatefulSet", "DaemonSet", "ReplicaSet",
    "Job", "CronJob", "ReplicationController",
)


def k8s_resources(docs: list[dict]) -> list[dict]:
    out = []
    for doc in docs:
        if not isinstance(doc, dict):
            continue
        if "kind" in doc and "apiVersion" in doc:
            out.append(doc)
            # flatten List kinds
            if doc.get("kind") == "List":
                out.extend(i for i in doc.get("items") or []
                           if isinstance(i, dict))
    return out


def k8s_pod_spec(resource: dict) -> dict | None:
    """Extract the pod spec from any workload kind."""
    kind = resource.get("kind", "")
    if kind == "Pod":
        return resource.get("spec")
    if kind == "CronJob":
        return (((resource.get("spec") or {}).get("jobTemplate") or {})
                .get("spec") or {}).get("template", {}).get("spec")
    if kind in _K8S_WORKLOAD_KINDS:
        return ((resource.get("spec") or {}).get("template") or {}).get(
            "spec")
    return None


def k8s_containers(resource: dict) -> list[dict]:
    spec = k8s_pod_spec(resource) or {}
    out = []
    for key in ("initContainers", "containers", "ephemeralContainers"):
        out.extend(c for c in spec.get(key) or [] if isinstance(c, dict))
    return out


# ------------------------------------------------------------ cloudformation


def cfn_resources(docs: list[dict]) -> dict[str, dict]:
    """name -> resource mapping from a CloudFormation template."""
    for doc in docs:
        res = doc.get("Resources")
        if isinstance(res, dict):
            return {
                k: v for k, v in res.items()
                if isinstance(v, dict) and not k.startswith("__")
            }
    return {}


_SUB_VAR = re.compile(r"\$\{[^}]+\}")


def cfn_scalar(value, default=None):
    """Resolve a possibly-intrinsic scalar to a comparable value; keeps
    literal scalars, renders Fn::Sub templates with vars blanked."""
    if isinstance(value, dict):
        if "Fn::Sub" in value:
            t = value["Fn::Sub"]
            if isinstance(t, list):
                t = t[0] if t else ""
            return _SUB_VAR.sub("", str(t)) or default
        return default  # Ref / GetAtt etc. → unknown
    return value if value is not None else default
