"""Per-format IaC parsers producing line-annotated IRs
(reference pkg/iac/scanners/*/parser)."""
