"""HCL2 subset parser for terraform files (reference pkg/iac/scanners/
terraform wraps hashicorp/hcl; this is a from-scratch recursive-descent
parser for the structural subset checks need: blocks, attributes,
literals, lists, objects, heredocs; expressions that reference variables
or call functions are kept as opaque Expr markers)."""

from __future__ import annotations

import re
from dataclasses import dataclass, field


class Expr:
    """Unevaluated expression (reference: hcl traversal/function exprs)."""

    def __init__(self, text: str):
        self.text = text.strip()

    def __repr__(self):
        return f"Expr({self.text!r})"

    def __eq__(self, other):
        return isinstance(other, Expr) and self.text == other.text

    def __hash__(self):
        return hash(("Expr", self.text))


@dataclass
class Attribute:
    name: str
    value: object
    line: int = 0


@dataclass
class Block:
    type: str = ""                 # resource / provider / variable / ...
    labels: list[str] = field(default_factory=list)
    attrs: dict[str, Attribute] = field(default_factory=dict)
    blocks: list["Block"] = field(default_factory=list)
    start_line: int = 0
    end_line: int = 0
    src_path: str = ""             # set by the terraform evaluator
    # module-instance path ("a.b" = module "b" inside module "a"; "" =
    # root), set by the terraform evaluator — distinguishes two
    # instantiations of the SAME source directory for checks whose
    # reference scopes per module instance
    module_id: str = ""

    def get(self, name: str, default=None):
        a = self.attrs.get(name)
        return a.value if a is not None else default

    def line_of(self, name: str) -> int:
        a = self.attrs.get(name)
        return a.line if a is not None else self.start_line

    def children(self, btype: str) -> list["Block"]:
        return [b for b in self.blocks if b.type == btype]

    def child(self, btype: str) -> "Block | None":
        for b in self.blocks:
            if b.type == btype:
                return b
        return None


# ------------------------------------------------------------ tokenizer

_TOKEN_RE = re.compile(r"""
    (?P<comment>\#[^\n]*|//[^\n]*|/\*.*?\*/)
  | (?P<heredoc><<-?\s*(?P<hd_tag>\w+)\n)
  | (?P<string>"(?:\$\{[^}]*\}|[^"\\]|\\.)*")
  | (?P<number>-?\d+(?:\.\d+)?)
  | (?P<ident>[A-Za-z_][\w.\-*]*(?:\[[^\]\n]*\][\w.\-*]*)*)
  | (?P<op>\|\||&&|==|!=|<=|>=|=>|\?|[+*/%!<>-])
  | (?P<punct>[{}\[\](),=:])
  | (?P<newline>\n)
  | (?P<ws>[ \t\r]+)
""", re.X | re.S)


@dataclass
class _Tok:
    kind: str
    text: str
    line: int


def _tokenize(text: str) -> list[_Tok]:
    toks: list[_Tok] = []
    line = 1
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            pos += 1  # skip unknown char
            continue
        kind = m.lastgroup
        tok_text = m.group(0)
        if kind == "heredoc":
            tag = m.group("hd_tag")
            end = re.search(rf"^\s*{re.escape(tag)}\s*$", text[m.end():],
                            re.M)
            if end:
                body = text[m.end():m.end() + end.start()]
                full_end = m.end() + end.end()
            else:
                body = text[m.end():]
                full_end = len(text)
            toks.append(_Tok("string", body, line))
            line += text[pos:full_end].count("\n")
            pos = full_end
            continue
        if kind not in ("ws", "comment"):
            if kind == "newline":
                toks.append(_Tok("newline", "\n", line))
            else:
                toks.append(_Tok(kind, tok_text, line))
        line += tok_text.count("\n")
        pos = m.end()
    toks.append(_Tok("eof", "", line))
    return toks


# ------------------------------------------------------------ parser


class _Parser:
    def __init__(self, toks: list[_Tok]):
        self.toks = toks
        self.i = 0

    def peek(self, skip_nl=True) -> _Tok:
        j = self.i
        while skip_nl and self.toks[j].kind == "newline":
            j += 1
        return self.toks[j]

    def next(self, skip_nl=True) -> _Tok:
        while skip_nl and self.toks[self.i].kind == "newline":
            self.i += 1
        t = self.toks[self.i]
        self.i += 1
        return t

    def parse_body(self, end_brace=False) -> tuple[dict, list]:
        attrs: dict[str, Attribute] = {}
        blocks: list[Block] = []
        while True:
            t = self.peek()
            if t.kind == "eof":
                break
            if end_brace and t.text == "}":
                self.next()
                break
            if t.kind in ("ident", "string"):
                self._parse_item(attrs, blocks)
            else:
                self.next()  # skip stray token
        return attrs, blocks

    def _parse_item(self, attrs, blocks):
        first = self.next()
        name = first.text.strip('"')
        nxt = self.peek()
        if nxt.text == "=":
            self.next()
            start = self.i
            if self.peek().kind == "op":    # unary !x / -x / ...
                value = self._capture_expr(start, ())
            else:
                value = self.parse_value()
                if self.peek(skip_nl=False).kind == "op":
                    # operator continues the expression (a ? b : c,
                    # x + y, ...): recapture the whole source span
                    value = self._capture_expr(start, ())
            attrs[name] = Attribute(name, value, first.line)
            return
        # block: ident [labels...] {
        labels = []
        while True:
            t = self.peek()
            if t.kind in ("string", "ident") and t.text != "{":
                labels.append(self.next().text.strip('"'))
            elif t.text == "{":
                self.next()
                a, b = self.parse_body(end_brace=True)
                blk = Block(type=name, labels=labels, attrs=a, blocks=b,
                            start_line=first.line,
                            end_line=self.toks[self.i - 1].line)
                blocks.append(blk)
                return
            else:
                return  # malformed; bail on this item

    def _capture_expr(self, start_idx: int, terminators) -> Expr:
        """Re-join raw tokens from start_idx up to the end of the
        expression (newline / terminator / closing bracket at depth 0)
        into an Expr for the terraform evaluator — multi-token
        expressions like `var.enabled ? 1 : 0` or `!var.open` span
        several tokens the literal-value grammar can't hold."""
        self.i = start_idx
        parts = []
        depth = 0
        while True:
            t = self.peek(skip_nl=False)
            if t.kind == "eof":
                break
            if t.kind == "newline":
                if depth == 0:
                    break
                self.next(skip_nl=False)
                continue
            if t.text in ("(", "[", "{"):
                depth += 1
            elif t.text in (")", "]", "}"):
                if depth == 0:
                    break
                depth -= 1
            elif depth == 0 and t.text in terminators:
                break
            parts.append(self.next(skip_nl=False).text)
        if not parts:
            self.next(skip_nl=False)    # always advance: a stuck
            # caller loop must never re-enter at the same token
        return Expr(" ".join(parts))

    def parse_value(self):
        t = self.peek()
        if t.text == "[":
            self.next()
            items = []
            while True:
                p = self.peek()
                if p.text == "]":
                    self.next()
                    break
                if p.kind == "eof":
                    break
                while self.peek(skip_nl=False).kind == "newline":
                    self.next(skip_nl=False)  # keep start off newlines:
                    # _capture_expr stops at depth-0 newlines
                start = self.i
                v = self.parse_value()
                if self.peek(skip_nl=False).kind == "op":
                    v = self._capture_expr(start, (",",))
                items.append(v)
                if self.peek().text == ",":
                    self.next()
            return items
        if t.text == "{":
            self.next()
            obj = {}
            while True:
                p = self.peek()
                if p.text == "}":
                    self.next()
                    break
                if p.kind == "eof":
                    break
                key = self.next().text.strip('"')
                if self.peek().text in ("=", ":"):
                    self.next()
                while self.peek(skip_nl=False).kind == "newline":
                    self.next(skip_nl=False)
                start = self.i
                v = self.parse_value()
                if self.peek(skip_nl=False).kind == "op":
                    v = self._capture_expr(start, (",",))
                obj[key] = v
                if self.peek().text == ",":
                    self.next()
            return obj
        if t.kind == "string":
            self.next()
            s = t.text
            if s.startswith('"'):
                s = s[1:-1]
            if "${" in s:
                # interpolation: literal if it collapses, else Expr
                stripped = re.sub(r"\$\{[^}]*\}", "", s)
                if stripped != s and not stripped:
                    return Expr(s)
            return s.replace('\\"', '"').replace("\\\\", "\\")
        if t.kind == "number":
            self.next()
            return float(t.text) if "." in t.text else int(t.text)
        if t.kind == "ident":
            # true/false/null or a reference/function-call expression
            self.next()
            if t.text == "true":
                return True
            if t.text == "false":
                return False
            if t.text == "null":
                return None
            expr = [t.text]
            # swallow a call's parens / indexing on the same line
            while self.peek(skip_nl=False).text == "(":
                depth = 0
                while True:
                    tok = self.next(skip_nl=False)
                    expr.append(tok.text)
                    if tok.text == "(":
                        depth += 1
                    elif tok.text == ")":
                        depth -= 1
                        if depth == 0:
                            break
                    if tok.kind == "eof":
                        break
            return Expr("".join(expr))
        self.next()
        return Expr(t.text)


def parse_hcl(content: bytes) -> list[Block]:
    """-> top-level blocks (resource/provider/module/variable/...)."""
    toks = _tokenize(content.decode("utf-8", "replace"))
    attrs, blocks = _Parser(toks).parse_body()
    # top-level attributes (tf.json style) are ignored here
    return blocks


def parse_tf_json(content: bytes) -> list[Block]:
    """Terraform JSON syntax (*.tf.json): {"resource": {"aws_s3_bucket":
    {"name": {attrs...}}}} -> the same Block IR parse_hcl yields."""
    import json as _json

    try:
        doc = _json.loads(content)
    except ValueError:
        return []
    if not isinstance(doc, dict):
        return []
    out: list[Block] = []
    for btype, groups in doc.items():
        if not isinstance(groups, dict):
            continue
        if btype in ("resource", "data"):
            for rtype, named in groups.items():
                if not isinstance(named, dict):
                    continue
                for name, body in named.items():
                    if isinstance(body, dict):
                        out.append(_json_block(btype, [rtype, name], body))
        else:  # provider/variable/... : one level of labels
            for name, body in groups.items():
                if isinstance(body, dict):
                    out.append(_json_block(btype, [name], body))
    return out


def _json_block(btype: str, labels: list[str], body: dict) -> Block:
    blk = Block(type=btype, labels=labels)
    for k, v in body.items():
        if isinstance(v, dict):
            blk.blocks.append(_json_block(k, [], v))
        elif (isinstance(v, list) and v
              and all(isinstance(i, dict) for i in v)):
            # repeated nested blocks (e.g. ingress rules)
            for i in v:
                blk.blocks.append(_json_block(k, [], i))
        else:
            val = v
            if isinstance(v, str) and "${" in v:
                val = Expr(v)
            blk.attrs[k] = Attribute(k, val, 0)
    return blk


def resources(blocks: list[Block], rtype: str | None = None) -> list[Block]:
    out = [b for b in blocks if b.type == "resource"]
    if rtype:
        out = [b for b in out if b.labels and b.labels[0] == rtype]
    return out
