"""Dockerfile parser (reference pkg/iac/scanners/dockerfile — the
reference wraps moby/buildkit's parser; this is a from-scratch
instruction parser with stage tracking and line numbers)."""

from __future__ import annotations

import re
from dataclasses import dataclass, field

_CONT = re.compile(r"\\\s*$")
_INSTR = re.compile(r"^\s*([A-Za-z]+)\s+(.*)$", re.S)
_COMMENT = re.compile(r"^\s*#")


@dataclass
class Instruction:
    cmd: str = ""          # upper-cased: FROM, RUN, USER, ...
    value: str = ""        # raw argument string (continuations joined)
    flags: list[str] = field(default_factory=list)  # --platform=... etc.
    start_line: int = 0
    end_line: int = 0

    def json_array(self) -> list[str] | None:
        """exec-form arguments, e.g. CMD [\"nginx\"] -> [\"nginx\"]."""
        v = self.value.strip()
        if not v.startswith("["):
            return None
        import json

        try:
            arr = json.loads(v)
        except ValueError:
            return None
        return [str(a) for a in arr] if isinstance(arr, list) else None


@dataclass
class Stage:
    name: str = ""         # FROM ... AS <name>, else the image ref
    base: str = ""         # image ref
    start_line: int = 0
    instructions: list[Instruction] = field(default_factory=list)


@dataclass
class Dockerfile:
    stages: list[Stage] = field(default_factory=list)
    instructions: list[Instruction] = field(default_factory=list)

    @property
    def final_stage(self) -> Stage | None:
        return self.stages[-1] if self.stages else None

    def by_cmd(self, cmd: str, stage: Stage | None = None):
        src = stage.instructions if stage else self.instructions
        return [i for i in src if i.cmd == cmd.upper()]


def parse_dockerfile(content: bytes) -> Dockerfile:
    text = content.decode("utf-8", "replace")
    df = Dockerfile()
    stage: Stage | None = None

    lines = text.splitlines()
    i = 0
    while i < len(lines):
        raw = lines[i]
        if not raw.strip() or _COMMENT.match(raw):
            i += 1
            continue
        start = i + 1
        # join continuation lines, dropping interleaved comments
        parts = []
        while i < len(lines):
            line = lines[i]
            if _COMMENT.match(line) and parts:
                i += 1
                continue
            if _CONT.search(line):
                parts.append(_CONT.sub("", line))
                i += 1
                continue
            parts.append(line)
            i += 1
            break
        joined = "\n".join(parts)
        m = _INSTR.match(joined)
        if not m:
            continue
        cmd = m.group(1).upper()
        rest = m.group(2).strip()
        flags = []
        while rest.startswith("--"):
            flag, _, rest2 = rest.partition(" ")
            flags.append(flag)
            rest = rest2.strip()
        instr = Instruction(cmd=cmd, value=rest, flags=flags,
                            start_line=start, end_line=i)
        if cmd == "FROM":
            fm = re.match(r"(\S+)(?:\s+[Aa][Ss]\s+(\S+))?", rest)
            base = fm.group(1) if fm else rest
            name = (fm.group(2) if fm else None) or base
            stage = Stage(name=name, base=base, start_line=start)
            df.stages.append(stage)
        if stage is not None:
            stage.instructions.append(instr)
        df.instructions.append(instr)
    return df
