"""IaC misconfiguration engine (reference pkg/iac, 41k LoC of Go+Rego,
re-expressed as a Python check engine over per-format parsers).

Pipeline (reference pkg/misconf/scanner.go): detect file type -> parse to
a typed IR -> evaluate builtin checks -> Misconfiguration with cause
line ranges and code snippets. Runs entirely host-side (the reference
keeps misconfig scanning client-side even in client/server mode,
docs/docs/references/modes/client-server.md:11-21).
"""
