"""Mini-Rego interpreter for user checks and ignore policies.

The reference's entire custom-check ecosystem is Rego: misconfig checks
are `deny` rules evaluated by OPA (reference pkg/iac/rego/scanner.go:179,
load.go), and `--ignore-policy` evaluates `package trivy; ignore {...}`
per finding (pkg/result/filter.go applyPolicy). This module implements
the Rego subset those policies actually use, so a migrating user's
`.rego` files run unmodified:

- complete rules (`name = value { body }`, `name := value`, constants),
  partial-set rules (`deny[msg] { body }`), default rules, functions
  (`f(x) = y { body }`), multiple bodies (disjunction)
- rego.v1 keywords: `name if body`, `name contains x if body`, `x in xs`
- `:=` / `=` binding, `not`, `some`, `[_]` iteration, refs over
  input/data/rules/literals, arrays/objects/sets, array/set/object
  comprehensions, arithmetic + comparison operators
- builtins: count/split/concat/sprintf/startswith/endswith/contains/
  lower/upper/trim*/replace/to_number/format_int/abs/sum/min/max/sort/
  array.concat/object.get/regex.match/json.unmarshal/... (sandboxed: no
  I/O, no http.send, no opa.runtime)
- `# METADATA` annotations and `__rego_metadata__` rules for check
  id/title/severity/selector (pkg/iac/rego/metadata.go)
- the `data.lib.trivy` helper module (parse_cvss_vector_v3) that the
  published ignore-policy examples import (pkg/result/module.go),
  provided as a native function

Undefined propagates the Rego way: an expression over a missing key
yields no results, `not` succeeds on undefined/false, comprehensions
over undefined collections yield empty collections, and a rule with no
succeeding body falls back to its `default` or is undefined.

Unsupported (raise RegoError at parse time): `else`, `every`, `with`,
dotted rule heads, multi-target unification beyond simple var binding.
"""

from __future__ import annotations

import json
import re

import yaml

__all__ = ["RegoError", "Set", "parse_module", "Evaluator",
           "load_rego_checks"]


class RegoError(Exception):
    pass


class _Undefined(Exception):
    """Internal: builtin hit an error -> expression is undefined."""


# ----------------------------------------------------------------- values


def _canon(v):
    if isinstance(v, Set):
        return {"__set__": sorted(_vkey(x) for x in v)}
    if isinstance(v, dict):
        return {str(k): _canon(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_canon(x) for x in v]
    return v


def _vkey(v) -> str:
    return json.dumps(_canon(v), sort_keys=True, default=str)


class Set:
    """A Rego set: ordered-insertion, dedup by structural equality
    (members may be unhashable dicts/lists)."""

    __slots__ = ("_items", "_keys")

    def __init__(self, items=()):
        self._items: list = []
        self._keys: set = set()
        for it in items:
            self.add(it)

    def add(self, v):
        k = _vkey(v)
        if k not in self._keys:
            self._keys.add(k)
            self._items.append(v)

    def __contains__(self, v):
        return _vkey(v) in self._keys

    def __iter__(self):
        return iter(self._items)

    def __len__(self):
        return len(self._items)

    def __eq__(self, other):
        return isinstance(other, Set) and self._keys == other._keys

    def __ne__(self, other):
        return not self.__eq__(other)

    def __repr__(self):
        return "Set(%r)" % (self._items,)

    def to_json(self):
        return sorted(self._items, key=_vkey)


# -------------------------------------------------------------- tokenizer


_PUNCTS = (":=", "==", "!=", "<=", ">=", "{", "}", "[", "]", "(", ")",
           ",", ":", ";", "=", "<", ">", "+", "-", "*", "/", "%", "|",
           "&", ".")

_NAME_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")
_NUM_RE = re.compile(r"\d+(\.\d+)?([eE][+-]?\d+)?")


class Tok:
    __slots__ = ("kind", "val", "line")

    def __init__(self, kind, val, line):
        self.kind, self.val, self.line = kind, val, line

    def __repr__(self):
        return f"Tok({self.kind},{self.val!r},{self.line})"


def _tokenize(src: str):
    toks: list[Tok] = []
    comments: dict[int, str] = {}
    i, n, line = 0, len(src), 1
    while i < n:
        c = src[i]
        if c == "\n":
            toks.append(Tok("nl", "\n", line))
            line += 1
            i += 1
            continue
        if c in " \t\r":
            i += 1
            continue
        if c == "#":
            j = src.find("\n", i)
            j = n if j < 0 else j
            comments[line] = src[i:j]
            i = j
            continue
        if c == '"':
            j, out = i + 1, []
            while j < n and src[j] != '"':
                if src[j] == "\\" and j + 1 < n:
                    esc = src[j + 1]
                    out.append({"n": "\n", "t": "\t", "r": "\r",
                                '"': '"', "\\": "\\", "/": "/"}.get(
                                    esc, "\\" + esc))
                    j += 2
                else:
                    out.append(src[j])
                    j += 1
            if j >= n:
                raise RegoError(f"line {line}: unterminated string")
            toks.append(Tok("str", "".join(out), line))
            i = j + 1
            continue
        if c == "`":
            j = src.find("`", i + 1)
            if j < 0:
                raise RegoError(f"line {line}: unterminated raw string")
            raw = src[i + 1:j]
            toks.append(Tok("str", raw, line))
            line += raw.count("\n")
            i = j + 1
            continue
        m = _NUM_RE.match(src, i)
        if m and c.isdigit():
            text = m.group(0)
            toks.append(Tok("num",
                            float(text) if ("." in text or "e" in text
                                            or "E" in text) else int(text),
                            line))
            i = m.end()
            continue
        m = _NAME_RE.match(src, i)
        if m:
            toks.append(Tok("name", m.group(0), line))
            i = m.end()
            continue
        for p in _PUNCTS:
            if src.startswith(p, i):
                toks.append(Tok("punct", p, line))
                i += len(p)
                break
        else:
            raise RegoError(f"line {line}: unexpected character {c!r}")
    toks.append(Tok("eof", "", line))
    return toks, comments


# ------------------------------------------------------------------ AST

# terms/stmts are tuples: ("scalar", v) ("var", name)
# ("ref", base_term, [("dot", name) | ("idx", term)])
# ("array", [t]) ("object", [(k, v)]) ("set", [t])
# ("compr_arr", t, query) ("compr_set", t, query)
# ("compr_obj", k, v, query) ("call", (path...), [args])
# ("binop", op, l, r) ("not", stmt) ("in", x, coll) ("inkv", k, v, coll)
# ("assign", name, t) ("unify", l, r) ("some", [names])
# ("somein", names, coll)


class Rule:
    __slots__ = ("name", "kind", "args", "key", "value", "bodies",
                 "default", "line")

    def __init__(self, name, kind, args=None, key=None, value=None,
                 bodies=None, default=None, line=0):
        self.name, self.kind = name, kind   # complete | set | obj | func
        self.args, self.key, self.value = args, key, value
        self.bodies = bodies if bodies is not None else []
        self.default = default              # ("has", term) or None
        self.line = line


class Module:
    __slots__ = ("package", "imports", "rules", "metadata", "source")

    def __init__(self, package, imports, rules, metadata, source=""):
        self.package = package      # tuple path, e.g. ("user", "foo")
        self.imports = imports      # {alias: tuple path}
        self.rules = rules          # {name: [Rule]}
        self.metadata = metadata    # {rule_name_or_"": dict}
        self.source = source


class _Parser:
    def __init__(self, toks, comments):
        self.toks = toks
        self.comments = comments
        self.i = 0

    # -- token plumbing
    def _peek(self, skip_nl=True):
        j = self.i
        while skip_nl and self.toks[j].kind == "nl":
            j += 1
        return self.toks[j]

    def _next(self, skip_nl=True):
        while skip_nl and self.toks[self.i].kind == "nl":
            self.i += 1
        t = self.toks[self.i]
        self.i += 1
        return t

    def _at(self, val, skip_nl=True):
        t = self._peek(skip_nl)
        return (t.kind in ("punct", "name")) and t.val == val

    def _eat(self, val, skip_nl=True):
        if self._at(val, skip_nl):
            self._next(skip_nl)
            return True
        return False

    def _expect(self, val):
        t = self._next()
        if t.val != val:
            raise RegoError(f"line {t.line}: expected {val!r}, "
                            f"got {t.val!r}")
        return t

    def _name(self):
        t = self._next()
        if t.kind != "name":
            raise RegoError(f"line {t.line}: expected name, got {t.val!r}")
        return t.val

    # -- module
    def parse_module(self) -> Module:
        pkg_line = self._peek().line
        pkg_md = self._metadata_above(pkg_line)
        self._expect("package")
        package = tuple(self._ref_path())
        imports: dict[str, tuple] = {}
        while self._at("import"):
            self._next()
            path = self._ref_path()
            alias = None
            if self._at("as"):
                self._next()
                alias = self._name()
            path_t = tuple(path)
            if path_t in (("rego", "v1"), ("future", "keywords")) or \
                    (len(path_t) == 3 and path_t[:2] == ("future",
                                                         "keywords")):
                continue        # keyword imports: always-on here
            if path_t[0] != "data":
                raise RegoError(f"unsupported import {'.'.join(path)}")
            imports[alias or path_t[-1]] = path_t[1:]
        rules: dict[str, list[Rule]] = {}
        metadata: dict[str, dict] = {}
        if pkg_md:
            metadata[""] = pkg_md
        while self._peek().kind != "eof":
            if self._at("else"):
                raise RegoError("`else` is not supported")
            line = self._peek().line
            r = self._rule()
            md = self._metadata_above(line)
            if md and r.name not in metadata:
                metadata[r.name] = md
            rules.setdefault(r.name, []).append(r)
        return Module(package, imports, rules, metadata)

    def _metadata_above(self, rule_line: int) -> dict | None:
        """Contiguous comment block ending at rule_line-1 that starts
        with `# METADATA` -> YAML-parsed annotations."""
        lines = []
        ln = rule_line - 1
        while ln in self.comments:
            lines.append(self.comments[ln])
            ln -= 1
        lines.reverse()
        if not lines or lines[0].strip() != "# METADATA":
            return None
        body = "\n".join(l.lstrip("#").removeprefix(" ")
                         for l in lines[1:])
        try:
            doc = yaml.safe_load(body)
        except yaml.YAMLError:
            return None
        return doc if isinstance(doc, dict) else None

    def _ref_path(self) -> list[str]:
        parts = [self._name()]
        while self._at(".", skip_nl=False):
            self._next(skip_nl=False)
            parts.append(self._name())
        return parts

    # -- rules
    def _rule(self) -> Rule:
        if self._at("default"):
            self._next()
            name = self._name()
            if not (self._eat(":=") or self._eat("=")):
                raise RegoError("default rule needs a value")
            val = self._term()
            return Rule(name, "complete", default=("has", val))
        t = self._peek()
        name = self._name()
        line = t.line
        for bad in ("else", "every", "with"):
            if self._at(bad):
                raise RegoError(f"line {line}: `{bad}` is not supported")
        if self._at("(", skip_nl=False):
            return self._func_rule(name, line)
        if self._at("[", skip_nl=False):
            return self._bracket_rule(name, line)
        if self._at("contains"):
            self._next()
            key = self._term()
            bodies = self._if_bodies()
            return Rule(name, "set", key=key, bodies=bodies, line=line)
        if self._eat(":=") or self._eat("="):
            value = self._term()
            bodies = self._if_bodies(optional=True)
            if not bodies:
                bodies = [[]]       # constant: vacuously true body
            return Rule(name, "complete", value=value, bodies=bodies,
                        line=line)
        bodies = self._if_bodies()
        return Rule(name, "complete", value=("scalar", True),
                    bodies=bodies, line=line)

    def _func_rule(self, name, line) -> Rule:
        self._expect("(")
        args = []
        if not self._at(")"):
            while True:
                args.append(self._term())
                if not self._eat(","):
                    break
        self._expect(")")
        value = ("scalar", True)
        if self._eat(":=") or self._eat("="):
            value = self._term()
        bodies = self._if_bodies(optional=True) or [[]]
        return Rule(name, "func", args=args, value=value, bodies=bodies,
                    line=line)

    def _bracket_rule(self, name, line) -> Rule:
        self._expect("[")
        key = self._term()
        self._expect("]")
        if self._eat(":=") or self._eat("="):
            value = self._term()
            bodies = self._if_bodies(optional=True) or [[]]
            return Rule(name, "obj", key=key, value=value, bodies=bodies,
                        line=line)
        bodies = self._if_bodies(optional=True) or [[]]
        return Rule(name, "set", key=key, bodies=bodies, line=line)

    def _if_bodies(self, optional=False) -> list[list]:
        """`if { q }` | `if stmt` | `{ q }` (possibly chained)."""
        bodies = []
        if self._at("if"):
            self._next()
            if self._at("{"):
                bodies.append(self._braced_query())
            else:
                bodies.append([self._stmt()])
        while self._at("{"):
            bodies.append(self._braced_query())
        if not bodies and not optional:
            t = self._peek()
            raise RegoError(f"line {t.line}: expected rule body")
        return bodies

    def _braced_query(self) -> list:
        self._expect("{")
        q = self._query(end="}")
        self._expect("}")
        return q

    # -- queries / statements
    def _query(self, end) -> list:
        stmts = []
        while True:
            while self._peek(skip_nl=False).kind == "nl" or \
                    self._at(";", skip_nl=False):
                self._next(skip_nl=False)
            if self._at(end):
                return stmts
            stmts.append(self._stmt())

    def _stmt(self):
        for bad in ("every", "with", "else"):
            if self._at(bad):
                t = self._peek()
                raise RegoError(
                    f"line {t.line}: `{bad}` is not supported")
        if self._at("not"):
            self._next()
            return ("not", self._stmt())
        if self._at("some"):
            self._next()
            names = [self._name()]
            while self._eat(",", skip_nl=False):
                names.append(self._name())
            if self._at("in"):
                self._next()
                return ("somein", names, self._expr())
            return ("some", names)
        return self._expr()

    # -- expressions (precedence: * / % > + - > cmp/in > = :=)
    def _expr(self):
        left = self._cmp()
        if self._at(":=", skip_nl=False):
            self._next()
            if left[0] != "var":
                raise RegoError(":= target must be a variable")
            return ("assign", left[1], self._cmp())
        if self._at("=", skip_nl=False):
            self._next()
            return ("unify", left, self._cmp())
        return left

    def _cmp(self, no_union=False):
        left = self._add(no_union)
        t = self._peek(skip_nl=False)
        if t.kind == "punct" and t.val in ("==", "!=", "<", "<=", ">",
                                           ">="):
            op = self._next(skip_nl=False).val
            return ("binop", op, left, self._add())
        if t.kind == "name" and t.val == "in":
            self._next(skip_nl=False)
            return ("in", left, self._add())
        return left

    def _add(self, no_union=False):
        left = self._mul()
        while True:
            t = self._peek(skip_nl=False)
            if no_union and t.kind == "punct" and t.val == "|":
                return left
            if t.kind == "punct" and t.val in ("+", "-", "|", "&"):
                op = self._next(skip_nl=False).val
                left = ("binop", op, left, self._mul())
            else:
                return left

    def _mul(self):
        left = self._unary()
        while True:
            t = self._peek(skip_nl=False)
            if t.kind == "punct" and t.val in ("*", "/", "%"):
                op = self._next(skip_nl=False).val
                left = ("binop", op, left, self._unary())
            else:
                return left

    def _unary(self):
        if self._at("-"):
            self._next()
            inner = self._unary()
            if inner[0] == "scalar" and isinstance(inner[1], (int, float)):
                return ("scalar", -inner[1])
            return ("binop", "-", ("scalar", 0), inner)
        return self._postfix()

    def _postfix(self):
        base = self._primary()
        ops = []
        while True:
            t = self._peek(skip_nl=False)
            if t.kind == "punct" and t.val == ".":
                self._next(skip_nl=False)
                ops.append(("dot", self._name()))
            elif t.kind == "punct" and t.val == "[":
                self._next(skip_nl=False)
                ops.append(("idx", self._term()))
                self._expect("]")
            elif t.kind == "punct" and t.val == "(":
                # call: base must be a plain ref path
                path = _ref_to_path(base, ops)
                if path is None:
                    raise RegoError(
                        f"line {t.line}: cannot call a non-reference")
                self._next(skip_nl=False)
                args = []
                if not self._at(")"):
                    while True:
                        args.append(self._term())
                        if not self._eat(","):
                            break
                self._expect(")")
                base, ops = ("call", tuple(path), args), []
            else:
                break
        if not ops:
            return base
        return ("ref", base, ops)

    def _primary(self):
        t = self._peek()
        if t.kind == "str":
            self._next()
            return ("scalar", t.val)
        if t.kind == "num":
            self._next()
            return ("scalar", t.val)
        if t.kind == "name":
            if t.val in ("true", "false"):
                self._next()
                return ("scalar", t.val == "true")
            if t.val == "null":
                self._next()
                return ("scalar", None)
            if t.val == "not":
                self._next()
                return ("not", self._stmt())
            self._next()
            return ("var", t.val)
        if t.val == "(":
            self._next()
            e = self._expr()
            self._expect(")")
            return e
        if t.val == "[":
            return self._array_or_compr()
        if t.val == "{":
            return self._obj_set_or_compr()
        raise RegoError(f"line {t.line}: unexpected token {t.val!r}")

    def _term(self, no_union=False):
        return self._cmp(no_union)

    def _array_or_compr(self):
        self._expect("[")
        if self._at("]"):
            self._next()
            return ("array", [])
        first = self._term(no_union=True)
        if self._at("|"):
            self._next()
            q = self._query(end="]")
            self._expect("]")
            return ("compr_arr", first, q)
        items = [first]
        while self._eat(","):
            if self._at("]"):
                break
            items.append(self._term())
        self._expect("]")
        return ("array", items)

    def _obj_set_or_compr(self):
        self._expect("{")
        if self._at("}"):
            self._next()
            return ("object", [])
        first = self._term(no_union=True)
        if self._at(":"):
            self._next()
            v = self._term(no_union=True)
            if self._at("|"):
                self._next()
                q = self._query(end="}")
                self._expect("}")
                return ("compr_obj", first, v, q)
            pairs = [(first, v)]
            while self._eat(","):
                if self._at("}"):
                    break
                k = self._term()
                self._expect(":")
                pairs.append((k, self._term()))
            self._expect("}")
            return ("object", pairs)
        if self._at("|"):
            self._next()
            q = self._query(end="}")
            self._expect("}")
            return ("compr_set", first, q)
        items = [first]
        while self._eat(","):
            if self._at("}"):
                break
            items.append(self._term())
        self._expect("}")
        return ("set", items)


def _ref_to_path(base, ops):
    if base[0] != "var":
        return None
    path = [base[1]]
    for op in ops:
        if op[0] == "dot":
            path.append(op[1])
        elif op[0] == "idx" and op[1][0] == "scalar" and \
                isinstance(op[1][1], str):
            path.append(op[1][1])
        else:
            return None
    return path


def parse_module(src: str) -> Module:
    toks, comments = _tokenize(src)
    mod = _Parser(toks, comments).parse_module()
    mod.source = src
    return mod


# ---------------------------------------------------------------- builtins


def _go_sprintf(fmt: str, args: list) -> str:
    out, ai = [], 0
    i, n = 0, len(fmt)
    while i < n:
        c = fmt[i]
        if c != "%":
            out.append(c)
            i += 1
            continue
        if i + 1 < n and fmt[i + 1] == "%":
            out.append("%")
            i += 2
            continue
        j = i + 1
        while j < n and not fmt[j].isalpha():
            j += 1
        if j >= n:
            out.append(fmt[i:])
            break
        verb, flags = fmt[j], fmt[i + 1:j]
        if verb not in "vsdfxXeqt":
            out.append(fmt[i:j + 1])    # unknown verb: keep literal,
            i = j + 1                   # do not consume an argument
            continue
        a = args[ai] if ai < len(args) else ""
        ai += 1
        if verb == "v":
            out.append(json.dumps(_canon(a)) if isinstance(
                a, (dict, list, Set)) else
                ("true" if a is True else "false" if a is False
                 else str(a)))
        elif verb == "s":
            out.append(("%" + flags + "s") % (str(a),))
        elif verb == "q":
            out.append(json.dumps(str(a)))
        elif verb == "t":
            out.append("true" if a else "false")
        elif verb in "dxX":
            out.append(("%" + flags + verb) % (int(a),))
        elif verb in "ef":
            out.append(("%" + flags + verb) % (float(a),))
        i = j + 1
    return "".join(out)


def _b_contains(a, b=None):
    if b is None:
        raise _Undefined
    if isinstance(a, str):
        return b in a if isinstance(b, str) else False
    if isinstance(a, (list, Set)):
        return b in a if isinstance(a, Set) else any(
            _vkey(x) == _vkey(b) for x in a)
    raise _Undefined


def _num2(f):
    def g(a, b):
        if isinstance(a, bool) or isinstance(b, bool) or not \
                isinstance(a, (int, float)) or not \
                isinstance(b, (int, float)):
            raise _Undefined
        return f(a, b)
    return g


def _parse_cvss_vector_v3(cvss):
    """Native data.lib.trivy.parse_cvss_vector_v3 (reference
    pkg/result/module.go embeds the equivalent Rego)."""
    if not isinstance(cvss, str):
        raise _Undefined
    s = cvss.split("/")
    tables = [
        ("AttackVector", {"AV:N": "Network", "AV:A": "Adjacent",
                          "AV:L": "Local", "AV:P": "Physical"}),
        ("AttackComplexity", {"AC:L": "Low", "AC:H": "High"}),
        ("PrivilegesRequired", {"PR:N": "None", "PR:L": "Low",
                                "PR:H": "High"}),
        ("UserInteraction", {"UI:N": "None", "UI:R": "Required"}),
        ("Scope", {"S:U": "Unchanged", "S:C": "Changed"}),
        ("Confidentiality", {"C:N": "None", "C:L": "Low", "C:H": "High"}),
        ("Integrity", {"I:N": "None", "I:L": "Low", "I:H": "High"}),
        ("Availability", {"A:N": "None", "A:L": "Low", "A:H": "High"}),
    ]
    out = {}
    for k, (name, table) in enumerate(tables, start=1):
        if k >= len(s) or s[k] not in table:
            raise _Undefined
        out[name] = table[s[k]]
    return out


def _b_sort(x):
    if isinstance(x, list):
        return sorted(x, key=_vkey)
    if isinstance(x, Set):
        return sorted(x, key=_vkey)
    raise _Undefined


_BUILTINS = {
    ("count",): lambda x: len(x) if isinstance(
        x, (list, dict, Set, str)) else (_ for _ in ()).throw(
            _Undefined()),
    ("split",): lambda s, d: s.split(d) if isinstance(s, str) else
    (_ for _ in ()).throw(_Undefined()),
    ("concat",): lambda d, xs: d.join(list(xs)),
    ("sprintf",): _go_sprintf,
    ("startswith",): lambda s, p: isinstance(s, str) and s.startswith(p),
    ("endswith",): lambda s, p: isinstance(s, str) and s.endswith(p),
    ("contains",): _b_contains,
    ("indexof",): lambda s, x: s.find(x),
    ("lower",): lambda s: s.lower(),
    ("upper",): lambda s: s.upper(),
    ("trim",): lambda s, cut: s.strip(cut),
    ("trim_space",): lambda s: s.strip(),
    ("trim_left",): lambda s, cut: s.lstrip(cut),
    ("trim_right",): lambda s, cut: s.rstrip(cut),
    ("trim_prefix",): lambda s, p: s[len(p):] if s.startswith(p) else s,
    ("trim_suffix",): lambda s, p: s[:-len(p)] if p and s.endswith(p)
    else s,
    ("replace",): lambda s, old, new: s.replace(old, new),
    ("substring",): lambda s, off, ln: s[off:] if ln < 0
    else s[off:off + ln],
    ("format_int",): lambda x, base: ({2: "{0:b}", 8: "{0:o}",
                                       10: "{0:d}", 16: "{0:x}"}
                                      [base]).format(int(x)),
    ("to_number",): lambda x: (int(x) if isinstance(x, bool) else
                               x if isinstance(x, (int, float)) else
                               float(x) if "." in str(x) else int(x)),
    ("abs",): lambda x: abs(x),
    ("round",): lambda x: round(x),
    ("ceil",): lambda x: __import__("math").ceil(x),
    ("floor",): lambda x: __import__("math").floor(x),
    ("max",): lambda xs: max(xs) if len(xs) else
    (_ for _ in ()).throw(_Undefined()),
    ("min",): lambda xs: min(xs) if len(xs) else
    (_ for _ in ()).throw(_Undefined()),
    ("sum",): lambda xs: sum(xs),
    ("product",): lambda xs: __import__("math").prod(xs),
    ("sort",): _b_sort,
    ("array", "concat"): lambda a, b: list(a) + list(b),
    ("array", "slice"): lambda a, i, j: a[max(i, 0):max(j, 0)],
    ("array", "reverse"): lambda a: list(reversed(a)),
    ("object", "get"): lambda o, k, d: o.get(k, d) if isinstance(
        o, dict) else d,
    ("object", "keys"): lambda o: Set(o.keys()),
    ("json", "marshal"): lambda x: json.dumps(_canon(x),
                                              separators=(",", ":")),
    ("json", "unmarshal"): lambda s: json.loads(s),
    ("base64", "encode"): lambda s: __import__("base64").b64encode(
        s.encode()).decode(),
    ("base64", "decode"): lambda s: __import__("base64").b64decode(
        s).decode(),
    ("regex", "match"): lambda p, s: re.search(p, s) is not None,
    ("re_match",): lambda p, s: re.search(p, s) is not None,
    ("regex", "replace"): lambda s, p, r: re.sub(p, r, s),
    ("regex", "split"): lambda p, s: re.split(p, s),
    ("is_string",): lambda x: isinstance(x, str),
    ("is_number",): lambda x: isinstance(x, (int, float)) and not
    isinstance(x, bool),
    ("is_boolean",): lambda x: isinstance(x, bool),
    ("is_array",): lambda x: isinstance(x, list),
    ("is_object",): lambda x: isinstance(x, dict),
    ("is_set",): lambda x: isinstance(x, Set),
    ("is_null",): lambda x: x is None,
    ("type_name",): lambda x: ("null" if x is None else
                               "boolean" if isinstance(x, bool) else
                               "number" if isinstance(x, (int, float))
                               else "string" if isinstance(x, str) else
                               "array" if isinstance(x, list) else
                               "set" if isinstance(x, Set) else
                               "object"),
    ("numbers", "range"): lambda a, b: list(range(a, b + 1)) if a <= b
    else list(range(a, b - 1, -1)),
    ("glob", "match"): lambda pat, delim, s: __import__(
        "fnmatch").fnmatch(s, pat),
    # data.lib.trivy natives (reference pkg/result/module.go)
    ("lib", "trivy", "parse_cvss_vector_v3"): _parse_cvss_vector_v3,
}


# --------------------------------------------------------------- evaluator


class _Node:
    """Position in the virtual `data` document: package tree + user
    data, merged (rules shadow plain data)."""

    __slots__ = ("tree", "data")

    def __init__(self, tree, data):
        self.tree, self.data = tree, data


_MAX_STEPS = 2_000_000


class Evaluator:
    def __init__(self, modules: list[Module], input=None, data=None):
        self.input = input
        self.data = data if isinstance(data, dict) else {}
        self.tree: dict = {}
        for m in modules:
            node = self.tree
            for part in m.package:
                node = node.setdefault(part, {})
            for name, group in m.rules.items():
                node.setdefault(name, []).extend(
                    (m, r) for r in group)
        self._cache: dict = {}
        self._steps = 0

    # ---- public
    def query(self, path: str, input=None):
        """Evaluate e.g. "data.user.foo.deny". Returns the document
        (sets materialize to Set) or None when undefined."""
        if input is not None:
            self.input = input
            self._cache.clear()
        parts = path.split(".")
        if parts[0] != "data":
            raise RegoError("query must start with data.")
        node: object = _Node(self.tree, self.data)
        for p in parts[1:]:
            node = self._descend(node, p)
            if node is None:
                return None
        if isinstance(node, _Node):
            return self._materialize_node(node)
        return node

    # ---- data descent
    def _descend(self, node, key):
        if isinstance(node, _Node):
            t = node.tree.get(key) if isinstance(node.tree, dict) else None
            d = node.data.get(key) if isinstance(node.data, dict) else None
            if isinstance(t, list):        # rule group leaf
                return self._rule_value(t)
            if t is not None:
                return _Node(t, d if isinstance(d, dict) else {})
            if d is not None or (isinstance(node.data, dict)
                                 and key in node.data):
                return d
            return None
        if isinstance(node, dict):
            return node.get(key)
        return None

    def _materialize_node(self, node: _Node):
        out = dict(node.data) if isinstance(node.data, dict) else {}
        for k, v in node.tree.items():
            if isinstance(v, list):
                rv = self._rule_value(v)
                if rv is not None:
                    out[k] = rv
            else:
                out[k] = self._materialize_node(_Node(v, out.get(k, {})))
        return out

    # ---- rule evaluation
    def _rule_value(self, group: list):
        key = id(group)
        if key in self._cache:
            return self._cache[key]
        self._cache[key] = None     # cycle guard: undefined during eval
        mod, first = group[0]
        kind = first.kind
        result = None
        if kind == "func":
            result = None   # functions are not values; calls go
            # through _call_func with the rule group directly
        elif kind == "set":
            out = Set()
            for mod, r in group:
                for body in r.bodies:
                    for env in self._eval_query(body, 0, {}, mod):
                        for v, env2 in self._eval_term(
                                r.key, env, mod):
                            out.add(v)
            result = out
        elif kind == "obj":
            obj = {}
            for mod, r in group:
                for body in r.bodies:
                    for env in self._eval_query(body, 0, {}, mod):
                        for k, env2 in self._eval_term(
                                r.key, env, mod):
                            for v, _ in self._eval_term(
                                    r.value, env2, mod):
                                obj[k] = v
            result = obj
        else:                       # complete
            default = None
            for mod, r in group:
                if r.default is not None:
                    for v, _ in self._eval_term(r.default[1], {},
                                                      mod):
                        default = v
            value = None
            found = False
            for mod, r in group:
                if r.default is not None and not r.bodies:
                    continue
                for body in r.bodies:
                    for env in self._eval_query(body, 0, {}, mod):
                        for v, _ in self._eval_term(r.value, env,
                                                          mod):
                            value, found = v, True
                            break
                        if found:
                            break
                    if found:
                        break
                if found:
                    break
            result = value if found else default
        self._cache[key] = result
        return result

    # ---- query evaluation: generator of envs
    def _eval_query(self, stmts, i, env, mod):
        self._steps += 1
        if self._steps > _MAX_STEPS:
            raise RegoError("evaluation budget exceeded")
        if i >= len(stmts):
            yield env
            return
        stmt = stmts[i]
        for env2 in self._eval_stmt(stmt, env, mod):
            yield from self._eval_query(stmts, i + 1, env2, mod)

    def _eval_stmt(self, stmt, env, mod):
        kind = stmt[0]
        if kind == "not":
            ok = True
            for v, _ in self._eval_stmt_values(stmt[1], env, mod):
                if v is not False:
                    ok = False
                    break
            if ok:
                yield env
            return
        if kind == "some":
            env2 = dict(env)
            for name in stmt[1]:
                env2.pop(name, None)
            yield env2
            return
        if kind == "somein":
            names, coll_t = stmt[1], stmt[2]
            for coll, env2 in self._eval_term(coll_t, env, mod):
                yield from self._iter_bind(names, coll, env2)
            return
        if kind == "assign":
            for v, env2 in self._eval_term(stmt[2], env, mod):
                env3 = dict(env2)
                env3[stmt[1]] = v
                yield env3
            return
        if kind == "unify":
            yield from self._unify(stmt[1], stmt[2], env, mod)
            return
        for v, env2 in self._eval_term(stmt, env, mod):
            if v is not False:
                yield env2

    def _eval_stmt_values(self, stmt, env, mod):
        """Like _eval_stmt but yields (value, env) — used by `not`."""
        kind = stmt[0]
        if kind in ("assign", "unify", "some", "somein", "not"):
            for env2 in self._eval_stmt(stmt, env, mod):
                yield True, env2
            return
        yield from self._eval_term(stmt, env, mod)

    def _iter_bind(self, names, coll, env):
        if isinstance(coll, list):
            for idx, v in enumerate(coll):
                env2 = dict(env)
                if len(names) == 1:
                    env2[names[0]] = v
                else:
                    env2[names[0]], env2[names[1]] = idx, v
                yield env2
        elif isinstance(coll, dict):
            for k, v in coll.items():
                env2 = dict(env)
                if len(names) == 1:
                    env2[names[0]] = v
                else:
                    env2[names[0]], env2[names[1]] = k, v
                yield env2
        elif isinstance(coll, Set):
            for v in coll:
                env2 = dict(env)
                env2[names[0]] = v
                yield env2

    def _unify(self, lt, rt, env, mod):
        # simple var on either side binds; otherwise equality
        if lt[0] == "var" and lt[1] != "_" and lt[1] not in env and not \
                self._is_rule_name(lt[1], mod):
            for v, env2 in self._eval_term(rt, env, mod):
                env3 = dict(env2)
                env3[lt[1]] = v
                yield env3
            return
        if rt[0] == "var" and rt[1] != "_" and rt[1] not in env and not \
                self._is_rule_name(rt[1], mod):
            for v, env2 in self._eval_term(lt, env, mod):
                env3 = dict(env2)
                env3[rt[1]] = v
                yield env3
            return
        if lt[0] == "array":
            # destructure [a, b] = expr (incl. array-literal rhs)
            for v, env2 in self._eval_term(rt, env, mod):
                if not isinstance(v, list) or len(v) != len(lt[1]):
                    continue
                envs = [env2]
                ok = True
                for elt_t, elt_v in zip(lt[1], v):
                    nxt = []
                    for e in envs:
                        nxt.extend(self._unify(
                            elt_t, ("scalar", elt_v), e, mod))
                    envs = nxt
                    if not envs:
                        ok = False
                        break
                if ok:
                    yield from iter(envs)
            return
        for lv, env2 in self._eval_term(lt, env, mod):
            for rv, env3 in self._eval_term(rt, env2, mod):
                if _eq(lv, rv):
                    yield env3

    def _is_rule_name(self, name, mod):
        return name in mod.rules

    # ---- term evaluation: generator of (value, env)
    def _eval_term(self, t, env, mod):
        self._steps += 1
        if self._steps > _MAX_STEPS:
            raise RegoError("evaluation budget exceeded")
        kind = t[0]
        if kind == "scalar":
            yield t[1], env
        elif kind == "var":
            yield from self._eval_var(t[1], env, mod)
        elif kind == "ref":
            for base, env2 in self._eval_term(t[1], env, mod):
                yield from self._apply_ops(base, t[2], 0, env2, mod)
        elif kind == "call":
            yield from self._eval_call(t[1], t[2], env, mod)
        elif kind == "array":
            yield from self._eval_seq(t[1], env, mod, list)
        elif kind == "set":
            yield from self._eval_seq(t[1], env, mod, Set)
        elif kind == "object":
            yield from self._eval_object(t[1], env, mod)
        elif kind == "compr_arr":
            out = []
            for e in self._eval_query(t[2], 0, env, mod):
                for v, _ in self._eval_term(t[1], e, mod):
                    out.append(v)
                    break
            yield out, env
        elif kind == "compr_set":
            out = Set()
            for e in self._eval_query(t[2], 0, env, mod):
                for v, _ in self._eval_term(t[1], e, mod):
                    out.add(v)
                    break
            yield out, env
        elif kind == "compr_obj":
            out = {}
            for e in self._eval_query(t[3], 0, env, mod):
                for k, e2 in self._eval_term(t[1], e, mod):
                    for v, _ in self._eval_term(t[2], e2, mod):
                        out[k] = v
                        break
                    break
            yield out, env
        elif kind == "binop":
            yield from self._eval_binop(t[1], t[2], t[3], env, mod)
        elif kind == "in":
            for x, env2 in self._eval_term(t[1], env, mod):
                for coll, env3 in self._eval_term(t[2], env2, mod):
                    yield _member(x, coll), env3
        elif kind == "not":
            ok = True
            for v, _ in self._eval_stmt_values(t[1], env, mod):
                if v is not False:
                    ok = False
                    break
            yield ok, env
        elif kind in ("assign", "unify"):
            for env2 in self._eval_stmt(t, env, mod):
                yield True, env2
        else:
            raise RegoError(f"cannot evaluate {kind}")

    def _eval_var(self, name, env, mod):
        if name in env:
            yield env[name], env
            return
        if name == "input":
            if self.input is not None:
                yield self.input, env
            return
        if name == "data":
            yield _Node(self.tree, self.data), env
            return
        if name in mod.imports:
            node: object = _Node(self.tree, self.data)
            for p in mod.imports[name]:
                node = self._descend(node, p)
                if node is None:
                    return
            yield node, env
            return
        if name in mod.rules:
            v = self._rule_value(self._group_for(name, mod))
            if v is not None:
                yield v, env
            return
        if name == "_":
            raise RegoError("`_` used outside an index position")
        # unbound var in value position: undefined
        return

    def _group_for(self, name, mod):
        node = self.tree
        for part in mod.package:
            node = node.get(part, {})
        return node.get(name, [])

    def _apply_ops(self, val, ops, i, env, mod):
        if i >= len(ops):
            if isinstance(val, _Node):
                val = self._materialize_node(val)
            yield val, env
            return
        op = ops[i]
        if op[0] == "dot":
            nxt = self._index(val, op[1])
            for v in nxt:
                yield from self._apply_ops(v, ops, i + 1, env, mod)
            return
        idx_t = op[1]
        # unbound-var (or `_`) index: iterate the collection
        if idx_t[0] == "var" and (idx_t[1] == "_" or
                                  (idx_t[1] not in env and not
                                   self._is_rule_name(idx_t[1], mod))):
            if isinstance(val, _Node):
                val = self._materialize_node(val)
            var = idx_t[1]
            if isinstance(val, list):
                items = list(enumerate(val))
            elif isinstance(val, dict):
                items = list(val.items())
            elif isinstance(val, Set):
                items = [(v, v) for v in val]
            else:
                return
            for k, v in items:
                env2 = env if var == "_" else {**env, var: k}
                yield from self._apply_ops(v, ops, i + 1, env2, mod)
            return
        for key, env2 in self._eval_term(idx_t, env, mod):
            for v in self._index(val, key):
                yield from self._apply_ops(v, ops, i + 1, env2, mod)

    def _index(self, val, key):
        if isinstance(val, _Node):
            v = self._descend(val, key)
            return [] if v is None else [v]
        if isinstance(val, dict):
            return [val[key]] if key in val else []
        if isinstance(val, list):
            if isinstance(key, bool) or not isinstance(key, int):
                return []
            return [val[key]] if 0 <= key < len(val) else []
        if isinstance(val, Set):
            return [key] if key in val else []
        return []

    def _eval_seq(self, terms, env, mod, ctor):
        def rec(j, env2, acc):
            if j >= len(terms):
                yield ctor(acc), env2
                return
            for v, env3 in self._eval_term(terms[j], env2, mod):
                yield from rec(j + 1, env3, acc + [v])
        yield from rec(0, env, [])

    def _eval_object(self, pairs, env, mod):
        def rec(j, env2, acc):
            if j >= len(pairs):
                yield dict(acc), env2
                return
            kt, vt = pairs[j]
            for k, env3 in self._eval_term(kt, env2, mod):
                for v, env4 in self._eval_term(vt, env3, mod):
                    yield from rec(j + 1, env4, acc + [(k, v)])
        yield from rec(0, env, [])

    def _eval_binop(self, op, lt, rt, env, mod):
        for lv, env2 in self._eval_term(lt, env, mod):
            for rv, env3 in self._eval_term(rt, env2, mod):
                try:
                    yield _binop(op, lv, rv), env3
                except _Undefined:
                    pass

    def _eval_call(self, path, args, env, mod):
        # resolve: local/imported function rule, else builtin
        group = None
        if len(path) == 1 and path[0] in mod.rules:
            group = self._group_for(path[0], mod)
        elif path[0] in mod.imports:
            node = self.tree
            for p in mod.imports[path[0]] + tuple(path[1:-1]):
                node = node.get(p, {}) if isinstance(node, dict) else {}
            g = node.get(path[-1]) if isinstance(node, dict) else None
            if isinstance(g, list):
                group = g
            else:
                # native fallthrough under the imported path
                native = _BUILTINS.get(
                    mod.imports[path[0]] + tuple(path[1:]))
                if native is not None:
                    yield from self._call_native(native, args, env, mod)
                    return
        elif path[0] == "data":
            node = self.tree
            for p in path[1:-1]:
                node = node.get(p, {}) if isinstance(node, dict) else {}
            g = node.get(path[-1]) if isinstance(node, dict) else None
            if isinstance(g, list):
                group = g
            elif tuple(path[1:]) in _BUILTINS:
                yield from self._call_native(_BUILTINS[tuple(path[1:])],
                                             args, env, mod)
                return
        if group:
            yield from self._call_func(group, args, env, mod)
            return
        native = _BUILTINS.get(tuple(path))
        if native is None:
            raise RegoError(f"unknown function {'.'.join(path)}")
        yield from self._call_native(native, args, env, mod)

    def _call_native(self, fn, args, env, mod):
        def rec(j, env2, acc):
            if j >= len(args):
                try:
                    yield fn(*acc), env2
                except _Undefined:
                    return
                except RegoError:
                    raise
                except Exception:
                    return          # builtin error -> undefined
                return
            for v, env3 in self._eval_term(args[j], env2, mod):
                yield from rec(j + 1, env3, acc + [v])
        yield from rec(0, env, [])

    def _call_func(self, group, args, env, mod):
        # evaluate args in caller env first (ground semantics)
        def rec(j, env2, acc):
            if j >= len(args):
                yield acc, env2
                return
            for v, env3 in self._eval_term(args[j], env2, mod):
                yield from rec(j + 1, env3, acc + [v])
        for vals, env2 in rec(0, env, []):
            for fmod, rule in group:
                if len(rule.args) != len(vals):
                    continue
                # bind params (vars bind, ground params must match)
                fenv: dict | None = {}
                for pt, pv in zip(rule.args, vals):
                    if pt[0] == "var" and pt[1] != "_":
                        fenv[pt[1]] = pv
                    elif pt[0] == "scalar":
                        if not _eq(pt[1], pv):
                            fenv = None
                            break
                if fenv is None:
                    continue
                done = False
                for body in rule.bodies:
                    for benv in self._eval_query(body, 0, fenv, fmod):
                        for v, _ in self._eval_term(rule.value, benv,
                                                    fmod):
                            yield v, env2
                            done = True
                            break
                        if done:
                            break
                    if done:
                        break
                if done:
                    break


def _eq(a, b):
    if isinstance(a, Set) or isinstance(b, Set):
        return isinstance(a, Set) and isinstance(b, Set) and a == b
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    return _vkey(a) == _vkey(b) if isinstance(
        a, (dict, list)) or isinstance(b, (dict, list)) else a == b


def _member(x, coll):
    if isinstance(coll, (list, Set)):
        return any(_eq(x, v) for v in coll)
    if isinstance(coll, dict):
        return any(_eq(x, v) for v in coll.values())
    if isinstance(coll, str) and isinstance(x, str):
        return x in coll
    return False


def _binop(op, a, b):
    if op in ("==", "!="):
        r = _eq(a, b)
        return r if op == "==" else not r
    if op in ("<", "<=", ">", ">="):
        if type(a) is bool or type(b) is bool:
            raise _Undefined
        if isinstance(a, (int, float)) and isinstance(b, (int, float)):
            pass
        elif isinstance(a, str) and isinstance(b, str):
            pass
        else:
            raise _Undefined
        return {"<": a < b, "<=": a <= b, ">": a > b,
                ">=": a >= b}[op]
    if isinstance(a, Set) and isinstance(b, Set):
        if op == "|":
            return Set(list(a) + list(b))
        if op == "&":
            return Set(v for v in a if v in b)
        if op == "-":
            return Set(v for v in a if v not in b)
        raise _Undefined
    return {"+": _num2(lambda x, y: x + y),
            "-": _num2(lambda x, y: x - y),
            "*": _num2(lambda x, y: x * y),
            "/": _num2(_div),
            "%": _num2(lambda x, y: x % y if y else
                       (_ for _ in ()).throw(_Undefined()))}[op](a, b)


def _div(x, y):
    if y == 0:
        raise _Undefined
    r = x / y
    return int(r) if isinstance(x, int) and isinstance(y, int) and \
        x % y == 0 else r


# ------------------------------------------------------- check integration


_SEVERITIES = ("CRITICAL", "HIGH", "MEDIUM", "LOW", "UNKNOWN")

_ALL_TYPES = ("dockerfile", "kubernetes", "terraform", "cloudformation",
              "terraformplan", "azure-arm", "helm", "yaml", "json")

_SELECTOR_MAP = {
    "dockerfile": ("dockerfile",),
    "kubernetes": ("kubernetes", "helm"),
    "rbac": ("kubernetes", "helm"),
    "cloud": ("terraform", "cloudformation", "terraformplan",
              "azure-arm"),
    "terraform": ("terraform", "terraformplan"),
    "cloudformation": ("cloudformation",),
    "yaml": ("yaml",),
    "json": ("json",),
    "toml": (),
    "azure-arm": ("azure-arm",),
    "helm": ("helm",),
}


def _module_metadata(mod: Module, ev: Evaluator) -> dict:
    """Check metadata: `# METADATA` annotations (custom: id/severity/
    input.selector) or a `__rego_metadata__` rule (legacy), reference
    pkg/iac/rego/metadata.go."""
    md: dict = {}
    ann = mod.metadata.get("deny") or mod.metadata.get("") or {}
    if ann:
        md.update({k: v for k, v in ann.items()
                   if k in ("title", "description")})
        custom = ann.get("custom") or {}
        if isinstance(custom, dict):
            md.update(custom)
    if "__rego_metadata__" in mod.rules:
        v = ev.query("data." + ".".join(mod.package) +
                     ".__rego_metadata__")
        if isinstance(v, dict):
            md.update(v)
    sel = md.get("input", {}).get("selector") if isinstance(
        md.get("input"), dict) else None
    if not sel and "__rego_input__" in mod.rules:
        v = ev.query("data." + ".".join(mod.package) + ".__rego_input__")
        if isinstance(v, dict):
            sel = (v.get("selector") or {})
            if isinstance(sel, dict):
                sel = [sel]
    if sel:
        # selector present: scope strictly to what it maps to (an
        # unsupported type maps to no inputs, not to every input)
        types: list[str] = []
        for s in sel:
            if isinstance(s, dict):
                types.extend(_SELECTOR_MAP.get(s.get("type", ""), ()))
        md["_file_types"] = tuple(dict.fromkeys(types))
    else:
        md["_file_types"] = _ALL_TYPES
    return md


def load_rego_checks(paths: list[str], data: dict | None = None) -> list:
    """Parse .rego files into engine Checks. All modules load into one
    shared Evaluator so cross-module imports (`import data.lib.x`)
    resolve; only modules with a `deny` rule become checks (the rest are
    libraries). Reference scanner behavior: a module without metadata
    reports ID "N/A" / severity UNKNOWN and applies to every input
    type (integration/testdata/dockerfile-custom-policies.json.golden)."""
    from trivy_tpu.iac.check import Cause, Check
    from trivy_tpu.iac.engine import input_doc

    modules = []
    for p in paths:
        with open(p, encoding="utf-8", errors="replace") as f:
            src = f.read()
        try:
            modules.append(parse_module(src))
        except RegoError as e:
            raise RegoError(f"{p}: {e}")
    checks = []
    for mod in modules:
        if "deny" not in mod.rules:
            continue
        pkg = ".".join(mod.package)
        ev = Evaluator(modules, data=data)
        md = _module_metadata(mod, ev)
        sev = str(md.get("severity", "UNKNOWN")).upper()
        if sev not in _SEVERITIES:
            sev = "UNKNOWN"

        def fn(ctx, _pkg=pkg, _modules=modules, _data=data):
            evq = Evaluator(_modules, input=input_doc(ctx), data=_data)
            res = evq.query(f"data.{_pkg}.deny")
            causes = []
            if res is True:         # classic complete rule: deny { .. }
                return [Cause(message=f"data.{_pkg}.deny")]
            if isinstance(res, (str, dict)):
                res = Set([res])    # deny = "msg" { .. } style
            if res is False or res is None:
                res = ()
            for item in res:
                if isinstance(item, dict):
                    causes.append(Cause(
                        message=str(item.get("msg", "")),
                        start_line=int(item.get("startline", 0) or 0),
                        end_line=int(item.get("endline", 0) or 0),
                    ))
                else:
                    causes.append(Cause(message=str(item)))
            return causes

        checks.append(Check(
            id=str(md.get("id", "N/A")),
            avd_id=str(md.get("avd_id", md.get("id", "N/A"))),
            title=str(md.get("title", "N/A")),
            description=md.get("description",
                               f"Rego module: data.{pkg}"),
            resolution=str(md.get("recommended_actions",
                                  md.get("recommended_action", ""))),
            severity=sev,
            file_types=md["_file_types"],
            provider="Generic", service="general",
            url=str(md.get("url", "")),
            namespace=pkg,
            fn=fn,
        ))
    return checks
