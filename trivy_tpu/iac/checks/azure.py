"""Azure ARM deployment-template checks (reference
pkg/iac/scanners/azure/arm + pkg/iac/adapters/arm: ARM JSON adapted into
typed azure provider structs, evaluated by the azure rule set)."""

from __future__ import annotations

from trivy_tpu.iac.check import check
from trivy_tpu.iac.checks.cloud import CloudResource

# azurerm terraform blocks adapt into the same resource types
# (azure_ext.adapt_terraform_azure), so these checks cover both inputs
_ARM = ("azure-arm", "terraform", "terraformplan")


def adapt_arm(doc: dict) -> list[CloudResource]:
    out: list[CloudResource] = []
    for res in doc.get("resources") or []:
        if not isinstance(res, dict):
            continue
        rtype = str(res.get("type", ""))
        name = str(res.get("name", ""))
        props = res.get("properties") or {}
        cr = CloudResource(name=f"{rtype}/{name}" if name else rtype)
        if rtype == "Microsoft.Storage/storageAccounts":
            cr.type = "storage_account"
            cr.attrs = {
                "https_only": props.get("supportsHttpsTrafficOnly"),
                "min_tls": props.get("minimumTlsVersion"),
                "public_blob_access": props.get("allowBlobPublicAccess"),
            }
        elif rtype == "Microsoft.Network/networkSecurityGroups":
            cr.type = "nsg"
            rules = []
            for rule in props.get("securityRules") or []:
                rp = (rule or {}).get("properties") or {}
                rules.append({
                    "direction": str(rp.get("direction", "")),
                    "access": str(rp.get("access", "")),
                    "source": str(rp.get("sourceAddressPrefix", "")),
                    "port": str(rp.get("destinationPortRange", "")),
                })
            cr.attrs = {"rules": rules}
        elif rtype == "Microsoft.Sql/servers":
            cr.type = "sql_server"
            cr.attrs = {
                "public_network_access":
                    props.get("publicNetworkAccess"),
                "min_tls": props.get("minimalTlsVersion"),
            }
        elif rtype == "Microsoft.Compute/virtualMachines":
            os_profile = props.get("osProfile") or {}
            linux = os_profile.get("linuxConfiguration") or {}
            cr.type = "virtual_machine"
            cr.attrs = {
                "password_auth":
                    not linux.get("disablePasswordAuthentication", False)
                    if linux else None,
            }
        elif rtype == "Microsoft.KeyVault/vaults":
            cr.type = "key_vault"
            cr.attrs = {
                # absent -> the Azure default (disabled), a definite
                # failing value; ARM expressions resolve to None=unknown
                "purge_protection": props.get("enablePurgeProtection",
                                              False),
                "soft_delete_days":
                    props.get("softDeleteRetentionInDays"),
            }
        else:
            continue
        out.append(cr)
    return out


def _of_type(ctx, t):
    return [r for r in ctx.cloud_resources if r.type == t]


@check("AVD-AZU-0008", "Storage account allows insecure (HTTP) transfer",
       severity="HIGH", file_types=_ARM, provider="azure", service="storage",
       resolution="Set supportsHttpsTrafficOnly to true")
def storage_https_only(ctx):
    out = []
    for r in _of_type(ctx, "storage_account"):
        if r.attrs.get("https_only") is False:
            out.append(r.cause(
                "Storage account allows non-HTTPS traffic"))
    return out


@check("AVD-AZU-0011", "Storage account uses an outdated minimum TLS "
                       "version", severity="MEDIUM", file_types=_ARM,
       provider="azure", service="storage",
       resolution="Set minimumTlsVersion to TLS1_2")
def storage_min_tls(ctx):
    out = []
    for r in _of_type(ctx, "storage_account"):
        tls = r.attrs.get("min_tls")
        if tls is not None and str(tls) in ("TLS1_0", "TLS1_1"):
            out.append(r.cause(
                f"Storage account minimum TLS version is '{tls}'"))
    return out


@check("AVD-AZU-0007", "Storage container allows public blob access",
       severity="HIGH", file_types=_ARM, provider="azure",
       service="storage",
       resolution="Set allowBlobPublicAccess to false")
def storage_public_blob(ctx):
    out = []
    for r in _of_type(ctx, "storage_account"):
        if r.attrs.get("public_blob_access") is True:
            out.append(r.cause(
                "Storage account permits public blob access"))
    return out


@check("AVD-AZU-0047", "Network security group rule allows unrestricted "
                       "ingress", severity="CRITICAL", file_types=_ARM,
       provider="azure", service="network",
       resolution="Restrict sourceAddressPrefix to known networks")
def nsg_open_ingress(ctx):
    out = []
    for r in _of_type(ctx, "nsg"):
        for rule in r.attrs.get("rules") or []:
            if (rule["direction"].lower() == "inbound"
                    and rule["access"].lower() == "allow"
                    and rule["source"] in ("*", "0.0.0.0/0", "Internet",
                                           "any")):
                out.append(r.cause(
                    f"NSG rule allows inbound access from "
                    f"'{rule['source']}' on port '{rule['port']}'"))
    return out


@check("AVD-AZU-0022", "SQL server allows public network access",
       severity="HIGH", file_types=_ARM, provider="azure", service="sql",
       resolution="Set publicNetworkAccess to Disabled")
def sql_public_access(ctx):
    out = []
    for r in _of_type(ctx, "sql_server"):
        if str(r.attrs.get("public_network_access", "")) == "Enabled":
            out.append(r.cause("SQL server public network access enabled"))
    return out


@check("AVD-AZU-0039", "Virtual machine allows password authentication",
       severity="MEDIUM", file_types=_ARM, provider="azure",
       service="compute",
       resolution="Set disablePasswordAuthentication to true and use SSH "
                  "keys")
def vm_password_auth(ctx):
    out = []
    for r in _of_type(ctx, "virtual_machine"):
        if r.attrs.get("password_auth") is True:
            out.append(r.cause(
                "Linux VM allows password authentication"))
    return out


@check("AVD-AZU-0016", "Key vault purge protection is disabled",
       severity="MEDIUM", file_types=_ARM, provider="azure",
       service="keyvault",
       resolution="Enable purge protection on the key vault")
def kv_purge_protection(ctx):
    out = []
    for r in _of_type(ctx, "key_vault"):
        if r.attrs.get("purge_protection") is False:
            out.append(r.cause("Key vault purge protection not enabled"))
    return out
