"""AWS cloud checks shared by terraform + cloudformation (reference
pkg/iac/adapters map both formats into typed provider structs at
pkg/iac/providers/aws; same idea here with a light canonical schema).

Canonical resource view: CloudResource{type, name, attrs, lines} where
type is e.g. "s3_bucket", "security_group", and attrs hold normalized
fields (None = unknown/unresolved -> checks stay silent, matching the
reference's unresolvable-value semantics)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from trivy_tpu.iac.check import Cause, check
from trivy_tpu.iac.parsers.hcl import Block, Expr
from trivy_tpu.iac.parsers.yamlconf import (
    cfn_scalar,
    get_end_line,
    get_line,
    strip_lines,
)

_C = ("terraform", "cloudformation", "terraformplan")


@dataclass
class CloudResource:
    type: str = ""
    name: str = ""
    attrs: dict = field(default_factory=dict)
    start_line: int = 0
    end_line: int = 0

    def cause(self, message: str) -> Cause:
        return Cause(message=message, resource=self.name,
                     start_line=self.start_line, end_line=self.end_line)


# ------------------------------------------------------------ terraform


# single source of truth for unresolved-value semantics: spec.py
from trivy_tpu.iac.checks.spec import (  # noqa: E402
    tf_value as _tf_value,
    tri as _tf_tristate,
)


def adapt_terraform(blocks: list[Block],
                    scan_blocks: list[Block] | None = None
                    ) -> list[CloudResource]:
    """scan_blocks: every evaluated block of the scan (all files, all
    modules) for adapters whose reference counterpart reads scan-wide
    context (e.g. aws_ebs_encryption_by_default); defaults to
    `blocks`."""
    out: list[CloudResource] = []
    from trivy_tpu.iac.checks.aws_ext import adapt_terraform_aws_ext
    from trivy_tpu.iac.checks.azure_ext import adapt_terraform_azure
    from trivy_tpu.iac.checks.gcp import adapt_terraform_gcp
    from trivy_tpu.iac.checks.gcp_ext import adapt_terraform_gcp_ext
    from trivy_tpu.iac.checks.providers_misc import adapt_terraform_misc

    out.extend(adapt_terraform_aws_ext(blocks, scan_blocks))
    out.extend(adapt_terraform_azure(blocks))
    out.extend(adapt_terraform_gcp(blocks))
    out.extend(adapt_terraform_gcp_ext(blocks))
    out.extend(adapt_terraform_misc(blocks))
    res_blocks = [b for b in blocks if b.type == "resource" and
                  len(b.labels) >= 2]
    # companion resources referenced by bucket: aws_s3_bucket_* attach
    # settings to buckets declared separately (tf >= 4 style)
    sse_for: set[str] = set()
    pab_true_for: set[str] = set()
    pab_flags_for: dict[str, dict] = {}
    for b in res_blocks:
        t = b.labels[0]
        if t == "aws_s3_bucket_server_side_encryption_configuration":
            ref = b.get("bucket")
            if isinstance(ref, Expr):
                sse_for.add(ref.text.split(".")[-2] if "." in ref.text
                            else ref.text)
            elif isinstance(ref, str):
                sse_for.add(ref)
        if t == "aws_s3_bucket_public_access_block":
            # absent flag -> the provider default false (a definite
            # failing value); present-but-unresolved -> None = unknown
            flags = {k: _tf_tristate(b, k, False) for k in (
                "block_public_acls", "block_public_policy",
                "ignore_public_acls", "restrict_public_buckets")}
            ref = b.get("bucket")
            key = (ref.text.split(".")[-2] if isinstance(ref, Expr)
                   and "." in ref.text else str(ref))
            pab_flags_for[key] = flags
            if all(v is True for v in flags.values()):
                pab_true_for.add(key)

    for b in res_blocks:
        t, name = b.labels[0], b.labels[1]
        full = f"{t}.{name}"
        cr = CloudResource(name=full, start_line=b.start_line,
                           end_line=b.end_line)
        if t == "aws_s3_bucket":
            cr.type = "s3_bucket"
            enc = b.child("server_side_encryption_configuration")
            cr.attrs = {
                "acl": _tf_value(b.get("acl")),
                "encrypted": True if enc is not None
                else (True if name in sse_for or b.get("bucket") in sse_for
                      else False),
                "public_access_block": name in pab_true_for
                or str(_tf_value(b.get("bucket"))) in pab_true_for,
                "pab_flags": pab_flags_for.get(
                    name, pab_flags_for.get(
                        str(_tf_value(b.get("bucket"))))),
                "logging": b.child("logging") is not None,
                "versioning": _bool_attr(b.child("versioning"), "enabled"),
            }
        elif t in ("aws_security_group", "aws_security_group_rule",
                   "aws_vpc_security_group_ingress_rule"):
            cr.type = "security_group"
            ingress_cidrs, egress_cidrs = [], []
            if t == "aws_security_group":
                for rule in b.children("ingress"):
                    ingress_cidrs.extend(_cidrs(rule))
                for rule in b.children("egress"):
                    egress_cidrs.extend(_cidrs(rule))
            elif t == "aws_security_group_rule":
                kind = _tf_value(b.get("type"))
                cidrs = _cidrs(b)
                (ingress_cidrs if kind == "ingress"
                 else egress_cidrs).extend(cidrs)
            else:
                v = _tf_value(b.get("cidr_ipv4"))
                if v:
                    ingress_cidrs.append(v)
            cr.attrs = {
                "ingress_cidrs": ingress_cidrs,
                "egress_cidrs": egress_cidrs,
                "description": _tf_value(b.get("description")),
            }
        elif t == "aws_ebs_volume":
            cr.type = "ebs_volume"
            cr.attrs = {"encrypted": _tf_tristate(b, "encrypted", False)}
        elif t == "aws_db_instance":
            cr.type = "rds_instance"
            cr.attrs = {
                "encrypted": _tf_tristate(b, "storage_encrypted", False),
                "public": _tf_tristate(b, "publicly_accessible", False),
            }
        elif t == "aws_instance":
            cr.type = "ec2_instance"
            mo = b.child("metadata_options")
            cr.attrs = {
                "http_tokens": _tf_value(mo.get("http_tokens"))
                if mo else None,
            }
        elif t in ("aws_iam_policy", "aws_iam_role_policy",
                   "aws_iam_user_policy", "aws_iam_group_policy"):
            cr.type = "iam_policy"
            cr.attrs = {"document": _policy_doc(_tf_value(b.get("policy")))}
        elif t == "aws_cloudtrail":
            cr.type = "cloudtrail"
            cr.attrs = {
                "multi_region": _tf_tristate(
                    b, "is_multi_region_trail", False),
                "kms_key": _tf_value(b.get("kms_key_id")),
                "kms_unknown": isinstance(b.get("kms_key_id"), Expr),
                "log_validation": _tf_tristate(
                    b, "enable_log_file_validation", False),
            }
        elif t == "aws_efs_file_system":
            cr.type = "efs"
            cr.attrs = {"encrypted": _tf_tristate(b, "encrypted", False)}
        elif t == "aws_eks_cluster":
            vpc = b.child("vpc_config")
            cr.type = "eks_cluster"
            # absent cidrs -> AWS default 0.0.0.0/0; present but
            # unresolved (variable/expression) -> _tf_value gives None =
            # unknown, so the check stays silent instead of false-positive
            raw_cidrs = vpc.get("public_access_cidrs") if vpc else None
            cidrs = ["0.0.0.0/0"] if raw_cidrs is None \
                else _tf_value(raw_cidrs)
            cr.attrs = {
                "public_access": _tf_tristate(
                    vpc, "endpoint_public_access", True)
                if vpc else True,
                "public_cidrs": cidrs,
            }
        elif t == "aws_sqs_queue":
            cr.type = "sqs_queue"
            cr.attrs = {
                "encrypted": bool(_tf_value(b.get("kms_master_key_id")))
                or _tf_tristate(b, "sqs_managed_sse_enabled", False)
                is True,
                "unknown_enc": isinstance(
                    b.get("kms_master_key_id"), Expr)
                or isinstance(b.get("sqs_managed_sse_enabled"), Expr),
            }
        elif t == "aws_sns_topic":
            cr.type = "sns_topic"
            cr.attrs = {
                "encrypted": bool(_tf_value(b.get("kms_master_key_id"))),
                "unknown_enc": isinstance(
                    b.get("kms_master_key_id"), Expr),
            }
        elif t in ("aws_lb_listener", "aws_alb_listener"):
            cr.type = "lb_listener"
            # an HTTP listener whose default action redirects to HTTPS is
            # the idiomatic force-HTTPS setup and is exempt (reference
            # avd-aws-0054 checks default action redirect protocol)
            redirect_https = False
            for act in b.children("default_action"):
                if _tf_value(act.get("type")) != "redirect":
                    continue
                red = act.child("redirect")
                raw_proto = red.get("protocol") if red else None
                if raw_proto is None:
                    # redirect.protocol defaults to #{protocol}: an HTTP
                    # listener redirecting keeps HTTP — not exempt
                    continue
                proto = _tf_value(raw_proto)
                if proto is None or str(proto).upper() == "HTTPS":
                    redirect_https = True  # unresolved expr = unknown
            cr.attrs = {"protocol": _tf_value(b.get("protocol")),
                        "redirect_https": redirect_https}
        elif t == "aws_cloudfront_distribution":
            # every cache behavior counts (reference adapts
            # ordered_cache_behavior blocks too)
            policies = []
            for cb in (b.children("default_cache_behavior")
                       + b.children("ordered_cache_behavior")):
                policies.append(_tf_value(
                    cb.get("viewer_protocol_policy")))
            cr.type = "cloudfront"
            cr.attrs = {"viewer_protocols": policies}
        else:
            continue
        out.append(cr)
    return out


def _bool_attr(block: Block | None, name: str):
    if block is None:
        return None
    return _tf_value(block.get(name))


def _cidrs(b: Block) -> list[str]:
    vals = b.get("cidr_blocks") or []
    if isinstance(vals, Expr):
        return []
    single = b.get("cidr_block")
    out = [v for v in vals if isinstance(v, str)]
    if isinstance(single, str):
        out.append(single)
    return out


def _policy_doc(policy) -> dict | None:
    if isinstance(policy, str):
        try:
            return json.loads(policy)
        except ValueError:
            return None
    if isinstance(policy, dict):
        return policy
    return None


# ------------------------------------------------------------ cloudformation


def _cfn_tristate(props: dict, key: str, default):
    """CFN boolean attr -> True / False / None(=unknown, stay silent).
    Mirrors _tf_tristate: an unresolved intrinsic must not read as a
    definite failing value."""
    v = props.get(key)
    if v is None:
        return default
    if isinstance(v, dict):
        v = cfn_scalar(v)
        if v is None:
            return None  # Ref / Fn::If etc. → unknown
    if v in (True, "true", "True"):
        return True
    if v in (False, "false", "False"):
        return False
    return None


def adapt_cloudformation(resources: dict[str, dict]) -> list[CloudResource]:
    from trivy_tpu.iac.checks.aws_ext import adapt_cloudformation_aws_ext

    out: list[CloudResource] = []
    out.extend(adapt_cloudformation_aws_ext(resources))
    for name, res in resources.items():
        rtype = str(res.get("Type", ""))
        props = res.get("Properties") or {}
        cr = CloudResource(name=name, start_line=get_line(res),
                           end_line=get_end_line(res))
        if rtype == "AWS::S3::Bucket":
            cr.type = "s3_bucket"
            pab = props.get("PublicAccessBlockConfiguration") or {}
            pab_vals = [cfn_scalar(pab.get(k)) for k in (
                "BlockPublicAcls", "BlockPublicPolicy",
                "IgnorePublicAcls", "RestrictPublicBuckets")]
            pab_flags = {
                snake: cfn_scalar(pab.get(camel)) in (True, "true",
                                                      "True")
                for snake, camel in (
                    ("block_public_acls", "BlockPublicAcls"),
                    ("block_public_policy", "BlockPublicPolicy"),
                    ("ignore_public_acls", "IgnorePublicAcls"),
                    ("restrict_public_buckets",
                     "RestrictPublicBuckets"))
            } if pab else None
            cr.attrs = {
                "acl": cfn_scalar(props.get("AccessControl")),
                "encrypted": bool(props.get("BucketEncryption")),
                "public_access_block": all(
                    v in (True, "true", "True") for v in pab_vals
                ) and bool(pab),
                "pab_flags": pab_flags,
                "logging": bool(props.get("LoggingConfiguration")),
                "versioning": cfn_scalar(
                    (props.get("VersioningConfiguration") or {})
                    .get("Status")) == "Enabled",
            }
        elif rtype == "AWS::EC2::SecurityGroup":
            cr.type = "security_group"
            ingress = props.get("SecurityGroupIngress") or []
            egress = props.get("SecurityGroupEgress") or []
            cr.attrs = {
                "ingress_cidrs": [
                    cfn_scalar(r.get("CidrIp")) for r in ingress
                    if isinstance(r, dict) and cfn_scalar(r.get("CidrIp"))
                ],
                "egress_cidrs": [
                    cfn_scalar(r.get("CidrIp")) for r in egress
                    if isinstance(r, dict) and cfn_scalar(r.get("CidrIp"))
                ],
                "description": cfn_scalar(props.get("GroupDescription")),
            }
        elif rtype == "AWS::EC2::Volume":
            cr.type = "ebs_volume"
            cr.attrs = {
                "encrypted": cfn_scalar(props.get("Encrypted"))
                in (True, "true", "True"),
            }
        elif rtype == "AWS::RDS::DBInstance":
            cr.type = "rds_instance"
            cr.attrs = {
                "encrypted": cfn_scalar(props.get("StorageEncrypted"))
                in (True, "true", "True"),
                "public": cfn_scalar(props.get("PubliclyAccessible"))
                in (True, "true", "True"),
            }
        elif rtype in ("AWS::IAM::Policy", "AWS::IAM::ManagedPolicy"):
            cr.type = "iam_policy"
            cr.attrs = {
                "document": strip_lines(props.get("PolicyDocument"))
                if isinstance(props.get("PolicyDocument"), dict) else None,
            }
        elif rtype == "AWS::CloudTrail::Trail":
            cr.type = "cloudtrail"
            cr.attrs = {
                "multi_region": _cfn_tristate(
                    props, "IsMultiRegionTrail", False),
                "kms_key": cfn_scalar(props.get("KMSKeyId")),
                "kms_unknown": isinstance(props.get("KMSKeyId"), dict),
                "log_validation": _cfn_tristate(
                    props, "EnableLogFileValidation", False),
            }
        elif rtype == "AWS::EFS::FileSystem":
            cr.type = "efs"
            cr.attrs = {
                "encrypted": _cfn_tristate(props, "Encrypted", False),
            }
        elif rtype == "AWS::EKS::Cluster":
            cr.type = "eks_cluster"
            rvc = props.get("ResourcesVpcConfig") or {}
            cidrs_raw = rvc.get("PublicAccessCidrs")
            if cidrs_raw is None:
                cidrs: list | None = ["0.0.0.0/0"]
            elif isinstance(cidrs_raw, dict):
                cidrs = None  # intrinsic → unknown, stay silent
            else:
                cidrs = [cfn_scalar(c) for c in cidrs_raw if cfn_scalar(c)]
            cr.attrs = {
                "public_access": _cfn_tristate(
                    rvc, "EndpointPublicAccess", True),
                "public_cidrs": cidrs,
            }
        elif rtype == "AWS::SQS::Queue":
            cr.type = "sqs_queue"
            cr.attrs = {
                "encrypted": bool(cfn_scalar(props.get("KmsMasterKeyId")))
                or _cfn_tristate(props, "SqsManagedSseEnabled", False)
                is True,
                "unknown_enc": isinstance(props.get("KmsMasterKeyId"), dict)
                or isinstance(props.get("SqsManagedSseEnabled"), dict),
            }
        elif rtype == "AWS::SNS::Topic":
            cr.type = "sns_topic"
            cr.attrs = {
                "encrypted": bool(cfn_scalar(props.get("KmsMasterKeyId"))),
                "unknown_enc": isinstance(props.get("KmsMasterKeyId"),
                                          dict),
            }
        elif rtype == "AWS::ElasticLoadBalancingV2::Listener":
            cr.type = "lb_listener"
            redirect_https = False
            for act in props.get("DefaultActions") or []:
                if not isinstance(act, dict):
                    continue
                if str(cfn_scalar(act.get("Type")) or "").lower() != \
                        "redirect":
                    continue
                raw_proto = (act.get("RedirectConfig") or {}).get(
                    "Protocol")
                if raw_proto is None:
                    continue  # defaults to #{protocol}: not exempt
                proto = cfn_scalar(raw_proto)
                if proto is None or str(proto).upper() == "HTTPS":
                    redirect_https = True  # intrinsic = unknown
            cr.attrs = {"protocol": cfn_scalar(props.get("Protocol")),
                        "redirect_https": redirect_https}
        elif rtype == "AWS::CloudFront::Distribution":
            cr.type = "cloudfront"
            dc = props.get("DistributionConfig") or {}
            policies = []
            dcb = dc.get("DefaultCacheBehavior")
            if isinstance(dcb, dict):
                policies.append(cfn_scalar(dcb.get("ViewerProtocolPolicy")))
            for cb in dc.get("CacheBehaviors") or []:
                if isinstance(cb, dict):
                    policies.append(
                        cfn_scalar(cb.get("ViewerProtocolPolicy")))
            cr.attrs = {"viewer_protocols": policies}
        else:
            continue
        out.append(cr)
    return out


# ------------------------------------------------------------ checks


def _of_type(ctx, t: str) -> list[CloudResource]:
    return [r for r in ctx.cloud_resources if r.type == t]


@check("AVD-AWS-0086", "S3 bucket does not block public ACLs",
       severity="HIGH", file_types=_C, provider="aws", service="s3",
       resolution="Enable blocking any PUT calls with a public ACL")
def s3_public_access(ctx):
    out = []
    for r in _of_type(ctx, "s3_bucket"):
        if not r.attrs.get("public_access_block"):
            out.append(r.cause(
                "No public access block so not blocking public acls"))
    return out


def _s3_pab_flag_check(flag: str, label: str):
    def fn(ctx):
        out = []
        for r in _of_type(ctx, "s3_bucket"):
            flags = r.attrs.get("pab_flags")
            if flags is None:       # no PAB at all -> 0094's finding
                continue
            v = flags.get(flag)
            if v is False:
                out.append(r.cause(
                    f"Public access block does not {label}"))
        return out
    return fn


check("AVD-AWS-0087", "S3 bucket does not block public policies",
      severity="HIGH", file_types=_C, provider="aws", service="s3",
      resolution="Set block_public_policy = true")(
    _s3_pab_flag_check("block_public_policy",
                       "block public bucket policies"))
check("AVD-AWS-0091", "S3 bucket does not ignore public ACLs",
      severity="HIGH", file_types=_C, provider="aws", service="s3",
      resolution="Set ignore_public_acls = true")(
    _s3_pab_flag_check("ignore_public_acls", "ignore public ACLs"))
check("AVD-AWS-0093", "S3 bucket does not restrict public buckets",
      severity="HIGH", file_types=_C, provider="aws", service="s3",
      resolution="Set restrict_public_buckets = true")(
    _s3_pab_flag_check("restrict_public_buckets",
                       "restrict public bucket policies"))


@check("AVD-AWS-0094", "S3 bucket has no public access block",
       severity="LOW", file_types=_C, provider="aws", service="s3",
       resolution="Define an aws_s3_bucket_public_access_block")
def s3_no_pab(ctx):
    out = []
    for r in _of_type(ctx, "s3_bucket"):
        if r.attrs.get("pab_flags") is None \
                and not r.attrs.get("public_access_block"):
            out.append(r.cause(
                "Bucket does not have a public access block"))
    return out


@check("AVD-AWS-0088", "S3 bucket is unencrypted", severity="HIGH",
       file_types=_C, provider="aws", service="s3",
       resolution="Configure bucket encryption")
def s3_encryption(ctx):
    out = []
    for r in _of_type(ctx, "s3_bucket"):
        if not r.attrs.get("encrypted"):
            out.append(r.cause("Bucket does not have encryption enabled"))
    return out


@check("AVD-AWS-0089", "S3 bucket logging is disabled", severity="LOW",
       file_types=_C, provider="aws", service="s3",
       resolution="Add a logging block to the resource")
def s3_logging(ctx):
    out = []
    for r in _of_type(ctx, "s3_bucket"):
        if not r.attrs.get("logging"):
            out.append(r.cause("Bucket does not have logging enabled"))
    return out


@check("AVD-AWS-0090", "S3 bucket versioning is disabled", severity="MEDIUM",
       file_types=_C, provider="aws", service="s3",
       resolution="Enable versioning to protect against accidental "
                  "deletions and overwrites")
def s3_versioning(ctx):
    out = []
    for r in _of_type(ctx, "s3_bucket"):
        if r.attrs.get("versioning") is not True:
            out.append(r.cause("Bucket does not have versioning enabled"))
    return out


@check("AVD-AWS-0092", "S3 bucket uses a public ACL", severity="HIGH",
       file_types=_C, provider="aws", service="s3",
       resolution="Don't use canned ACLs or switch to private acl")
def s3_public_acl(ctx):
    out = []
    for r in _of_type(ctx, "s3_bucket"):
        acl = str(r.attrs.get("acl") or "")
        if acl.lower().replace("_", "-") in (
            "public-read", "public-read-write", "publicread",
            "publicreadwrite", "website",
        ):
            out.append(r.cause(f"Bucket has a public ACL: '{acl}'"))
    return out


_ANYWHERE = ("0.0.0.0/0", "::/0")


@check("AVD-AWS-0107", "Security group rule allows ingress from public "
                       "internet", severity="CRITICAL", file_types=_C,
       provider="aws", service="ec2",
       resolution="Set a more restrictive CIDR range")
def sg_open_ingress(ctx):
    out = []
    for r in _of_type(ctx, "security_group"):
        for cidr in r.attrs.get("ingress_cidrs") or []:
            if cidr in _ANYWHERE:
                out.append(r.cause(
                    f"Security group rule allows ingress from public "
                    f"internet: '{cidr}'"))
    return out


@check("AVD-AWS-0104", "Security group rule allows egress to multiple "
                       "public internet addresses", severity="CRITICAL",
       file_types=_C, provider="aws", service="ec2",
       resolution="Set a more restrictive CIDR range")
def sg_open_egress(ctx):
    out = []
    for r in _of_type(ctx, "security_group"):
        for cidr in r.attrs.get("egress_cidrs") or []:
            if cidr in _ANYWHERE:
                out.append(r.cause(
                    f"Security group rule allows egress to public "
                    f"internet: '{cidr}'"))
    return out


@check("AVD-AWS-0124", "Security group rule does not have a description",
       severity="LOW", file_types=_C, provider="aws", service="ec2",
       resolution="Add descriptions for all security groups rules")
def sg_no_description(ctx):
    out = []
    for r in _of_type(ctx, "security_group"):
        if not r.attrs.get("description"):
            out.append(r.cause(
                "Security group rule does not have a description"))
    return out


@check("AVD-AWS-0026", "EBS volume is unencrypted", severity="HIGH",
       file_types=_C, provider="aws", service="ebs",
       resolution="Enable encryption of EBS volume")
def ebs_encryption(ctx):
    out = []
    for r in _of_type(ctx, "ebs_volume"):
        if r.attrs.get("encrypted") is False:  # None = unknown, stay silent
            out.append(r.cause("EBS volume is not encrypted"))
    return out


@check("AVD-AWS-0080", "RDS instance is unencrypted", severity="HIGH",
       file_types=_C, provider="aws", service="rds",
       resolution="Enable encryption for RDS instance")
def rds_encryption(ctx):
    out = []
    for r in _of_type(ctx, "rds_instance"):
        if r.attrs.get("encrypted") is False:  # None = unknown
            out.append(r.cause(
                "Instance does not have storage encryption enabled"))
    return out


@check("AVD-AWS-0082", "RDS instance is publicly accessible",
       severity="HIGH", file_types=_C, provider="aws", service="rds",
       resolution="Set 'publicly_accessible' to false")
def rds_public(ctx):
    out = []
    for r in _of_type(ctx, "rds_instance"):
        if r.attrs.get("public") is True:  # None = unknown
            out.append(r.cause("Instance is exposed publicly"))
    return out


@check("AVD-AWS-0028", "EC2 instance allows IMDSv1", severity="HIGH",
       file_types=_C, provider="aws", service="ec2",
       resolution="Enable HTTP token requirement for IMDS "
                  "(http_tokens = required)")
def ec2_imdsv1(ctx):
    out = []
    for r in _of_type(ctx, "ec2_instance"):
        tokens = r.attrs.get("http_tokens")
        if tokens is not None and tokens != "required":
            out.append(r.cause(
                "Instance does not require IMDS access to require a "
                "token"))
        elif tokens is None:
            out.append(r.cause(
                "Instance does not configure metadata_options "
                "http_tokens; IMDSv1 is allowed by default"))
    return out


@check("AVD-AWS-0057", "IAM policy allows wildcard actions",
       severity="HIGH", file_types=_C, provider="aws", service="iam",
       resolution="Specify the exact permissions required, and the "
                  "resources they apply to")
def iam_wildcard(ctx):
    out = []
    for r in _of_type(ctx, "iam_policy"):
        doc = r.attrs.get("document")
        if not isinstance(doc, dict):
            continue
        stmts = doc.get("Statement")
        if isinstance(stmts, dict):
            stmts = [stmts]
        for stmt in stmts or []:
            if not isinstance(stmt, dict):
                continue
            if str(stmt.get("Effect", "Allow")) != "Allow":
                continue
            actions = stmt.get("Action")
            actions = [actions] if isinstance(actions, str) else actions
            resources_ = stmt.get("Resource")
            resources_ = [resources_] if isinstance(resources_, str) \
                else resources_
            if any(a == "*" for a in actions or []) and \
                    any(x == "*" for x in resources_ or []):
                out.append(r.cause(
                    "IAM policy document uses wildcarded action and "
                    "resource"))
    return out


# ------------------------------------------------------------ terraform plan


def adapt_terraform_plan(doc: dict) -> list[CloudResource]:
    """tfplan JSON (terraform show -json): planned_values.root_module
    resources carry fully-resolved values, so the mapping mirrors
    adapt_terraform with concrete values and no line info (reference
    pkg/iac/scanners/terraformplan)."""
    out: list[CloudResource] = []
    sse_buckets: set[str] = set()

    # attrs computed at apply time are absent from planned_values;
    # resource_changes' after_unknown marks them so absent-vs-unknown is
    # distinguishable (an unknown encryption key must not read as unset)
    unknowns: dict[str, dict] = {}
    for rc in doc.get("resource_changes") or []:
        au = (rc.get("change") or {}).get("after_unknown")
        if isinstance(au, dict):
            unknowns[str(rc.get("address", ""))] = au

    def collect_sse(mod: dict):
        for res in mod.get("resources") or []:
            if res.get("type") == \
                    "aws_s3_bucket_server_side_encryption_configuration":
                bucket = (res.get("values") or {}).get("bucket")
                if bucket:
                    sse_buckets.add(str(bucket))
        for child in mod.get("child_modules") or []:
            collect_sse(child)

    def walk_module(mod: dict):
        for res in mod.get("resources") or []:
            cr = _plan_resource(
                res, unknowns.get(str(res.get("address", "")), {}))
            if cr is not None:
                if cr.type == "s3_bucket" and \
                        str(cr.attrs.get("bucket_name") or "") in sse_buckets:
                    cr.attrs["encrypted"] = True
                out.append(cr)
        for child in mod.get("child_modules") or []:
            walk_module(child)

    planned = doc.get("planned_values") or {}
    collect_sse(planned.get("root_module") or {})
    walk_module(planned.get("root_module") or {})
    plan_apply_public_access_blocks(doc, out)
    return out


def _plan_resource(res: dict,
                   unknown: dict | None = None) -> CloudResource | None:
    t = str(res.get("type", ""))
    vals = res.get("values") or {}
    unknown = unknown or {}
    cr = CloudResource(name=str(res.get("address", "")))
    if t == "aws_s3_bucket":
        sse = vals.get("server_side_encryption_configuration")
        cr.type = "s3_bucket"
        cr.attrs = {
            "acl": vals.get("acl"),
            "bucket_name": vals.get("bucket"),
            "encrypted": bool(sse),
            "public_access_block": False,  # separate resource; see below
            "logging": bool(vals.get("logging")),
            "versioning": bool(
                (vals.get("versioning") or [{}])[0].get("enabled")
                if isinstance(vals.get("versioning"), list)
                else (vals.get("versioning") or {}).get("enabled")),
        }
    elif t in ("aws_security_group", "aws_security_group_rule",
               "aws_vpc_security_group_ingress_rule"):
        cr.type = "security_group"
        ingress_cidrs, egress_cidrs = [], []
        if t == "aws_security_group":
            for rule in vals.get("ingress") or []:
                ingress_cidrs.extend(rule.get("cidr_blocks") or [])
            for rule in vals.get("egress") or []:
                egress_cidrs.extend(rule.get("cidr_blocks") or [])
        elif t == "aws_security_group_rule":
            cidrs = vals.get("cidr_blocks") or []
            (ingress_cidrs if vals.get("type") == "ingress"
             else egress_cidrs).extend(cidrs)
        else:
            v = vals.get("cidr_ipv4")
            if v:
                ingress_cidrs.append(v)
        cr.attrs = {
            "ingress_cidrs": ingress_cidrs,
            "egress_cidrs": egress_cidrs,
            "description": vals.get("description"),
        }
    elif t == "aws_ebs_volume":
        cr.type = "ebs_volume"
        cr.attrs = {"encrypted": bool(vals.get("encrypted"))}
    elif t == "aws_db_instance":
        cr.type = "rds_instance"
        cr.attrs = {
            "encrypted": bool(vals.get("storage_encrypted")),
            "public": bool(vals.get("publicly_accessible")),
        }
    elif t == "aws_instance":
        cr.type = "ec2_instance"
        mo = vals.get("metadata_options")
        mo = mo[0] if isinstance(mo, list) and mo else (mo or {})
        cr.attrs = {"http_tokens": mo.get("http_tokens")}
    elif t in ("aws_iam_policy", "aws_iam_role_policy",
               "aws_iam_user_policy", "aws_iam_group_policy"):
        cr.type = "iam_policy"
        cr.attrs = {"document": _policy_doc(vals.get("policy"))}
    elif t == "aws_cloudtrail":
        cr.type = "cloudtrail"
        cr.attrs = {
            "multi_region": bool(vals.get("is_multi_region_trail")),
            "kms_key": vals.get("kms_key_id"),
            # a key created in the same apply is unknown at plan time
            # (marked in after_unknown, absent from planned values)
            "kms_unknown": bool(unknown.get("kms_key_id")),
            "log_validation": bool(vals.get("enable_log_file_validation")),
        }
    elif t == "aws_efs_file_system":
        cr.type = "efs"
        enc = vals.get("encrypted")
        cr.attrs = {"encrypted": None if unknown.get("encrypted")
                    else bool(enc)}
    elif t == "aws_eks_cluster":
        cr.type = "eks_cluster"
        vpcs = vals.get("vpc_config")
        vpc = vpcs[0] if isinstance(vpcs, list) and vpcs else (
            vpcs if isinstance(vpcs, dict) else {})
        vu = unknown.get("vpc_config")
        vu = vu[0] if isinstance(vu, list) and vu else (
            vu if isinstance(vu, dict) else {})
        pub = vpc.get("endpoint_public_access")
        cidrs = vpc.get("public_access_cidrs")
        if cidrs is None:
            cidrs_attr = None if vu.get("public_access_cidrs") \
                else ["0.0.0.0/0"]
        else:
            cidrs_attr = [c for c in cidrs if isinstance(c, str)]
        cr.attrs = {
            "public_access": True if pub is None else bool(pub),
            "public_cidrs": cidrs_attr,
        }
    elif t == "aws_sqs_queue":
        cr.type = "sqs_queue"
        cr.attrs = {
            "encrypted": bool(vals.get("kms_master_key_id"))
            or bool(vals.get("sqs_managed_sse_enabled")),
            "unknown_enc": bool(unknown.get("kms_master_key_id")
                                or unknown.get("sqs_managed_sse_enabled")),
        }
    elif t == "aws_sns_topic":
        cr.type = "sns_topic"
        cr.attrs = {
            "encrypted": bool(vals.get("kms_master_key_id")),
            "unknown_enc": bool(unknown.get("kms_master_key_id")),
        }
    elif t in ("aws_lb_listener", "aws_alb_listener"):
        cr.type = "lb_listener"

        def _first_block(v):
            if isinstance(v, list) and v:
                return v[0] if isinstance(v[0], dict) else {}
            return v if isinstance(v, dict) else {}

        redirect_https = False
        acts = vals.get("default_action") or []
        # a wholly-unknown attribute encodes as the literal `true` in
        # after_unknown, not a mirrored list
        unk_acts = unknown.get("default_action")
        if not isinstance(unk_acts, list):
            unk_acts = []
        for i, act in enumerate(acts):
            if not isinstance(act, dict) or act.get("type") != "redirect":
                continue
            red = _first_block(act.get("redirect"))
            proto = red.get("protocol")
            if proto is None:
                # computed at apply time -> unknown -> exempt (matches
                # the HCL/CFN unknown handling); truly absent defaults
                # to #{protocol} (scheme kept) -> not exempt
                unk_act = unk_acts[i] if i < len(unk_acts) and \
                    isinstance(unk_acts[i], dict) else {}
                unk_red = _first_block(unk_act.get("redirect"))
                if unk_red.get("protocol"):
                    redirect_https = True
            elif str(proto).upper() == "HTTPS":
                redirect_https = True
        cr.attrs = {"protocol": vals.get("protocol"),
                    "redirect_https": redirect_https}
    elif t == "aws_cloudfront_distribution":
        cr.type = "cloudfront"
        policies = []
        for key in ("default_cache_behavior", "ordered_cache_behavior"):
            v = vals.get(key)
            items = v if isinstance(v, list) else (
                [v] if isinstance(v, dict) else [])
            for cb in items:
                if isinstance(cb, dict):
                    policies.append(cb.get("viewer_protocol_policy"))
        cr.attrs = {"viewer_protocols": policies}
    else:
        return None
    return cr


def plan_apply_public_access_blocks(doc: dict,
                                    resources: list[CloudResource]) -> None:
    """aws_s3_bucket_public_access_block resources in the plan mark their
    bucket as protected (mirrors the companion-resource handling in
    adapt_terraform)."""
    protected: set[str] = set()

    def walk(mod: dict):
        for res in mod.get("resources") or []:
            if res.get("type") == "aws_s3_bucket_public_access_block":
                vals = res.get("values") or {}
                bucket = vals.get("bucket")
                if bucket and all(vals.get(k) for k in (
                        "block_public_acls", "block_public_policy",
                        "ignore_public_acls", "restrict_public_buckets")):
                    protected.add(str(bucket))
        for child in mod.get("child_modules") or []:
            walk(child)

    walk((doc.get("planned_values") or {}).get("root_module") or {})
    if not protected:
        return
    for cr in resources:
        if cr.type == "s3_bucket" and \
                str(cr.attrs.get("bucket_name") or "") in protected:
            cr.attrs["public_access_block"] = True


@check("AVD-AWS-0014", "CloudTrail is not a multi-region trail",
       severity="MEDIUM", file_types=_C, provider="aws",
       service="cloudtrail", resolution="Enable is_multi_region_trail")
def cloudtrail_multi_region(ctx):
    out = []
    for r in _of_type(ctx, "cloudtrail"):
        if r.attrs.get("multi_region") is False:
            out.append(r.cause("Trail is not a multi-region trail"))
    return out


@check("AVD-AWS-0015", "CloudTrail is not encrypted with a customer key",
       severity="HIGH", file_types=_C, provider="aws",
       service="cloudtrail", resolution="Set kms_key_id")
def cloudtrail_encryption(ctx):
    out = []
    for r in _of_type(ctx, "cloudtrail"):
        # kms_key_id = aws_kms_key.x.arn is the idiomatic form: an
        # unresolved reference means a key IS configured — stay silent
        if not r.attrs.get("kms_key") and not r.attrs.get("kms_unknown"):
            out.append(r.cause("Trail is not encrypted with a CMK"))
    return out


@check("AVD-AWS-0016", "CloudTrail log file validation is disabled",
       severity="HIGH", file_types=_C, provider="aws",
       service="cloudtrail", resolution="Enable log file validation")
def cloudtrail_validation(ctx):
    out = []
    for r in _of_type(ctx, "cloudtrail"):
        if r.attrs.get("log_validation") is False:
            out.append(r.cause("Trail does not have log validation "
                               "enabled"))
    return out


@check("AVD-AWS-0037", "EFS file system is unencrypted", severity="HIGH",
       file_types=_C, provider="aws", service="efs",
       resolution="Enable encryption for the file system")
def efs_encryption(ctx):
    out = []
    for r in _of_type(ctx, "efs"):
        if r.attrs.get("encrypted") is False:
            out.append(r.cause("File system is not encrypted"))
    return out


@check("AVD-AWS-0040", "EKS cluster endpoint is publicly accessible",
       severity="CRITICAL", file_types=_C, provider="aws", service="eks",
       resolution="Disable endpoint_public_access or restrict "
                  "public_access_cidrs")
def eks_public_endpoint(ctx):
    out = []
    for r in _of_type(ctx, "eks_cluster"):
        if r.attrs.get("public_access") is True and \
                "0.0.0.0/0" in (r.attrs.get("public_cidrs") or []):
            out.append(r.cause(
                "Cluster endpoint is publicly accessible from anywhere"))
    return out


@check("AVD-AWS-0096", "SQS queue is unencrypted", severity="HIGH",
       file_types=_C, provider="aws", service="sqs",
       resolution="Enable server-side encryption for the queue")
def sqs_encryption(ctx):
    out = []
    for r in _of_type(ctx, "sqs_queue"):
        if not r.attrs.get("encrypted") and not r.attrs.get("unknown_enc"):
            out.append(r.cause("Queue is not encrypted"))
    return out


@check("AVD-AWS-0095", "SNS topic is unencrypted", severity="HIGH",
       file_types=_C, provider="aws", service="sns",
       resolution="Set kms_master_key_id on the topic")
def sns_encryption(ctx):
    out = []
    for r in _of_type(ctx, "sns_topic"):
        if not r.attrs.get("encrypted") and not r.attrs.get("unknown_enc"):
            out.append(r.cause("Topic does not have encryption enabled"))
    return out


@check("AVD-AWS-0054", "Load balancer listener uses plain HTTP",
       severity="CRITICAL", file_types=_C, provider="aws", service="elb",
       resolution="Switch the listener to HTTPS/TLS")
def lb_plain_http(ctx):
    out = []
    for r in _of_type(ctx, "lb_listener"):
        if str(r.attrs.get("protocol") or "").upper() == "HTTP" \
                and not r.attrs.get("redirect_https"):
            out.append(r.cause("Listener uses plain HTTP"))
    return out


@check("AVD-AWS-0012", "CloudFront distribution allows unencrypted "
                       "viewer traffic", severity="HIGH", file_types=_C,
       provider="aws", service="cloudfront",
       resolution="Set viewer_protocol_policy to redirect-to-https or "
                  "https-only")
def cloudfront_viewer_policy(ctx):
    out = []
    for r in _of_type(ctx, "cloudfront"):
        if any(str(p or "") == "allow-all"
               for p in r.attrs.get("viewer_protocols") or []):
            out.append(r.cause(
                "Distribution allows unencrypted communications"))
    return out
