"""Shared helpers for the declarative (spec-table) check modules
(aws_ext / azure_ext / gcp_ext): unresolved-value tristate extraction
and the spec -> Check registration loop. One copy, so the
None-means-unknown semantics cannot drift between providers."""

from __future__ import annotations

from trivy_tpu.iac.check import Cause, check
from trivy_tpu.iac.parsers.hcl import Block, Expr


def tf_value(x):
    """Resolved terraform value or None for unresolved expressions."""
    return None if isinstance(x, Expr) else x


def tri(b: Block | None, name: str, absent_default):
    """Attribute absent -> the provider default (a definite value);
    present but unresolved -> None = unknown (checks stay silent)."""
    if b is None or name not in b.attrs:
        return absent_default
    return tf_value(b.attrs[name].value)


def fail_if(attr, bad_values, message):
    def test(a):
        v = a.get(attr)
        if v is None:
            return None
        return message if v in bad_values else False
    return test


def fail_unless(attr, good_values, message):
    def test(a):
        v = a.get(attr)
        if v is None:
            return None
        return False if v in good_values else message
    return test


def lt(attr, minimum, message):
    """Fail when the numeric attr is below `minimum`. Tolerates
    string-typed numbers (CFN accepts quoted integers); a value that
    cannot be read as a number is unknown, not failing."""
    def test(a):
        v = a.get(attr)
        if v is None:
            return None
        if isinstance(v, bool):
            return None
        if isinstance(v, str):
            try:
                v = float(v)
            except ValueError:
                return None
        if not isinstance(v, (int, float)):
            return None
        return message if v < minimum else False
    return test


def register_specs(specs, *, provider: str, file_types) -> None:
    """(id, title, severity, rtype(s), service, test, resolution)
    entries -> registered Checks walking ctx.cloud_resources."""
    for cid, title, sev, rtype, service, test, resolution in specs:
        rtypes = rtype if isinstance(rtype, tuple) else (rtype,)

        def fn(ctx, _rtypes=rtypes, _test=test):
            causes = []
            for r in ctx.cloud_resources:
                if r.type not in _rtypes:
                    continue
                msg = _test(r.attrs)
                if msg:
                    causes.append(Cause(
                        message=msg, resource=r.name,
                        start_line=r.start_line, end_line=r.end_line))
            return causes

        check(cid, title, severity=sev, file_types=file_types,
              provider=provider, service=service,
              resolution=resolution)(fn)
