"""Google Cloud terraform checks (reference pkg/iac/adapters/terraform/
google + pkg/iac/providers/google rule set, re-expressed over the
CloudResource layer)."""

from __future__ import annotations

from trivy_tpu.iac.check import check
from trivy_tpu.iac.parsers.hcl import Expr
from trivy_tpu.iac.checks.cloud import (
    CloudResource,
    _tf_tristate,
    _tf_value as _tf_val,
)

_C = ("terraform", "terraformplan")


def adapt_terraform_gcp(blocks) -> list[CloudResource]:
    """google_* terraform resources -> typed CloudResources."""
    out: list[CloudResource] = []
    for b in blocks:
        if b.type != "resource" or len(b.labels) < 2:
            continue
        t = b.labels[0]
        if not t.startswith("google_"):
            continue
        cr = CloudResource(
            name=f"{t}.{b.labels[1]}",
            start_line=b.start_line, end_line=b.end_line)
        if t == "google_storage_bucket":
            cr.type = "gcs_bucket"
            # absent -> provider default; unresolved -> None = unknown,
            # and unknowns never fail a check (cloud.py _tf_tristate)
            cr.attrs = {
                "uniform_access": _tf_tristate(
                    b, "uniform_bucket_level_access", False),
                "public_prevention": _tf_val(
                    b.get("public_access_prevention")),
            }
        elif t == "google_storage_bucket_iam_member":
            cr.type = "gcs_iam_member"
            cr.attrs = {"member": _tf_val(b.get("member"))}
        elif t == "google_compute_firewall":
            allows = []
            for a in b.children("allow"):
                allows.append({
                    "protocol": _tf_val(a.get("protocol")),
                    "ports": _tf_val(a.get("ports")) or [],
                })
            cr.type = "gcp_firewall"
            cr.attrs = {
                "source_ranges": _tf_val(b.get("source_ranges")) or [],
                "allows": allows,
            }
        elif t == "google_sql_database_instance":
            settings = b.child("settings")
            ip_cfg = settings.child("ip_configuration") if settings \
                else None
            cr.type = "gcp_sql"
            cr.attrs = {
                "public_ip": _tf_tristate(ip_cfg, "ipv4_enabled", True)
                if ip_cfg else True,  # provider default is enabled
                "require_ssl": _tf_tristate(ip_cfg, "require_ssl", False)
                if ip_cfg else False,
            }
        elif t == "google_container_cluster":
            cr.type = "gke_cluster"
            private = b.child("private_cluster_config")
            np_block = b.child("network_policy")
            cr.attrs = {
                "legacy_abac": _tf_tristate(
                    b, "enable_legacy_abac", False),
                "private_nodes": _tf_tristate(
                    private, "enable_private_nodes", False)
                if private else False,
                # the provider defaults network_policy.enabled to FALSE
                # even when the block is present (reference gke adapt.go)
                "network_policy": _tf_tristate(np_block, "enabled", False)
                if np_block else False,
                "datapath": _tf_val(b.get("datapath_provider")),
                "datapath_unresolved": isinstance(
                    b.get("datapath_provider"), Expr),
            }
        elif t == "google_compute_instance":
            cr.type = "gcp_instance"
            shielded = b.child("shielded_instance_config")
            cr.attrs = {
                "serial_port": any(
                    str(_tf_val(m.get("key"))) == "serial-port-enable"
                    for m in b.children("metadata")
                ) or (isinstance(_tf_val(b.get("metadata")), dict)
                      and str(_tf_val(b.get("metadata")).get(
                          "serial-port-enable", "")).lower()
                      in ("true", "1")),
                "shielded_vm": shielded is not None,
            }
        else:
            continue
        out.append(cr)
    return out


def _of_type(ctx, t):
    return [r for r in ctx.cloud_resources if r.type == t]


@check("AVD-GCP-0001", "Storage bucket is publicly accessible",
       severity="HIGH", file_types=_C, provider="google", service="storage",
       resolution="Restrict public access to the bucket")
def gcs_public_member(ctx):
    out = []
    for r in _of_type(ctx, "gcs_iam_member"):
        if str(r.attrs.get("member")) in ("allUsers",
                                          "allAuthenticatedUsers"):
            out.append(r.cause(
                f"Bucket is granted to '{r.attrs['member']}'"))
    return out


@check("AVD-GCP-0002", "Storage bucket does not use uniform bucket-level "
                       "access", severity="MEDIUM", file_types=_C,
       provider="google", service="storage",
       resolution="Enable uniform_bucket_level_access")
def gcs_uniform_access(ctx):
    out = []
    for r in _of_type(ctx, "gcs_bucket"):
        if r.attrs.get("uniform_access") is False:
            out.append(r.cause(
                "Bucket has uniform bucket level access disabled"))
    return out


@check("AVD-GCP-0027", "Compute firewall allows ingress from the public "
                       "internet", severity="CRITICAL", file_types=_C,
       provider="google", service="compute",
       resolution="Restrict source ranges")
def gcp_firewall_open(ctx):
    out = []
    for r in _of_type(ctx, "gcp_firewall"):
        for cidr in r.attrs.get("source_ranges") or []:
            if str(cidr) in ("0.0.0.0/0", "::/0"):
                out.append(r.cause(
                    f"Firewall allows ingress from '{cidr}'"))
    return out


@check("AVD-GCP-0017", "Cloud SQL instance has a public IP address",
       severity="HIGH", file_types=_C, provider="google", service="sql",
       resolution="Disable ipv4_enabled or restrict authorized networks")
def gcp_sql_public_ip(ctx):
    out = []
    for r in _of_type(ctx, "gcp_sql"):
        if r.attrs.get("public_ip") is True:
            out.append(r.cause("Database instance is granted a public IP"))
    return out


@check("AVD-GCP-0015", "Cloud SQL instance does not require TLS",
       severity="HIGH", file_types=_C, provider="google", service="sql",
       resolution="Set ip_configuration.require_ssl")
def gcp_sql_tls(ctx):
    out = []
    for r in _of_type(ctx, "gcp_sql"):
        if r.attrs.get("require_ssl") is False:
            out.append(r.cause(
                "Database instance does not require TLS for connections"))
    return out


@check("AVD-GCP-0064", "GKE cluster uses legacy ABAC authorization",
       severity="HIGH", file_types=_C, provider="google", service="gke",
       resolution="Disable enable_legacy_abac")
def gke_legacy_abac(ctx):
    out = []
    for r in _of_type(ctx, "gke_cluster"):
        if r.attrs.get("legacy_abac") in (True, "true"):
            out.append(r.cause("Cluster has legacy ABAC enabled"))
    return out


@check("AVD-GCP-0059", "GKE cluster nodes are not private",
       severity="MEDIUM", file_types=_C, provider="google", service="gke",
       resolution="Enable private_cluster_config.enable_private_nodes")
def gke_private_nodes(ctx):
    out = []
    for r in _of_type(ctx, "gke_cluster"):
        if r.attrs.get("private_nodes") is False:
            out.append(r.cause("Cluster does not have private nodes"))
    return out


@check("AVD-GCP-0061", "GKE cluster has no network policy", severity="MEDIUM",
       file_types=_C, provider="google", service="gke",
       resolution="Enable a network policy (or dataplane v2)")
def gke_network_policy(ctx):
    out = []
    for r in _of_type(ctx, "gke_cluster"):
        # dataplane v2 enforces network policy without the block; an
        # unresolved datapath_provider stays silent (unknown)
        if str(r.attrs.get("datapath") or "") == "ADVANCED_DATAPATH":
            continue
        if r.attrs.get("datapath_unresolved"):
            continue
        if r.attrs.get("network_policy") is False:
            out.append(r.cause("Cluster does not have a network policy"))
    return out


@check("AVD-GCP-0032", "Compute instance has serial port enabled",
       severity="MEDIUM", file_types=_C, provider="google",
       service="compute", resolution="Disable serial-port-enable metadata")
def gcp_serial_port(ctx):
    out = []
    for r in _of_type(ctx, "gcp_instance"):
        if r.attrs.get("serial_port"):
            out.append(r.cause("Instance has serial port enabled"))
    return out
