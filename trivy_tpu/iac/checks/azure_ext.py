"""Azure check breadth: the azurerm terraform surface plus new service
families (reference pkg/iac/providers/azure/{appservice,container,
database,keyvault,monitor,network,securitycenter,storage,synapse,
datafactory}/ and pkg/iac/adapters/terraform/azure/*/adapt.go).

Same declarative layout as aws_ext: terraform adapters normalize
azurerm_* blocks into CloudResource attrs (None = unknown -> silent),
one Check per AVD rule, IDs/severities following the public AVD
registry (avd.aquasec.com/misconfig/azure). The ARM adapter
(checks/azure.py adapt_arm) emits the same resource types for the
storage/sql/vm shapes it covers, so these checks run on both inputs."""

from __future__ import annotations

from trivy_tpu.iac.checks.spec import (
    fail_if as _fail_if,
    lt as _lt,
    register_specs,
    tf_value as _v,
    tri as _tri,
)
from trivy_tpu.iac.parsers.hcl import Block

_C = ("terraform", "terraformplan", "azure-arm")


def adapt_terraform_azure(blocks: list[Block]) -> list:
    from trivy_tpu.iac.checks.cloud import CloudResource

    out = []
    for b in blocks:
        if b.type != "resource" or len(b.labels) < 2:
            continue
        fn = _TF.get(b.labels[0])
        if fn is None:
            continue
        rtype, attrs = fn(b)
        out.append(CloudResource(
            type=rtype, name=f"{b.labels[0]}.{b.labels[1]}",
            attrs=attrs, start_line=b.start_line, end_line=b.end_line))
    return out


def _tf_storage_account(b):
    rules = b.child("network_rules")
    queue_logging = False
    qp = b.child("queue_properties")
    if qp is not None:
        lg = qp.child("logging")
        if lg is not None:
            queue_logging = all(
                _tri(lg, k, False) is True
                for k in ("delete", "read", "write"))
    return "storage_account", {
        "https_only": _tri(b, "enable_https_traffic_only",
                           _tri(b, "https_traffic_only_enabled", True)),
        "min_tls": _tri(b, "min_tls_version", "TLS1_2"),
        "public_blob_access": _tri(b, "allow_blob_public_access",
                                   _tri(b,
                                        "allow_nested_items_to_be_public",
                                        True)),
        "network_default_deny": (_tri(rules, "default_action", None)
                                 in ("Deny", "deny"))
        if rules is not None else False,
        "queue_logging": queue_logging,
    }


def _tf_app_service(b):
    site = b.child("site_config")
    auth = b.child("auth_settings")
    identity = b.child("identity")
    return "app_service", {
        "https_only": _tri(b, "https_only", False),
        "min_tls": _tri(site, "min_tls_version", "1.2")
        if site else "1.2",
        "http2": _tri(site, "http2_enabled", False) if site else False,
        "client_cert": _tri(b, "client_cert_enabled", False),
        "auth_enabled": _tri(auth, "enabled", False)
        if auth else False,
        "identity": identity is not None,
    }


def _tf_aks(b):
    rbac = b.child("role_based_access_control")
    np = _v(b.get("network_profile.network_policy")) \
        if "network_profile.network_policy" in b.attrs else None
    net = b.child("network_profile")
    oms = None
    addons = b.child("addon_profile")
    if addons is not None:
        agent = addons.child("oms_agent")
        oms = _tri(agent, "enabled", False) if agent else False
    if oms is None:
        oms = b.child("oms_agent") is not None
    api = b.child("api_server_access_profile")
    ranges = _v(b.get("api_server_authorized_ip_ranges"))
    if ranges is None and api is not None:
        ranges = _v(api.get("authorized_ip_ranges"))
    return "aks_cluster", {
        "rbac": _tri(rbac, "enabled", False) if rbac is not None
        else _tri(b, "role_based_access_control_enabled", True),
        "network_policy": bool(_tri(net, "network_policy", None))
        if net is not None else (bool(np) if np else False),
        "logging": bool(oms),
        "authorized_ranges": bool(ranges),
    }


def _tf_postgresql_server(b):
    return "pg_server", {
        "ssl": _tri(b, "ssl_enforcement_enabled", False),
        "min_tls": _tri(b, "ssl_minimal_tls_version_enforced",
                        "TLSEnforcementDisabled"),
        "public": _tri(b, "public_network_access_enabled", True),
    }


def _tf_pg_config(b):
    return "pg_config", {
        "name": _v(b.get("name")),
        "value": _v(b.get("value")),
    }


def _tf_mysql_server(b):
    return "mysql_server", {
        "ssl": _tri(b, "ssl_enforcement_enabled", False),
        "min_tls": _tri(b, "ssl_minimal_tls_version_enforced",
                        "TLSEnforcementDisabled"),
        "public": _tri(b, "public_network_access_enabled", True),
    }


def _tf_mssql_server(b):
    return "mssql_server", {
        "min_tls": _tri(b, "minimum_tls_version", None),
        "public": _tri(b, "public_network_access_enabled", True),
    }


def _tf_mssql_auditing(b):
    return "mssql_auditing", {
        "retention": _tri(b, "retention_in_days", 0),
    }


def _tf_mssql_alert(b):
    return "mssql_alert", {
        "disabled_alerts": _v(b.get("disabled_alerts")) or [],
        "email_account_admins": _tri(b, "email_account_admins", False),
    }


def _tf_keyvault(b):
    acls = b.child("network_acls")
    return "key_vault", {
        "purge_protection": _tri(b, "purge_protection_enabled", False),
        "network_default_deny": (_tri(acls, "default_action", None)
                                 in ("Deny", "deny"))
        if acls is not None else False,
    }


def _tf_keyvault_secret(b):
    return "key_vault_secret", {
        "expiry": bool(_v(b.get("expiration_date"))),
        "content_type": bool(_v(b.get("content_type"))),
    }


def _tf_keyvault_key(b):
    return "key_vault_key", {
        "expiry": bool(_v(b.get("expiration_date"))),
    }


def _tf_monitor_log_profile(b):
    ret = b.child("retention_policy")
    return "monitor_log_profile", {
        "retention_enabled": _tri(ret, "enabled", False)
        if ret else False,
        "retention_days": _tri(ret, "days", 0) if ret else 0,
        "categories": _v(b.get("categories")) or [],
        "locations": _v(b.get("locations")) or [],
    }


def _tf_nsg_rule(b):
    return "nsg_rule", {
        "direction": _v(b.get("direction")),
        "access": _v(b.get("access")),
        "port_range": str(_v(b.get("destination_port_range")) or ""),
        "source": _v(b.get("source_address_prefix")),
    }


def _tf_security_contact(b):
    return "security_center_contact", {
        "phone": bool(_v(b.get("phone"))),
    }


def _tf_security_pricing(b):
    return "security_center_pricing", {
        "tier": _v(b.get("tier")),
    }


def _tf_synapse(b):
    return "synapse_workspace", {
        "managed_vnet": _tri(b, "managed_virtual_network_enabled",
                             False),
    }


def _tf_data_factory(b):
    return "data_factory", {
        "public": _tri(b, "public_network_enabled", True),
    }


def _tf_managed_disk(b):
    enc = b.child("encryption_settings")
    return "managed_disk", {
        "encryption_disabled": (_tri(enc, "enabled", True) is False)
        if enc is not None else False,
    }


def _tf_redis_cache(b):
    return "redis_cache", {
        "non_ssl_port": _tri(b, "enable_non_ssl_port", False),
    }


def _tf_datalake_store(b):
    return "data_lake_store", {
        "encrypted": _tri(b, "encryption_state", "Enabled"),
    }


_TF = {
    "azurerm_storage_account": _tf_storage_account,
    "azurerm_app_service": _tf_app_service,
    "azurerm_linux_web_app": _tf_app_service,
    "azurerm_windows_web_app": _tf_app_service,
    "azurerm_kubernetes_cluster": _tf_aks,
    "azurerm_postgresql_server": _tf_postgresql_server,
    "azurerm_postgresql_configuration": _tf_pg_config,
    "azurerm_mysql_server": _tf_mysql_server,
    "azurerm_mssql_server": _tf_mssql_server,
    "azurerm_mssql_server_extended_auditing_policy": _tf_mssql_auditing,
    "azurerm_mssql_server_security_alert_policy": _tf_mssql_alert,
    "azurerm_key_vault": _tf_keyvault,
    "azurerm_key_vault_secret": _tf_keyvault_secret,
    "azurerm_key_vault_key": _tf_keyvault_key,
    "azurerm_monitor_log_profile": _tf_monitor_log_profile,
    "azurerm_network_security_rule": _tf_nsg_rule,
    "azurerm_security_center_contact": _tf_security_contact,
    "azurerm_security_center_subscription_pricing":
        _tf_security_pricing,
    "azurerm_synapse_workspace": _tf_synapse,
    "azurerm_data_factory": _tf_data_factory,
    "azurerm_data_lake_store": _tf_datalake_store,
    "azurerm_managed_disk": _tf_managed_disk,
    "azurerm_redis_cache": _tf_redis_cache,
}


def _nsg_internet_rule(port):
    def test(a):
        if a.get("direction") is None or a.get("access") is None:
            return None
        if str(a["direction"]).lower() != "inbound" or \
                str(a["access"]).lower() != "allow":
            return False
        src = a.get("source")
        if src is None:
            return None
        if str(src) not in ("*", "0.0.0.0/0", "Internet", "any",
                            "::/0"):
            return False
        pr = a.get("port_range")
        if pr == "*" or pr == str(port):
            return f"Port {port} is exposed to the internet"
        if "-" in pr:
            try:
                lo, hi = pr.split("-")
                if int(lo) <= port <= int(hi):
                    return f"Port {port} is exposed to the internet"
            except ValueError:
                return False
        return False
    return test


SPECS = [
    # --- storage
    ("AVD-AZU-0012", "Storage account network rules do not deny by "
     "default", "MEDIUM", "storage_account", "storage",
     _fail_if("network_default_deny", (False,),
              "Default network action is not Deny"),
     "Set network_rules default_action = Deny"),
    ("AVD-AZU-0009", "Storage queue services logging is disabled",
     "MEDIUM", "storage_account", "storage",
     _fail_if("queue_logging", (False,),
              "Queue logging is not enabled for read/write/delete"),
     "Enable queue_properties logging"),
    # --- app service
    ("AVD-AZU-0001", "App Service does not enforce HTTPS", "HIGH",
     "app_service", "appservice",
     _fail_if("https_only", (False,), "https_only is not enabled"),
     "Set https_only = true"),
    ("AVD-AZU-0005", "App Service uses an outdated minimum TLS",
     "HIGH", "app_service", "appservice",
     _fail_if("min_tls", ("1.0", "1.1"),
              "Minimum TLS version is below 1.2"),
     "Set site_config min_tls_version = 1.2"),
    ("AVD-AZU-0003", "App Service HTTP/2 is disabled", "LOW",
     "app_service", "appservice",
     _fail_if("http2", (False,), "HTTP/2 is not enabled"),
     "Set site_config http2_enabled = true"),
    ("AVD-AZU-0004", "App Service does not require client "
     "certificates", "LOW", "app_service", "appservice",
     _fail_if("client_cert", (False,),
              "Client certificates are not required"),
     "Set client_cert_enabled = true"),
    ("AVD-AZU-0002", "App Service authentication is disabled",
     "MEDIUM", "app_service", "appservice",
     _fail_if("auth_enabled", (False,),
              "Built-in authentication is not enabled"),
     "Enable auth_settings"),
    ("AVD-AZU-0006", "App Service has no managed identity", "LOW",
     "app_service", "appservice",
     _fail_if("identity", (False,),
              "No managed identity is registered"),
     "Add an identity block"),
    # --- AKS
    ("AVD-AZU-0042", "AKS cluster RBAC is disabled", "HIGH",
     "aks_cluster", "container",
     _fail_if("rbac", (False,), "RBAC is not enabled"),
     "Enable role_based_access_control"),
    ("AVD-AZU-0043", "AKS cluster has no network policy", "MEDIUM",
     "aks_cluster", "container",
     _fail_if("network_policy", (False,),
              "No network policy is configured"),
     "Set network_profile.network_policy"),
    ("AVD-AZU-0040", "AKS cluster monitoring is disabled", "MEDIUM",
     "aks_cluster", "container",
     _fail_if("logging", (False,),
              "The OMS agent addon is not enabled"),
     "Enable the oms_agent addon"),
    ("AVD-AZU-0041", "AKS API server allows all networks", "CRITICAL",
     "aks_cluster", "container",
     _fail_if("authorized_ranges", (False,),
              "No authorized IP ranges are configured"),
     "Set api_server_authorized_ip_ranges"),
    # --- databases
    ("AVD-AZU-0018", "PostgreSQL server does not enforce SSL", "HIGH",
     "pg_server", "database",
     _fail_if("ssl", (False,), "SSL enforcement is disabled"),
     "Set ssl_enforcement_enabled = true"),
    ("AVD-AZU-0028", "Database server allows pre-TLS1.2 connections",
     "HIGH", ("pg_server", "mysql_server", "mssql_server"), "database",
     _fail_if("min_tls", ("TLS1_0", "TLS1_1", "1.0", "1.1",
                          "TLSEnforcementDisabled"),
              "Minimum TLS version allows outdated protocols"),
     "Enforce TLS1_2"),
    ("AVD-AZU-0020", "PostgreSQL connection throttling is disabled",
     "MEDIUM", "pg_config", "database",
     lambda a: None if a.get("name") is None else (
         "connection_throttling is off"
         if a["name"] == "connection_throttling" and
         str(a.get("value")).lower() == "off" else False),
     "Set connection_throttling = on"),
    ("AVD-AZU-0021", "PostgreSQL checkpoint logging is disabled",
     "MEDIUM", "pg_config", "database",
     lambda a: None if a.get("name") is None else (
         "log_checkpoints is off"
         if a["name"] == "log_checkpoints" and
         str(a.get("value")).lower() == "off" else False),
     "Set log_checkpoints = on"),
    ("AVD-AZU-0027", "MSSQL auditing retention is under 90 days",
     "MEDIUM", "mssql_auditing", "database",
     _lt("retention", 90, "Audit retention is below 90 days"),
     "Set retention_in_days >= 90"),
    ("AVD-AZU-0026", "MSSQL security alerts do not notify admins",
     "MEDIUM", "mssql_alert", "database",
     _fail_if("email_account_admins", (False,),
              "Account admins are not emailed on alerts"),
     "Set email_account_admins = true"),
    # --- key vault
    ("AVD-AZU-0013", "Key vault network ACLs do not deny by default",
     "CRITICAL", "key_vault", "keyvault",
     _fail_if("network_default_deny", (False,),
              "Default network action is not Deny"),
     "Set network_acls default_action = Deny"),
    ("AVD-AZU-0014", "Key vault secret has no expiration", "LOW",
     "key_vault_secret", "keyvault",
     _fail_if("expiry", (False,), "Secret has no expiration_date"),
     "Set expiration_date"),
    ("AVD-AZU-0017", "Key vault secret has no content type", "LOW",
     "key_vault_secret", "keyvault",
     _fail_if("content_type", (False,),
              "Secret has no content_type"),
     "Set content_type"),
    ("AVD-AZU-0015", "Key vault key has no expiration", "MEDIUM",
     "key_vault_key", "keyvault",
     _fail_if("expiry", (False,), "Key has no expiration_date"),
     "Set expiration_date"),
    # --- monitor
    ("AVD-AZU-0031", "Log profile retention is under a year", "MEDIUM",
     "monitor_log_profile", "monitor",
     lambda a: None if a.get("retention_enabled") is None else (
         "Retention is not enabled for 365 days"
         if a["retention_enabled"] is False or
         (isinstance(a.get("retention_days"), int) and
          0 < a["retention_days"] < 365) else False),
     "Enable retention for >= 365 days"),
    ("AVD-AZU-0033", "Log profile does not capture all activities",
     "MEDIUM", "monitor_log_profile", "monitor",
     lambda a: None if a.get("categories") is None else (
         "Write/Delete/Action categories are not all captured"
         if not {"Write", "Delete", "Action"} <= set(
             a["categories"]) else False),
     "Capture Write, Delete and Action categories"),
    # --- network
    ("AVD-AZU-0048", "NSG rule exposes RDP to the internet",
     "CRITICAL", "nsg_rule", "network",
     _nsg_internet_rule(3389),
     "Restrict RDP (3389) source addresses"),
    ("AVD-AZU-0050", "NSG rule exposes SSH to the internet",
     "CRITICAL", "nsg_rule", "network",
     _nsg_internet_rule(22),
     "Restrict SSH (22) source addresses"),
    # --- security center
    ("AVD-AZU-0044", "Security center contact has no phone", "LOW",
     "security_center_contact", "securitycenter",
     _fail_if("phone", (False,), "No contact phone is set"),
     "Set a contact phone number"),
    ("AVD-AZU-0045", "Security center uses the free tier", "LOW",
     "security_center_pricing", "securitycenter",
     _fail_if("tier", ("Free",), "Defender pricing tier is Free"),
     "Use the Standard tier"),
    # --- synapse / data factory / data lake
    ("AVD-AZU-0034", "Synapse workspace has no managed VNet", "MEDIUM",
     "synapse_workspace", "synapse",
     _fail_if("managed_vnet", (False,),
              "Managed virtual network is not enabled"),
     "Set managed_virtual_network_enabled = true"),
    ("AVD-AZU-0035", "Data factory is publicly accessible", "CRITICAL",
     "data_factory", "datafactory",
     _fail_if("public", (True,),
              "Public network access is enabled"),
     "Set public_network_enabled = false"),
    ("AVD-AZU-0038", "Managed disk encryption is disabled", "HIGH",
     "managed_disk", "compute",
     _fail_if("encryption_disabled", (True,),
              "encryption_settings disables encryption"),
     "Leave managed disk encryption enabled"),
    ("AVD-AZU-0023", "Redis cache enables the non-SSL port", "HIGH",
     "redis_cache", "database",
     _fail_if("non_ssl_port", (True,),
              "enable_non_ssl_port is true"),
     "Disable the non-SSL port"),
    ("AVD-AZU-0036", "Data lake store is unencrypted", "HIGH",
     "data_lake_store", "datalake",
     _fail_if("encrypted", ("Disabled",),
              "Encryption state is Disabled"),
     "Leave encryption_state Enabled"),
]


register_specs(SPECS, provider="azure", file_types=_C)
