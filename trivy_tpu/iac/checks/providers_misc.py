"""Terraform checks for the reference's smaller cloud providers:
github, digitalocean, openstack, oracle, cloudstack, nifcloud
(reference pkg/iac/providers/{github,digitalocean,openstack,oracle,
cloudstack,nifcloud} + pkg/iac/adapters/terraform/*). Check IDs follow
the reference AVD naming; severities are best-effort matches to the
upstream rule metadata.

Terraform-only (these providers have no CloudFormation/ARM surface);
unknown-stays-silent conventions follow iac/checks/cloud.py: an
attribute present but unresolved reads as unknown, never as a failing
value.
"""

from __future__ import annotations

from trivy_tpu.iac.check import check
from trivy_tpu.iac.checks.cloud import (
    _ANYWHERE,
    CloudResource,
    _of_type,
    _tf_tristate,
    _tf_value,
)
from trivy_tpu.iac.parsers.hcl import Block, Expr

_TF = ("terraform",)


def _str_list(v) -> list[str]:
    if isinstance(v, Expr) or v is None:
        return []
    if isinstance(v, str):
        return [v]
    return [x for x in v if isinstance(x, str)]


def adapt_terraform_misc(blocks: list[Block]) -> list[CloudResource]:
    out: list[CloudResource] = []
    for b in blocks:
        if b.type != "resource" or len(b.labels) < 2:
            continue
        t = b.labels[0]
        cr = CloudResource(name=f"{t}.{b.labels[1]}",
                           start_line=b.start_line, end_line=b.end_line)
        if t == "github_repository":
            # reference adapters/terraform/github/repositories/adapt.go:
            # visibility overrides private; default is public
            public: bool | None = True
            private = _tf_tristate(b, "private", None)
            if private is True:
                public = False
            elif private is None and "private" in b.attrs:
                public = None  # unresolved
            vis = b.get("visibility")
            if vis is not None:
                v = _tf_value(vis)
                if v in ("private", "internal"):
                    public = False
                elif v == "public":
                    public = True
                else:
                    public = None  # unresolved expression
            cr.type = "github_repository"
            cr.attrs = {
                "public": public,
                "vulnerability_alerts": _tf_tristate(
                    b, "vulnerability_alerts", False),
                "archived": _tf_tristate(b, "archived", False),
            }
        elif t in ("github_branch_protection",
                   "github_branch_protection_v3"):
            cr.type = "github_branch_protection"
            cr.attrs = {
                "require_signed_commits": _tf_tristate(
                    b, "require_signed_commits", False),
            }
        elif t == "github_actions_environment_secret":
            cr.type = "github_env_secret"
            cr.attrs = {
                "plaintext": bool(_tf_value(b.get("plaintext_value"))),
            }
        elif t == "digitalocean_firewall":
            inbound, outbound = [], []
            for rule in b.children("inbound_rule"):
                inbound.extend(_str_list(rule.get("source_addresses")))
            for rule in b.children("outbound_rule"):
                outbound.extend(
                    _str_list(rule.get("destination_addresses")))
            cr.type = "do_firewall"
            cr.attrs = {"inbound": inbound, "outbound": outbound}
        elif t == "digitalocean_loadbalancer":
            protos = [
                _tf_value(r.get("entry_protocol"))
                for r in b.children("forwarding_rule")
            ]
            cr.type = "do_loadbalancer"
            cr.attrs = {
                "entry_protocols": protos,
                "redirect_http": _tf_tristate(
                    b, "redirect_http_to_https", False),
            }
        elif t == "digitalocean_droplet":
            keys = b.get("ssh_keys")
            cr.type = "do_droplet"
            cr.attrs = {
                # unresolved list -> unknown (not "no keys")
                "has_ssh_keys": None if isinstance(keys, Expr)
                else bool(keys),
            }
        elif t == "digitalocean_kubernetes_cluster":
            cr.type = "do_kubernetes"
            cr.attrs = {
                "auto_upgrade": _tf_tristate(b, "auto_upgrade", False),
                "surge_upgrade": _tf_tristate(b, "surge_upgrade", False),
            }
        elif t == "digitalocean_spaces_bucket":
            vers = b.child("versioning")
            cr.type = "do_spaces_bucket"
            cr.attrs = {
                "acl": _tf_value(b.get("acl")),
                "versioning": _tf_tristate(vers, "enabled", False)
                if vers else False,
            }
        elif t == "openstack_networking_secgroup_rule_v2":
            cr.type = "openstack_secgroup_rule"
            cr.attrs = {
                "direction": _tf_value(b.get("direction")),
                "cidr": _tf_value(b.get("remote_ip_prefix")),
            }
        elif t == "openstack_compute_instance_v2":
            cr.type = "openstack_instance"
            cr.attrs = {
                "admin_pass": bool(_tf_value(b.get("admin_pass"))),
            }
        elif t == "opc_compute_ip_address_reservation":
            cr.type = "oracle_ip_reservation"
            cr.attrs = {"pool": _tf_value(b.get("pool"))}
        elif t == "cloudstack_instance":
            ud = _tf_value(b.get("user_data"))
            if isinstance(ud, str):
                # CloudStack user_data is conventionally base64; decode
                # when decodable so markers inside are still found
                # (reference adapters/terraform/cloudstack)
                import base64 as _b64

                try:
                    decoded = _b64.b64decode(ud, validate=True)
                    ud = decoded.decode("utf-8", "replace")
                except (ValueError, UnicodeDecodeError):
                    pass
            cr.type = "cloudstack_instance"
            cr.attrs = {"user_data": ud if isinstance(ud, str) else ""}
        elif t in ("nifcloud_security_group_rule",):
            cr.type = "nifcloud_sg_rule"
            cr.attrs = {
                # absent -> provider default IN; unresolved -> None
                "type": _tf_tristate(b, "type", "IN"),
                "cidr": _tf_value(b.get("cidr_ip")),
            }
        elif t == "nifcloud_load_balancer":
            cr.type = "nifcloud_lb"
            cr.attrs = {
                "protocol": _tf_value(b.get("load_balancer_protocol")),
            }
        else:
            continue
        out.append(cr)
    return out


# ------------------------------------------------------------- github


@check("AVD-GIT-0001", "GitHub repository is public", severity="MEDIUM",
       file_types=_TF, provider="github", service="repositories",
       resolution="Set visibility = private (or internal)")
def github_repo_public(ctx):
    out = []
    for r in _of_type(ctx, "github_repository"):
        if r.attrs.get("public") is True:
            out.append(r.cause("Repository is public"))
    return out


@check("AVD-GIT-0004", "GitHub branch protection does not require signed "
                       "commits", severity="HIGH", file_types=_TF,
       provider="github", service="branch_protections",
       resolution="Set require_signed_commits = true")
def github_signed_commits(ctx):
    out = []
    for r in _of_type(ctx, "github_branch_protection"):
        if r.attrs.get("require_signed_commits") is False:
            out.append(r.cause(
                "Branch protection does not require signed commits"))
    return out


@check("AVD-GIT-0003", "GitHub repository has vulnerability alerts "
                       "disabled", severity="HIGH", file_types=_TF,
       provider="github", service="repositories",
       resolution="Set vulnerability_alerts = true")
def github_vuln_alerts(ctx):
    out = []
    for r in _of_type(ctx, "github_repository"):
        if r.attrs.get("vulnerability_alerts") is False \
                and r.attrs.get("archived") is not True:
            out.append(r.cause("Vulnerability alerts are not enabled"))
    return out


@check("AVD-GIT-0002", "GitHub Actions environment secret has a "
                       "plaintext value", severity="HIGH", file_types=_TF,
       provider="github", service="actions",
       resolution="Use encrypted_value instead of plaintext_value")
def github_plaintext_secret(ctx):
    out = []
    for r in _of_type(ctx, "github_env_secret"):
        if r.attrs.get("plaintext"):
            out.append(r.cause(
                "Environment secret is set from a plaintext value"))
    return out


# ------------------------------------------------------- digitalocean


@check("AVD-DIG-0001", "DigitalOcean firewall allows unrestricted "
                       "ingress", severity="CRITICAL", file_types=_TF,
       provider="digitalocean", service="compute",
       resolution="Restrict inbound source addresses")
def do_firewall_open_inbound(ctx):
    out = []
    for r in _of_type(ctx, "do_firewall"):
        if any(a in _ANYWHERE for a in r.attrs.get("inbound") or []):
            out.append(r.cause(
                "Firewall rule allows ingress from anywhere"))
    return out


@check("AVD-DIG-0002", "DigitalOcean firewall allows unrestricted "
                       "egress", severity="CRITICAL", file_types=_TF,
       provider="digitalocean", service="compute",
       resolution="Restrict outbound destination addresses")
def do_firewall_open_outbound(ctx):
    out = []
    for r in _of_type(ctx, "do_firewall"):
        if any(a in _ANYWHERE for a in r.attrs.get("outbound") or []):
            out.append(r.cause(
                "Firewall rule allows egress to anywhere"))
    return out


@check("AVD-DIG-0003", "DigitalOcean load balancer accepts plain HTTP",
       severity="HIGH", file_types=_TF, provider="digitalocean",
       service="compute",
       resolution="Use https/http2 entry protocols or redirect HTTP")
def do_lb_plain_http(ctx):
    out = []
    for r in _of_type(ctx, "do_loadbalancer"):
        if r.attrs.get("redirect_http") is not False:
            continue  # True = exempt; None = unresolved = unknown
        if any(str(p or "").lower() == "http"
               for p in r.attrs.get("entry_protocols") or []):
            out.append(r.cause(
                "Load balancer forwarding rule uses plain HTTP"))
    return out


@check("AVD-DIG-0004", "DigitalOcean droplet has no SSH keys",
       severity="CRITICAL", file_types=_TF, provider="digitalocean",
       service="compute",
       resolution="Provision droplets with ssh_keys (password logins "
                  "are emailed in plaintext)")
def do_droplet_no_keys(ctx):
    out = []
    for r in _of_type(ctx, "do_droplet"):
        if r.attrs.get("has_ssh_keys") is False:
            out.append(r.cause("Droplet created without SSH keys"))
    return out


@check("AVD-DIG-0005", "DigitalOcean kubernetes cluster does not "
                       "auto-upgrade", severity="MEDIUM", file_types=_TF,
       provider="digitalocean", service="compute",
       resolution="Set auto_upgrade = true")
def do_k8s_auto_upgrade(ctx):
    out = []
    for r in _of_type(ctx, "do_kubernetes"):
        if r.attrs.get("auto_upgrade") is False:
            out.append(r.cause("Cluster does not auto-upgrade"))
    return out


@check("AVD-DIG-0008", "DigitalOcean kubernetes cluster has surge "
                       "upgrades disabled", severity="MEDIUM",
       file_types=_TF, provider="digitalocean", service="compute",
       resolution="Set surge_upgrade = true")
def do_k8s_surge_upgrade(ctx):
    out = []
    for r in _of_type(ctx, "do_kubernetes"):
        if r.attrs.get("surge_upgrade") is False:
            out.append(r.cause("Cluster has surge upgrades disabled"))
    return out


@check("AVD-DIG-0006", "DigitalOcean Spaces bucket has a public ACL",
       severity="CRITICAL", file_types=_TF, provider="digitalocean",
       service="spaces",
       resolution="Set acl = private")
def do_spaces_public(ctx):
    out = []
    for r in _of_type(ctx, "do_spaces_bucket"):
        if str(r.attrs.get("acl") or "") == "public-read":
            out.append(r.cause("Spaces bucket ACL is public-read"))
    return out


@check("AVD-DIG-0007", "DigitalOcean Spaces bucket versioning disabled",
       severity="MEDIUM", file_types=_TF, provider="digitalocean",
       service="spaces",
       resolution="Enable versioning")
def do_spaces_versioning(ctx):
    out = []
    for r in _of_type(ctx, "do_spaces_bucket"):
        if r.attrs.get("versioning") is False:
            out.append(r.cause("Spaces bucket has versioning disabled"))
    return out


# --------------------------------------------------------- openstack


@check("AVD-OPNSTK-0001", "OpenStack instance sets a plaintext admin "
                          "password", severity="MEDIUM", file_types=_TF,
       provider="openstack", service="compute",
       resolution="Avoid admin_pass; use key pairs")
def openstack_admin_pass(ctx):
    out = []
    for r in _of_type(ctx, "openstack_instance"):
        if r.attrs.get("admin_pass"):
            out.append(r.cause("Instance sets admin_pass in plaintext"))
    return out


@check("AVD-OPNSTK-0002", "OpenStack security group rule allows ingress "
                          "from anywhere", severity="MEDIUM",
       file_types=_TF, provider="openstack", service="networking",
       resolution="Restrict remote_ip_prefix")
def openstack_open_ingress(ctx):
    out = []
    for r in _of_type(ctx, "openstack_secgroup_rule"):
        if str(r.attrs.get("direction") or "") == "ingress" and \
                str(r.attrs.get("cidr") or "") in _ANYWHERE:
            out.append(r.cause(
                "Security group rule allows ingress from anywhere"))
    return out


# ------------------------------------------------------------- oracle


@check("AVD-OCI-0001", "OCI compute IP reservation from a public pool",
       severity="CRITICAL", file_types=_TF, provider="oracle",
       service="compute",
       resolution="Reserve addresses from a private pool")
def oracle_public_ip_pool(ctx):
    out = []
    for r in _of_type(ctx, "oracle_ip_reservation"):
        if str(r.attrs.get("pool") or "") == "public-ippool":
            out.append(r.cause(
                "IP reservation draws from the public pool"))
    return out


# --------------------------------------------------------- cloudstack


_SENSITIVE_MARKERS = ("password", "secret", "token", "aws_access_key",
                      "private_key")


@check("AVD-CLDSTK-0001", "CloudStack instance user data contains "
                          "sensitive material", severity="HIGH",
       file_types=_TF, provider="cloudstack", service="compute",
       resolution="Keep credentials out of user_data")
def cloudstack_userdata_secrets(ctx):
    out = []
    for r in _of_type(ctx, "cloudstack_instance"):
        ud = str(r.attrs.get("user_data") or "").lower()
        if any(marker in ud for marker in _SENSITIVE_MARKERS):
            out.append(r.cause(
                "Instance user_data embeds sensitive values"))
    return out


# ----------------------------------------------------------- nifcloud


@check("AVD-NIF-0001", "NIFCLOUD security group rule allows ingress "
                       "from anywhere", severity="CRITICAL",
       file_types=_TF, provider="nifcloud", service="network",
       resolution="Restrict cidr_ip")
def nifcloud_open_ingress(ctx):
    out = []
    for r in _of_type(ctx, "nifcloud_sg_rule"):
        kind = r.attrs.get("type")
        if kind is None:
            continue  # unresolved direction = unknown, stay silent
        if str(kind).upper() != "OUT" and \
                str(r.attrs.get("cidr") or "") in _ANYWHERE:
            out.append(r.cause(
                "Security group rule allows ingress from anywhere"))
    return out


@check("AVD-NIF-0002", "NIFCLOUD load balancer uses plain HTTP",
       severity="HIGH", file_types=_TF, provider="nifcloud",
       service="network",
       resolution="Use HTTPS for the load balancer listener")
def nifcloud_lb_http(ctx):
    out = []
    for r in _of_type(ctx, "nifcloud_lb"):
        if str(r.attrs.get("protocol") or "").upper() == "HTTP":
            out.append(r.cause("Load balancer listener uses plain HTTP"))
    return out
