"""Kubernetes workload checks (reference trivy-checks
checks/kubernetes/*.rego; IDs match the published KSV rules)."""

from __future__ import annotations

from trivy_tpu.iac.check import Cause, check
from trivy_tpu.iac.parsers.yamlconf import get_end_line, get_line

_K = ("kubernetes", "helm")


def _name(res: dict) -> str:
    md = res.get("metadata") or {}
    return f"{res.get('kind', '')}/{md.get('name', '')}"


def _container_cause(ctx, c: dict, msg: str) -> Cause:
    return Cause(
        message=msg, resource=_name(ctx.resource),
        start_line=get_line(c) or get_line(ctx.resource),
        end_line=get_end_line(c) or get_line(c) or get_line(ctx.resource),
    )


def _sc(c: dict) -> dict:
    return c.get("securityContext") or {}


def _pod_sc(ctx) -> dict:
    return (ctx.pod_spec or {}).get("securityContext") or {}


@check("KSV001", "Process can elevate its own privileges",
       severity="MEDIUM", file_types=_K, avd_id="AVD-KSV-0001",
       provider="kubernetes", service="general",
       resolution="Set 'securityContext.allowPrivilegeEscalation' to "
                  "false")
def allow_priv_escalation(ctx):
    out = []
    for c in ctx.containers:
        if _sc(c).get("allowPrivilegeEscalation") is not False:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'securityContext.allowPrivilegeEscalation' to false"))
    return out


@check("KSV003", "Default capabilities not dropped", severity="LOW",
       file_types=_K, avd_id="AVD-KSV-0003", provider="kubernetes",
       service="general",
       resolution="Add 'ALL' to 'securityContext.capabilities.drop'")
def drop_capabilities(ctx):
    out = []
    for c in ctx.containers:
        drop = (_sc(c).get("capabilities") or {}).get("drop") or []
        if not any(str(d).upper() == "ALL" for d in drop):
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should add 'ALL' to "
                f"'securityContext.capabilities.drop'"))
    return out


@check("KSV005", "SYS_ADMIN capability added", severity="HIGH",
       file_types=_K, avd_id="AVD-KSV-0005", provider="kubernetes",
       service="general",
       resolution="Remove the SYS_ADMIN capability")
def sys_admin(ctx):
    out = []
    for c in ctx.containers:
        add = (_sc(c).get("capabilities") or {}).get("add") or []
        if any(str(a).upper() == "SYS_ADMIN" for a in add):
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should not include 'SYS_ADMIN' in "
                f"'securityContext.capabilities.add'"))
    return out


@check("KSV006", "hostPath volume mounts docker.sock", severity="HIGH",
       file_types=_K, avd_id="AVD-KSV-0006", provider="kubernetes",
       service="general",
       resolution="Do not mount /var/run/docker.sock")
def docker_sock(ctx):
    out = []
    for v in (ctx.pod_spec or {}).get("volumes") or []:
        hp = (v or {}).get("hostPath") or {}
        if hp.get("path") == "/var/run/docker.sock":
            out.append(Cause(
                message=f"{_name(ctx.resource)} should not mount "
                        f"'/var/run/docker.sock'",
                resource=_name(ctx.resource),
                start_line=get_line(v), end_line=get_end_line(v),
            ))
    return out


@check("KSV008", "Access to host IPC namespace", severity="HIGH",
       file_types=_K, avd_id="AVD-KSV-0008", provider="kubernetes",
       service="general", resolution="Set 'spec.hostIPC' to false")
def host_ipc(ctx):
    if (ctx.pod_spec or {}).get("hostIPC") is True:
        return [Cause(
            message=f"{_name(ctx.resource)} should not set "
                    f"'spec.template.spec.hostIPC' to true",
            resource=_name(ctx.resource),
            start_line=get_line(ctx.pod_spec),
            end_line=get_line(ctx.pod_spec),
        )]
    return []


@check("KSV009", "Access to host network", severity="HIGH",
       file_types=_K, avd_id="AVD-KSV-0009", provider="kubernetes",
       service="general", resolution="Set 'spec.hostNetwork' to false")
def host_network(ctx):
    if (ctx.pod_spec or {}).get("hostNetwork") is True:
        return [Cause(
            message=f"{_name(ctx.resource)} should not set "
                    f"'spec.template.spec.hostNetwork' to true",
            resource=_name(ctx.resource),
            start_line=get_line(ctx.pod_spec),
            end_line=get_line(ctx.pod_spec),
        )]
    return []


@check("KSV010", "Access to host PID", severity="HIGH", file_types=_K,
       avd_id="AVD-KSV-0010", provider="kubernetes", service="general",
       resolution="Set 'spec.hostPID' to false")
def host_pid(ctx):
    if (ctx.pod_spec or {}).get("hostPID") is True:
        return [Cause(
            message=f"{_name(ctx.resource)} should not set "
                    f"'spec.template.spec.hostPID' to true",
            resource=_name(ctx.resource),
            start_line=get_line(ctx.pod_spec),
            end_line=get_line(ctx.pod_spec),
        )]
    return []


@check("KSV011", "CPU not limited", severity="LOW", file_types=_K,
       avd_id="AVD-KSV-0011", provider="kubernetes", service="general",
       resolution="Set 'resources.limits.cpu'")
def cpu_limit(ctx):
    out = []
    for c in ctx.containers:
        limits = (c.get("resources") or {}).get("limits") or {}
        if "cpu" not in limits:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'resources.limits.cpu'"))
    return out


@check("KSV012", "Runs as root user", severity="MEDIUM", file_types=_K,
       avd_id="AVD-KSV-0012", provider="kubernetes", service="general",
       resolution="Set 'securityContext.runAsNonRoot' to true")
def run_as_non_root(ctx):
    out = []
    pod_nonroot = _pod_sc(ctx).get("runAsNonRoot") is True
    for c in ctx.containers:
        own = _sc(c).get("runAsNonRoot")
        # container-level setting overrides pod-level; only an unset
        # container inherits the pod default
        if own is True or (own is None and pod_nonroot):
            continue
        out.append(_container_cause(
            ctx, c,
            f"Container '{c.get('name', '')}' of {_name(ctx.resource)} "
            f"should set 'securityContext.runAsNonRoot' to true"))
    return out


@check("KSV013", "Image tag ':latest' used", severity="MEDIUM",
       file_types=_K, avd_id="AVD-KSV-0013", provider="kubernetes",
       service="general",
       resolution="Use a specific container image tag")
def image_tag(ctx):
    out = []
    for c in ctx.containers:
        image = str(c.get("image", ""))
        if not image or "@" in image:
            continue
        tail = image.split("/")[-1]
        tag = tail.rsplit(":", 1)[1] if ":" in tail else ""
        if not tag or tag == "latest":
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should specify an image tag"))
    return out


@check("KSV014", "Root file system is not read-only", severity="HIGH",
       file_types=_K, avd_id="AVD-KSV-0014", provider="kubernetes",
       service="general",
       resolution="Set 'securityContext.readOnlyRootFilesystem' to true")
def read_only_rootfs(ctx):
    out = []
    for c in ctx.containers:
        if _sc(c).get("readOnlyRootFilesystem") is not True:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'securityContext.readOnlyRootFilesystem' to true"))
    return out


@check("KSV015", "CPU requests not specified", severity="LOW",
       file_types=_K, avd_id="AVD-KSV-0015", provider="kubernetes",
       service="general", resolution="Set 'resources.requests.cpu'")
def cpu_request(ctx):
    out = []
    for c in ctx.containers:
        req = (c.get("resources") or {}).get("requests") or {}
        if "cpu" not in req:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'resources.requests.cpu'"))
    return out


@check("KSV016", "Memory requests not specified", severity="LOW",
       file_types=_K, avd_id="AVD-KSV-0016", provider="kubernetes",
       service="general", resolution="Set 'resources.requests.memory'")
def memory_request(ctx):
    out = []
    for c in ctx.containers:
        req = (c.get("resources") or {}).get("requests") or {}
        if "memory" not in req:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'resources.requests.memory'"))
    return out


@check("KSV017", "Privileged container", severity="HIGH", file_types=_K,
       avd_id="AVD-KSV-0017", provider="kubernetes", service="general",
       resolution="Set 'securityContext.privileged' to false")
def privileged(ctx):
    out = []
    for c in ctx.containers:
        if _sc(c).get("privileged") is True:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'securityContext.privileged' to false"))
    return out


@check("KSV018", "Memory not limited", severity="LOW", file_types=_K,
       avd_id="AVD-KSV-0018", provider="kubernetes", service="general",
       resolution="Set 'resources.limits.memory'")
def memory_limit(ctx):
    out = []
    for c in ctx.containers:
        limits = (c.get("resources") or {}).get("limits") or {}
        if "memory" not in limits:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'resources.limits.memory'"))
    return out


@check("KSV023", "hostPath volumes mounted", severity="MEDIUM",
       file_types=_K, avd_id="AVD-KSV-0023", provider="kubernetes",
       service="general", resolution="Do not set 'spec.volumes.hostPath'")
def host_path(ctx):
    out = []
    for v in (ctx.pod_spec or {}).get("volumes") or []:
        if (v or {}).get("hostPath"):
            out.append(Cause(
                message=f"{_name(ctx.resource)} should not set "
                        f"'spec.template.volumes.hostPath'",
                resource=_name(ctx.resource),
                start_line=get_line(v), end_line=get_end_line(v),
            ))
    return out

@check("KSV002", "Default AppArmor profile not set", severity="MEDIUM",
       file_types=_K, avd_id="AVD-KSV-0002", provider="kubernetes",
       service="general",
       resolution="Remove 'container.apparmor.security.beta.kubernetes.io' "
                  "annotations or set them to 'runtime/default'")
def apparmor_profile(ctx):
    out = []
    annotations = (ctx.resource.get("metadata") or {}).get("annotations") or {}
    tmpl_md = {}
    spec = ctx.resource.get("spec") or {}
    if isinstance(spec.get("template"), dict):
        tmpl_md = (spec["template"].get("metadata") or {})
    tmpl_ann = tmpl_md.get("annotations") or {}
    for ann in ({**annotations, **tmpl_ann}).items():
        key, value = ann
        if key.startswith("container.apparmor.security.beta.kubernetes.io/") \
                and value not in ("runtime/default", "localhost/default"):
            out.append(Cause(
                message=f"{_name(ctx.resource)} should specify an AppArmor "
                        f"profile of 'runtime/default'",
                resource=_name(ctx.resource),
                start_line=get_line(ctx.resource),
                end_line=get_line(ctx.resource),
            ))
    return out


@check("KSV024", "Access to host ports", severity="HIGH", file_types=_K,
       avd_id="AVD-KSV-0024", provider="kubernetes", service="general",
       resolution="Do not set 'spec.containers.ports.hostPort'")
def host_ports(ctx):
    out = []
    for c in ctx.containers:
        for p in c.get("ports") or []:
            if (p or {}).get("hostPort"):
                out.append(_container_cause(
                    ctx, c,
                    f"Container '{c.get('name', '')}' of "
                    f"{_name(ctx.resource)} should not set "
                    f"'ports.hostPort'"))
    return out


@check("KSV029", "A root primary or supplementary GID set", severity="LOW",
       file_types=_K, avd_id="AVD-KSV-0029", provider="kubernetes",
       service="general",
       resolution="Set 'securityContext.runAsGroup' to a non-zero integer "
                  "and do not include group 0 in 'supplementalGroups'")
def root_group(ctx):
    out = []
    pod_sc = _pod_sc(ctx)
    if pod_sc.get("runAsGroup") == 0 or pod_sc.get("fsGroup") == 0 or \
            0 in (pod_sc.get("supplementalGroups") or []):
        out.append(Cause(
            message=f"{_name(ctx.resource)} should not set a root group "
                    f"(runAsGroup/fsGroup/supplementalGroups of 0)",
            resource=_name(ctx.resource),
            start_line=get_line(ctx.pod_spec),
            end_line=get_line(ctx.pod_spec),
        ))
    for c in ctx.containers:
        if _sc(c).get("runAsGroup") == 0:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of {_name(ctx.resource)} "
                f"should not set 'securityContext.runAsGroup' to 0"))
    return out


@check("KSV030", "Runtime/Default Seccomp profile not set", severity="LOW",
       file_types=_K, avd_id="AVD-KSV-0030", provider="kubernetes",
       service="general",
       resolution="Set 'securityContext.seccompProfile.type' to "
                  "'RuntimeDefault'")
def seccomp_profile(ctx):
    allowed = ("RuntimeDefault", "Localhost")
    pod_type = (_pod_sc(ctx).get("seccompProfile") or {}).get("type")
    out = []
    for c in ctx.containers:
        own = (_sc(c).get("seccompProfile") or {}).get("type")
        effective = own if own is not None else pod_type
        if effective not in allowed:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of {_name(ctx.resource)} "
                f"should set 'securityContext.seccompProfile.type' to "
                f"'RuntimeDefault'"))
    return out


@check("KSV036", "Service account token mounted automatically",
       severity="MEDIUM", file_types=_K, avd_id="AVD-KSV-0036",
       provider="kubernetes", service="general",
       resolution="Set 'automountServiceAccountToken' to false or mount "
                  "the token only where needed")
def automount_token(ctx):
    """Fails on explicit opt-in only: automountServiceAccountToken=true
    or an explicit token volumeMount (upstream rego semantics — a bare
    pod with the field unset passes, per the reference helm goldens)."""
    spec = ctx.pod_spec or {}
    if spec.get("automountServiceAccountToken") is False:
        return []
    token_path = "/var/run/secrets/kubernetes.io/serviceaccount"
    mounted = any(
        str((vm or {}).get("mountPath", "")).rstrip("/") == token_path
        for c in ctx.containers
        for vm in c.get("volumeMounts") or []
    )
    if spec.get("automountServiceAccountToken") is True or mounted:
        return [Cause(
            message=f"{_name(ctx.resource)} should set "
                    f"'automountServiceAccountToken' to false",
            resource=_name(ctx.resource),
            start_line=get_line(ctx.pod_spec),
            end_line=get_line(ctx.pod_spec),
        )]
    return []


@check("KSV037", "User Pods should not be placed in kube-system namespace",
       severity="MEDIUM", file_types=_K, avd_id="AVD-KSV-0037",
       provider="kubernetes", service="general",
       resolution="Deploy user workloads outside the kube-system namespace")
def kube_system_namespace(ctx):
    md = ctx.resource.get("metadata") or {}
    if md.get("namespace") != "kube-system":
        return []
    labels = md.get("labels") or {}
    # control-plane components themselves are exempt
    if labels.get("tier") == "control-plane" or "component" in labels:
        return []
    return [Cause(
        message=f"{_name(ctx.resource)} should not be deployed in the "
                f"'kube-system' namespace",
        resource=_name(ctx.resource),
        start_line=get_line(ctx.resource),
        end_line=get_line(ctx.resource),
    )]


@check("KSV103", "HostProcess container defined", severity="HIGH",
       file_types=_K, avd_id="AVD-KSV-0103", provider="kubernetes",
       service="general",
       resolution="Do not enable 'windowsOptions.hostProcess'")
def host_process(ctx):
    out = []
    pod_wo = _pod_sc(ctx).get("windowsOptions") or {}
    if pod_wo.get("hostProcess") is True:
        out.append(Cause(
            message=f"{_name(ctx.resource)} should not set "
                    f"'securityContext.windowsOptions.hostProcess' to true",
            resource=_name(ctx.resource),
            start_line=get_line(ctx.pod_spec),
            end_line=get_line(ctx.pod_spec),
        ))
    for c in ctx.containers:
        wo = _sc(c).get("windowsOptions") or {}
        if wo.get("hostProcess") is True:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of {_name(ctx.resource)} "
                f"should not enable 'windowsOptions.hostProcess'"))
    return out


@check("KSV025", "SELinux custom options set", severity="MEDIUM",
       file_types=_K, avd_id="AVD-KSV-0025", provider="kubernetes",
       service="general",
       resolution="Do not set 'securityContext.seLinuxOptions' custom "
                  "type/user/role")
def selinux_options(ctx):
    out = []

    def bad(opts: dict) -> bool:
        return bool(opts.get("user") or opts.get("role") or
                    (opts.get("type") and opts["type"] not in
                     ("container_t", "container_init_t", "container_kvm_t")))

    if bad(_pod_sc(ctx).get("seLinuxOptions") or {}):
        out.append(Cause(
            message=f"{_name(ctx.resource)} should not set custom "
                    f"'securityContext.seLinuxOptions'",
            resource=_name(ctx.resource),
            start_line=get_line(ctx.pod_spec),
            end_line=get_line(ctx.pod_spec),
        ))
    for c in ctx.containers:
        if bad(_sc(c).get("seLinuxOptions") or {}):
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of {_name(ctx.resource)} "
                f"should not set custom 'securityContext.seLinuxOptions'"))
    return out


@check("KSV020", "Runs with a low user ID", severity="LOW",
       file_types=_K, avd_id="AVD-KSV-0020", provider="kubernetes",
       service="general",
       resolution="Set 'securityContext.runAsUser' above 10000")
def low_user_id(ctx):
    out = []
    pod_uid = _pod_sc(ctx).get("runAsUser")
    for c in ctx.containers:
        uid = _sc(c).get("runAsUser", pod_uid)
        try:
            ok = uid is not None and int(uid) > 10000
        except (TypeError, ValueError):
            ok = False
        if not ok:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'securityContext.runAsUser' > 10000"))
    return out


@check("KSV021", "Runs with a low group ID", severity="LOW",
       file_types=_K, avd_id="AVD-KSV-0021", provider="kubernetes",
       service="general",
       resolution="Set 'securityContext.runAsGroup' above 10000")
def low_group_id(ctx):
    out = []
    pod_gid = _pod_sc(ctx).get("runAsGroup")
    for c in ctx.containers:
        gid = _sc(c).get("runAsGroup", pod_gid)
        try:
            ok = gid is not None and int(gid) > 10000
        except (TypeError, ValueError):
            ok = False
        if not ok:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'securityContext.runAsGroup' > 10000"))
    return out


def _pod_annotations(ctx) -> dict:
    """Pod-template annotations: spec.template.metadata for workloads,
    the object's own metadata for bare Pods."""
    res = ctx.resource or {}
    tmpl_meta = (((res.get("spec") or {}).get("template") or {})
                 .get("metadata") or {})
    meta = tmpl_meta or res.get("metadata") or {}
    return meta.get("annotations") or {}


@check("KSV104", "Seccomp profile not configured", severity="MEDIUM",
       file_types=_K, avd_id="AVD-KSV-0104", provider="kubernetes",
       service="general",
       resolution="Set 'securityContext.seccompProfile.type'")
def seccomp_unset(ctx):
    out = []
    pod_prof = (_pod_sc(ctx).get("seccompProfile") or {}).get("type")
    annotated = any(
        str(k).startswith("seccomp.security.alpha.kubernetes.io")
        for k in _pod_annotations(ctx))
    for c in ctx.containers:
        prof = (_sc(c).get("seccompProfile") or {}).get("type", pod_prof)
        if not prof and not annotated:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should specify a seccomp "
                f"profile"))
    return out


@check("KSV105", "Container runs as root user (UID 0)", severity="LOW",
       file_types=_K, avd_id="AVD-KSV-0105", provider="kubernetes",
       service="general",
       resolution="Do not set 'securityContext.runAsUser' to 0")
def run_as_root_uid(ctx):
    out = []
    pod_uid = _pod_sc(ctx).get("runAsUser")
    for c in ctx.containers:
        uid = _sc(c).get("runAsUser", pod_uid)
        try:
            is_root = uid is not None and int(uid) == 0
        except (TypeError, ValueError):
            is_root = False
        if is_root:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} runs with runAsUser 0"))
    return out


@check("KSV106", "Container capabilities beyond NET_BIND_SERVICE",
       severity="LOW", file_types=_K, avd_id="AVD-KSV-0106",
       provider="kubernetes", service="general",
       resolution="Drop ALL capabilities; add only NET_BIND_SERVICE "
                  "when needed")
def restricted_capabilities(ctx):
    out = []
    for c in ctx.containers:
        caps = _sc(c).get("capabilities") or {}
        drop = [str(d).upper() for d in caps.get("drop") or []]
        add = [str(a).upper() for a in caps.get("add") or []]
        ok = "ALL" in drop and all(a == "NET_BIND_SERVICE" for a in add)
        if not ok:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should drop ALL capabilities "
                f"and add only NET_BIND_SERVICE"))
    return out


@check("KSV117", "Container binds to a privileged port", severity="LOW",
       file_types=_K, avd_id="AVD-KSV-0117", provider="kubernetes",
       service="general",
       resolution="Use container ports above 1024")
def privileged_port(ctx):
    out = []
    for c in ctx.containers:
        for port in c.get("ports") or []:
            if not isinstance(port, dict):
                continue
            for key in ("containerPort", "hostPort"):
                v = port.get(key)
                try:
                    low = v is not None and int(v) < 1024
                except (TypeError, ValueError):
                    low = False
                if low:
                    out.append(_container_cause(
                        ctx, c,
                        f"Container '{c.get('name', '')}' of "
                        f"{_name(ctx.resource)} binds privileged port "
                        f"{v}"))
                    break
            else:
                continue
            break
    return out


# --------------------------------------------- breadth wave (r5): more
# published KSV workload + RBAC rules (reference trivy-checks
# checks/kubernetes/{workload,rbac})


@check("KSV007", "hostAliases is set", severity="LOW", file_types=_K,
       avd_id="AVD-KSV-0007", provider="kubernetes", service="general",
       resolution="Do not set spec.hostAliases")
def host_aliases(ctx):
    spec = ctx.pod_spec or {}
    if spec.get("hostAliases"):
        return [Cause(message=f"{_name(ctx.resource)} should not set "
                              f"spec.template.spec.hostAliases",
                      resource=_name(ctx.resource),
                      start_line=get_line(ctx.resource))]
    return []


@check("KSV022", "Non-default capabilities added", severity="MEDIUM",
       file_types=_K, avd_id="AVD-KSV-0022", provider="kubernetes",
       service="general",
       resolution="Remove capabilities.add entries")
def added_capabilities(ctx):
    out = []
    for c in ctx.containers:
        add = (_sc(c).get("capabilities") or {}).get("add") or []
        extra = [a for a in add
                 if str(a).upper() not in ("NET_BIND_SERVICE",)]
        if extra:
            out.append(_container_cause(
                ctx, c, f"Container '{c.get('name', '')}' adds "
                        f"capabilities {sorted(map(str, extra))}"))
    return out


@check("KSV026", "Unsafe sysctls set", severity="MEDIUM",
       file_types=_K, avd_id="AVD-KSV-0026", provider="kubernetes",
       service="general",
       resolution="Remove sysctls outside the safe set")
def unsafe_sysctls(ctx):
    safe = {"kernel.shm_rmid_forced", "net.ipv4.ip_local_port_range",
            "net.ipv4.ip_unprivileged_port_start",
            "net.ipv4.tcp_syncookies", "net.ipv4.ping_group_range"}
    spec = ctx.pod_spec or {}
    sysctls = (spec.get("securityContext") or {}).get("sysctls") or []
    out = []
    for s in sysctls:
        nm = s.get("name") if isinstance(s, dict) else None
        if nm and nm not in safe:
            out.append(Cause(
                message=f"{_name(ctx.resource)} sets unsafe sysctl "
                        f"'{nm}'",
                resource=_name(ctx.resource),
                start_line=get_line(ctx.resource)))
    return out


@check("KSV027", "Non-default /proc mount", severity="MEDIUM",
       file_types=_K, avd_id="AVD-KSV-0027", provider="kubernetes",
       service="general", resolution="Remove procMount")
def proc_mount(ctx):
    out = []
    for c in ctx.containers:
        pm = _sc(c).get("procMount")
        if pm and str(pm) != "Default":
            out.append(_container_cause(
                ctx, c, f"Container '{c.get('name', '')}' uses a "
                        f"non-default procMount '{pm}'"))
    return out


@check("KSV028", "Non-ephemeral volume types used", severity="LOW",
       file_types=_K, avd_id="AVD-KSV-0028", provider="kubernetes",
       service="general",
       resolution="Use only configMap/secret/emptyDir/projected/"
                  "downwardAPI/csi/ephemeral/pvc volumes")
def volume_types(ctx):
    allowed = {"configMap", "secret", "emptyDir", "projected",
               "downwardAPI", "csi", "ephemeral",
               "persistentVolumeClaim", "name"}
    spec = ctx.pod_spec or {}
    out = []
    for v in spec.get("volumes") or []:
        if not isinstance(v, dict):
            continue
        bad = [k for k in v
               if k not in allowed and not k.startswith("__")]
        if bad:
            out.append(Cause(
                message=f"{_name(ctx.resource)} uses restricted volume "
                        f"type(s) {sorted(bad)}",
                resource=_name(ctx.resource),
                start_line=get_line(v) or get_line(ctx.resource)))
    return out


@check("KSV102", "Helm Tiller is deployed", severity="CRITICAL",
       file_types=_K, avd_id="AVD-KSV-0102", provider="kubernetes",
       service="general", resolution="Migrate to Helm v3")
def tiller_deployed(ctx):
    out = []
    for c in ctx.containers:
        img = str(c.get("image", ""))
        repo = img.split("/")[-1].split(":")[0].split("@")[0]
        if repo == "tiller":
            out.append(_container_cause(
                ctx, c, f"Container '{c.get('name', '')}' runs the "
                        f"Tiller image '{img}'"))
    return out


def _role_rules(ctx):
    if ctx.resource.get("kind") not in ("Role", "ClusterRole"):
        return []
    return [r for r in ctx.resource.get("rules") or []
            if isinstance(r, dict)]


def _rbac_cause(ctx, msg):
    return Cause(message=msg, resource=_name(ctx.resource),
                 start_line=get_line(ctx.resource))


@check("KSV041", "Role permits managing secrets", severity="CRITICAL",
       file_types=_K, avd_id="AVD-KSV-0041", provider="kubernetes",
       service="rbac", resolution="Remove secrets write verbs")
def rbac_manage_secrets(ctx):
    out = []
    for r in _role_rules(ctx):
        if "secrets" in (r.get("resources") or []) and \
                any(v in (r.get("verbs") or [])
                    for v in ("create", "update", "patch", "delete",
                              "deletecollection", "impersonate", "*")):
            out.append(_rbac_cause(
                ctx, f"{_name(ctx.resource)} permits managing secrets"))
    return out


@check("KSV042", "Role permits deleting pod logs", severity="MEDIUM",
       file_types=_K, avd_id="AVD-KSV-0042", provider="kubernetes",
       service="rbac", resolution="Remove pods/log delete verbs")
def rbac_delete_pod_logs(ctx):
    out = []
    for r in _role_rules(ctx):
        if "pods/log" in (r.get("resources") or []) and \
                any(v in (r.get("verbs") or [])
                    for v in ("delete", "deletecollection", "*")):
            out.append(_rbac_cause(
                ctx,
                f"{_name(ctx.resource)} permits deleting pod logs"))
    return out


@check("KSV045", "Role uses wildcard verbs", severity="CRITICAL",
       file_types=_K, avd_id="AVD-KSV-0045", provider="kubernetes",
       service="rbac", resolution="Enumerate the needed verbs")
def rbac_wildcard_verbs(ctx):
    out = []
    for r in _role_rules(ctx):
        if "*" in (r.get("verbs") or []) and \
                (r.get("resources") or []) != ["*"]:
            out.append(_rbac_cause(
                ctx, f"{_name(ctx.resource)} uses a wildcard verb"))
    return out


@check("KSV046", "Role permits managing all resources",
       severity="CRITICAL", file_types=_K, avd_id="AVD-KSV-0046",
       provider="kubernetes", service="rbac",
       resolution="Scope the role to specific resources")
def rbac_all_resources(ctx):
    out = []
    for r in _role_rules(ctx):
        if "*" in (r.get("resources") or []) and \
                "*" in (r.get("verbs") or []):
            out.append(_rbac_cause(
                ctx, f"{_name(ctx.resource)} permits managing all "
                     f"resources"))
    return out


@check("KSV049", "Role permits managing configmaps",
       severity="MEDIUM", file_types=_K, avd_id="AVD-KSV-0049",
       provider="kubernetes", service="rbac",
       resolution="Limit configmap write access")
def rbac_manage_configmaps(ctx):
    out = []
    for r in _role_rules(ctx):
        if "configmaps" in (r.get("resources") or []) and \
                any(v in (r.get("verbs") or [])
                    for v in ("create", "update", "patch", "delete",
                              "deletecollection", "*")):
            out.append(_rbac_cause(
                ctx,
                f"{_name(ctx.resource)} permits managing configmaps"))
    return out


@check("KSV050", "Role permits managing RBAC resources",
       severity="CRITICAL", file_types=_K, avd_id="AVD-KSV-0050",
       provider="kubernetes", service="rbac",
       resolution="Restrict RBAC management permissions")
def rbac_manage_rbac(ctx):
    rbac_resources = {"roles", "clusterroles", "rolebindings",
                      "clusterrolebindings"}
    out = []
    for r in _role_rules(ctx):
        if rbac_resources & set(r.get("resources") or []) and \
                any(v in (r.get("verbs") or [])
                    for v in ("create", "update", "patch", "delete",
                              "deletecollection", "bind", "escalate",
                              "*")):
            out.append(_rbac_cause(
                ctx, f"{_name(ctx.resource)} permits managing RBAC "
                     f"resources"))
    return out
