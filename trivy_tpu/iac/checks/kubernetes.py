"""Kubernetes workload checks (reference trivy-checks
checks/kubernetes/*.rego; IDs match the published KSV rules)."""

from __future__ import annotations

from trivy_tpu.iac.check import Cause, check
from trivy_tpu.iac.parsers.yamlconf import get_end_line, get_line

_K = ("kubernetes", "helm")


def _name(res: dict) -> str:
    md = res.get("metadata") or {}
    return f"{res.get('kind', '')}/{md.get('name', '')}"


def _container_cause(ctx, c: dict, msg: str) -> Cause:
    return Cause(
        message=msg, resource=_name(ctx.resource),
        start_line=get_line(c) or get_line(ctx.resource),
        end_line=get_end_line(c) or get_line(c) or get_line(ctx.resource),
    )


def _sc(c: dict) -> dict:
    return c.get("securityContext") or {}


def _pod_sc(ctx) -> dict:
    return (ctx.pod_spec or {}).get("securityContext") or {}


@check("KSV001", "Process can elevate its own privileges",
       severity="MEDIUM", file_types=_K, avd_id="AVD-KSV-0001",
       provider="kubernetes", service="general",
       resolution="Set 'securityContext.allowPrivilegeEscalation' to "
                  "false")
def allow_priv_escalation(ctx):
    out = []
    for c in ctx.containers:
        if _sc(c).get("allowPrivilegeEscalation") is not False:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'securityContext.allowPrivilegeEscalation' to false"))
    return out


@check("KSV003", "Default capabilities not dropped", severity="LOW",
       file_types=_K, avd_id="AVD-KSV-0003", provider="kubernetes",
       service="general",
       resolution="Add 'ALL' to 'securityContext.capabilities.drop'")
def drop_capabilities(ctx):
    out = []
    for c in ctx.containers:
        drop = (_sc(c).get("capabilities") or {}).get("drop") or []
        if not any(str(d).upper() == "ALL" for d in drop):
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should add 'ALL' to "
                f"'securityContext.capabilities.drop'"))
    return out


@check("KSV005", "SYS_ADMIN capability added", severity="HIGH",
       file_types=_K, avd_id="AVD-KSV-0005", provider="kubernetes",
       service="general",
       resolution="Remove the SYS_ADMIN capability")
def sys_admin(ctx):
    out = []
    for c in ctx.containers:
        add = (_sc(c).get("capabilities") or {}).get("add") or []
        if any(str(a).upper() == "SYS_ADMIN" for a in add):
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should not include 'SYS_ADMIN' in "
                f"'securityContext.capabilities.add'"))
    return out


@check("KSV006", "hostPath volume mounts docker.sock", severity="HIGH",
       file_types=_K, avd_id="AVD-KSV-0006", provider="kubernetes",
       service="general",
       resolution="Do not mount /var/run/docker.sock")
def docker_sock(ctx):
    out = []
    for v in (ctx.pod_spec or {}).get("volumes") or []:
        hp = (v or {}).get("hostPath") or {}
        if hp.get("path") == "/var/run/docker.sock":
            out.append(Cause(
                message=f"{_name(ctx.resource)} should not mount "
                        f"'/var/run/docker.sock'",
                resource=_name(ctx.resource),
                start_line=get_line(v), end_line=get_end_line(v),
            ))
    return out


@check("KSV008", "Access to host IPC namespace", severity="HIGH",
       file_types=_K, avd_id="AVD-KSV-0008", provider="kubernetes",
       service="general", resolution="Set 'spec.hostIPC' to false")
def host_ipc(ctx):
    if (ctx.pod_spec or {}).get("hostIPC") is True:
        return [Cause(
            message=f"{_name(ctx.resource)} should not set "
                    f"'spec.template.spec.hostIPC' to true",
            resource=_name(ctx.resource),
            start_line=get_line(ctx.pod_spec),
            end_line=get_line(ctx.pod_spec),
        )]
    return []


@check("KSV009", "Access to host network", severity="HIGH",
       file_types=_K, avd_id="AVD-KSV-0009", provider="kubernetes",
       service="general", resolution="Set 'spec.hostNetwork' to false")
def host_network(ctx):
    if (ctx.pod_spec or {}).get("hostNetwork") is True:
        return [Cause(
            message=f"{_name(ctx.resource)} should not set "
                    f"'spec.template.spec.hostNetwork' to true",
            resource=_name(ctx.resource),
            start_line=get_line(ctx.pod_spec),
            end_line=get_line(ctx.pod_spec),
        )]
    return []


@check("KSV010", "Access to host PID", severity="HIGH", file_types=_K,
       avd_id="AVD-KSV-0010", provider="kubernetes", service="general",
       resolution="Set 'spec.hostPID' to false")
def host_pid(ctx):
    if (ctx.pod_spec or {}).get("hostPID") is True:
        return [Cause(
            message=f"{_name(ctx.resource)} should not set "
                    f"'spec.template.spec.hostPID' to true",
            resource=_name(ctx.resource),
            start_line=get_line(ctx.pod_spec),
            end_line=get_line(ctx.pod_spec),
        )]
    return []


@check("KSV011", "CPU not limited", severity="LOW", file_types=_K,
       avd_id="AVD-KSV-0011", provider="kubernetes", service="general",
       resolution="Set 'resources.limits.cpu'")
def cpu_limit(ctx):
    out = []
    for c in ctx.containers:
        limits = (c.get("resources") or {}).get("limits") or {}
        if "cpu" not in limits:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'resources.limits.cpu'"))
    return out


@check("KSV012", "Runs as root user", severity="MEDIUM", file_types=_K,
       avd_id="AVD-KSV-0012", provider="kubernetes", service="general",
       resolution="Set 'securityContext.runAsNonRoot' to true")
def run_as_non_root(ctx):
    out = []
    pod_nonroot = _pod_sc(ctx).get("runAsNonRoot") is True
    for c in ctx.containers:
        own = _sc(c).get("runAsNonRoot")
        # container-level setting overrides pod-level; only an unset
        # container inherits the pod default
        if own is True or (own is None and pod_nonroot):
            continue
        out.append(_container_cause(
            ctx, c,
            f"Container '{c.get('name', '')}' of {_name(ctx.resource)} "
            f"should set 'securityContext.runAsNonRoot' to true"))
    return out


@check("KSV013", "Image tag ':latest' used", severity="MEDIUM",
       file_types=_K, avd_id="AVD-KSV-0013", provider="kubernetes",
       service="general",
       resolution="Use a specific container image tag")
def image_tag(ctx):
    out = []
    for c in ctx.containers:
        image = str(c.get("image", ""))
        if not image or "@" in image:
            continue
        tail = image.split("/")[-1]
        tag = tail.rsplit(":", 1)[1] if ":" in tail else ""
        if not tag or tag == "latest":
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should specify an image tag"))
    return out


@check("KSV014", "Root file system is not read-only", severity="HIGH",
       file_types=_K, avd_id="AVD-KSV-0014", provider="kubernetes",
       service="general",
       resolution="Set 'securityContext.readOnlyRootFilesystem' to true")
def read_only_rootfs(ctx):
    out = []
    for c in ctx.containers:
        if _sc(c).get("readOnlyRootFilesystem") is not True:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'securityContext.readOnlyRootFilesystem' to true"))
    return out


@check("KSV015", "CPU requests not specified", severity="LOW",
       file_types=_K, avd_id="AVD-KSV-0015", provider="kubernetes",
       service="general", resolution="Set 'resources.requests.cpu'")
def cpu_request(ctx):
    out = []
    for c in ctx.containers:
        req = (c.get("resources") or {}).get("requests") or {}
        if "cpu" not in req:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'resources.requests.cpu'"))
    return out


@check("KSV016", "Memory requests not specified", severity="LOW",
       file_types=_K, avd_id="AVD-KSV-0016", provider="kubernetes",
       service="general", resolution="Set 'resources.requests.memory'")
def memory_request(ctx):
    out = []
    for c in ctx.containers:
        req = (c.get("resources") or {}).get("requests") or {}
        if "memory" not in req:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'resources.requests.memory'"))
    return out


@check("KSV017", "Privileged container", severity="HIGH", file_types=_K,
       avd_id="AVD-KSV-0017", provider="kubernetes", service="general",
       resolution="Set 'securityContext.privileged' to false")
def privileged(ctx):
    out = []
    for c in ctx.containers:
        if _sc(c).get("privileged") is True:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'securityContext.privileged' to false"))
    return out


@check("KSV018", "Memory not limited", severity="LOW", file_types=_K,
       avd_id="AVD-KSV-0018", provider="kubernetes", service="general",
       resolution="Set 'resources.limits.memory'")
def memory_limit(ctx):
    out = []
    for c in ctx.containers:
        limits = (c.get("resources") or {}).get("limits") or {}
        if "memory" not in limits:
            out.append(_container_cause(
                ctx, c,
                f"Container '{c.get('name', '')}' of "
                f"{_name(ctx.resource)} should set "
                f"'resources.limits.memory'"))
    return out


@check("KSV023", "hostPath volumes mounted", severity="MEDIUM",
       file_types=_K, avd_id="AVD-KSV-0023", provider="kubernetes",
       service="general", resolution="Do not set 'spec.volumes.hostPath'")
def host_path(ctx):
    out = []
    for v in (ctx.pod_spec or {}).get("volumes") or []:
        if (v or {}).get("hostPath"):
            out.append(Cause(
                message=f"{_name(ctx.resource)} should not set "
                        f"'spec.template.volumes.hostPath'",
                resource=_name(ctx.resource),
                start_line=get_line(v), end_line=get_end_line(v),
            ))
    return out
