"""Builtin checks (reference trivy-checks bundle embedded at
pkg/iac/rego/embed.go; IDs match the published DS/KSV/AVD-AWS rules)."""
