"""AWS check breadth wave: the service families the reference covers
through its typed provider schema + adapters (reference
pkg/iac/providers/aws/{apigateway,athena,cloudfront,cloudwatch,codebuild,
config,documentdb,dynamodb,ec2,ecr,ecs,eks,elasticache,elasticsearch,
elb,emr,iam,kinesis,kms,lambda,mq,msk,neptune,rds,redshift,ssm,
workspaces}/ and pkg/iac/adapters/{terraform,cloudformation}/aws/*).

Declarative layout: per-resource adapters normalize terraform blocks /
CloudFormation properties into CloudResource attrs with the reference's
unresolved-value semantics (None = unknown -> checks stay silent,
_tf_tristate / cfn_scalar), and a SPECS table registers one Check per
AVD rule. IDs/titles/severities follow the public AVD registry
(avd.aquasec.com/misconfig/aws)."""

from __future__ import annotations

from trivy_tpu.iac.checks.spec import (
    fail_if as _fail_if,
    lt as _lt,
    register_specs,
    tf_value as _v,
    tri as _tri,
)
from trivy_tpu.iac.parsers.hcl import Block, Expr
from trivy_tpu.iac.parsers.yamlconf import (
    cfn_scalar,
    get_end_line,
    get_line,
)

_C = ("terraform", "cloudformation", "terraformplan")


def _cfn_tri(props: dict, key: str, default):
    v = props.get(key)
    if v is None:
        return default
    if isinstance(v, dict):
        v = cfn_scalar(v)
        if v is None:
            return None
    if v in (True, "true", "True"):
        return True
    if v in (False, "false", "False"):
        return False
    return v


# ------------------------------------------------------------- terraform


def adapt_terraform_aws_ext(blocks: list[Block],
                            scan_blocks: list[Block] | None = None) -> list:
    from trivy_tpu.iac.checks.cloud import CloudResource

    import os as _os

    res = [b for b in blocks if b.type == "resource" and
           len(b.labels) >= 2]
    # account-level default EBS encryption overrides every instance /
    # launch-config block device to encrypted (reference adapters/
    # terraform/aws/ec2/{adapt,autoscaling}.go: `enabled` NotEqual(false)
    # — so unset or unresolved counts as enabled). Scoping differs by
    # resource kind, mirroring the reference exactly:
    # - ec2_instance_ext: the lookup spans ALL modules of the scan
    #   (adapt.go modules.GetResourcesByType), so the flag is computed
    #   over scan_blocks when the caller has wider context;
    # - launch_config: autoscaling.go runs module.GetResourcesByType
    #   inside its per-module loop — a default declared in the root
    #   module must NOT suppress launch-config findings in a child
    #   module. A module INSTANCE is identified by its module_id path
    #   (stamped by the terraform evaluator; two instantiations of the
    #   same source dir stay distinct) plus its source directory.
    def _module_key(b):
        return (getattr(b, "module_id", ""),
                _os.path.dirname(b.src_path))

    wide = scan_blocks if scan_blocks is not None else blocks
    ebs_defaults = [b for b in wide if b.type == "resource"
                    and b.labels[:1] == ["aws_ebs_encryption_by_default"]]
    ebs_default_enc = any(
        _tri(b, "enabled", True) is not False for b in ebs_defaults)
    ebs_default_dirs = {
        _module_key(b) for b in ebs_defaults
        if _tri(b, "enabled", True) is not False}
    out = []
    for b in res:
        t, name = b.labels[0], b.labels[1]
        fn = _TF.get(t)
        if fn is None:
            continue
        rtype, attrs = fn(b)
        if rtype == "ec2_instance_ext" and ebs_default_enc:
            attrs["unencrypted_block_device"] = False
        elif rtype == "launch_config" and \
                _module_key(b) in ebs_default_dirs:
            attrs["unencrypted_block_device"] = False
        out.append(CloudResource(
            type=rtype, name=f"{t}.{name}", attrs=attrs,
            start_line=b.start_line, end_line=b.end_line))
    return out


def _tf_apigw_stage(b):
    access_log = b.child("access_log_settings")
    settings = b.child("settings")  # method settings on api_gateway
    return "apigateway_stage", {
        "access_logging": access_log is not None,
        "xray": _tri(b, "xray_tracing_enabled", False),
        "cache_encrypted": _tri(settings, "cache_data_encrypted", False)
        if settings else None,
    }


def _tf_apigw_v2_stage(b):
    access_log = b.child("access_log_settings")
    return "apigateway_stage", {
        "access_logging": access_log is not None,
        "xray": None,       # X-Ray tracing is a REST (v1) stage knob
        "cache_encrypted": None,
    }


def _tf_apigw_method_settings(b):
    s = b.child("settings")
    return "apigateway_method_settings", {
        "cache_encrypted": _tri(s, "cache_data_encrypted", False),
    }


def _tf_apigw_domain(b):
    return "apigateway_domain", {
        "security_policy": _tri(b, "security_policy", None),
    }


def _tf_athena_workgroup(b):
    cfg = b.child("configuration")
    rc = cfg.child("result_configuration") if cfg else None
    enc = rc.child("encryption_configuration") if rc else None
    return "athena_workgroup", {
        "encrypted": enc is not None,
        "enforce": _tri(cfg, "enforce_workgroup_configuration", True)
        if cfg else True,
    }


def _tf_athena_database(b):
    enc = b.child("encryption_configuration")
    return "athena_database", {"encrypted": enc is not None}


def _tf_cloudfront(b):
    logging = b.child("logging_config")
    viewer = b.child("viewer_certificate")
    return "cloudfront_ext", {
        "logging": logging is not None,
        "waf": bool(_v(b.get("web_acl_id"))) or
        isinstance(b.get("web_acl_id"), Expr) or None
        if b.get("web_acl_id") is not None else False,
        "minimum_protocol_version": _tri(
            viewer, "minimum_protocol_version", "TLSv1")
        if viewer else "TLSv1",
    }


def _tf_cw_log_group(b):
    return "cloudwatch_log_group", {
        "kms": bool(_v(b.get("kms_key_id"))) if not isinstance(
            b.get("kms_key_id"), Expr) else None,
    }


def _tf_codebuild(b):
    arts = b.children("artifacts") + b.children("secondary_artifacts")
    disabled = [
        _tri(a, "encryption_disabled", False) for a in arts
    ]
    return "codebuild_project", {
        "encryption_disabled": True if any(d is True for d in disabled)
        else (None if any(d is None for d in disabled) else False),
    }


def _tf_config_aggregator(b):
    src = b.child("account_aggregation_source") or \
        b.child("organization_aggregation_source")
    return "config_aggregator", {
        "all_regions": _tri(src, "all_regions", False)
        if src else False,
    }


def _tf_docdb(b):
    exports = _v(b.get("enabled_cloudwatch_logs_exports"))
    return "docdb_cluster", {
        "log_exports": exports if isinstance(exports, list) else (
            None if isinstance(
                b.get("enabled_cloudwatch_logs_exports"), Expr) else []),
        "encrypted": _tri(b, "storage_encrypted", False),
        "kms": bool(_v(b.get("kms_key_id"))) if not isinstance(
            b.get("kms_key_id"), Expr) else None,
    }


def _tf_dax(b):
    sse = b.child("server_side_encryption")
    return "dax_cluster", {
        "encrypted": _tri(sse, "enabled", False) if sse else False,
    }


def _tf_dynamodb(b):
    sse = b.child("server_side_encryption")
    pitr = b.child("point_in_time_recovery")
    return "dynamodb_table", {
        "pitr": _tri(pitr, "enabled", False) if pitr else False,
        "cmk": bool(_v(sse.get("kms_key_arn"))) if sse is not None
        and not isinstance(sse.get("kms_key_arn"), Expr) else
        (None if sse is not None else False),
    }


def _block_device_attrs(b) -> dict:
    """Shared aws_instance / aws_launch_configuration block-device
    adaptation: the reference materializes a root device with
    encrypted=false even when the block is absent (adapters/terraform/
    aws/ec2/{adapt,autoscaling}.go) — a bare resource counts as
    unencrypted."""
    roots = b.children("root_block_device")
    devs = roots + b.children("ebs_block_device")
    encs = [_tri(d, "encrypted", False) for d in devs]
    if not roots:
        encs.append(False)
    return {
        "unencrypted_block_device": True if any(e is False for e in encs)
        else (None if any(e is None for e in encs) else False),
        "user_data": _v(b.get("user_data")),
    }


def _tf_launch_config(b):
    return "launch_config", _block_device_attrs(b)


def _tf_launch_template(b):
    encs = []
    for bd in b.children("block_device_mappings"):
        ebs = bd.child("ebs")
        if ebs is not None:
            encs.append(_tri(ebs, "encrypted", False))
    return "launch_template", {
        "unencrypted_block_device": True if any(
            e in (False, "false") for e in encs)
        else (None if any(e is None for e in encs) else False),
    }


def _tf_instance_ext(b):
    return "ec2_instance_ext", _block_device_attrs(b)


def _tf_nacl_rule(b):
    action = _v(b.get("rule_action"))
    proto = _v(b.get("protocol"))
    egress = _tri(b, "egress", False)
    return "network_acl_rule", {
        "action": str(action).lower() if action is not None else None,
        "protocol": str(proto) if proto is not None else None,
        "egress": egress,
        "cidr": _v(b.get("cidr_block")) or _v(
            b.get("ipv6_cidr_block")),
    }


def _tf_ecr(b):
    scan = b.child("image_scanning_configuration")
    enc = b.child("encryption_configuration")
    return "ecr_repository", {
        "scan_on_push": _tri(scan, "scan_on_push", False)
        if scan else False,
        "immutable": _v(b.get("image_tag_mutability")) == "IMMUTABLE"
        if not isinstance(b.get("image_tag_mutability"), Expr)
        else None,
        "cmk": (_tri(enc, "encryption_type", "AES256") == "KMS")
        if enc else False,
    }


def _tf_ecr_policy(b):
    from trivy_tpu.iac.checks.cloud import _policy_doc

    return "ecr_policy", {
        "document": _policy_doc(_v(b.get("policy"))),
    }


def _tf_ecs_cluster(b):
    insights = None
    for s in b.children("setting"):
        if _v(s.get("name")) == "containerInsights":
            insights = _v(s.get("value"))
    return "ecs_cluster", {
        "container_insights": str(insights).lower() == "enabled"
        if insights is not None else False,
    }


def _tf_ecs_task(b):
    import json as _json

    raw = _v(b.get("container_definitions"))
    defs = None
    if isinstance(raw, str):
        try:
            defs = _json.loads(raw)
        except ValueError:
            defs = None
    elif isinstance(raw, list):
        defs = raw
    plaintext = False
    if isinstance(defs, list):
        for d in defs:
            for env in (d.get("environment") or []) \
                    if isinstance(d, dict) else []:
                nm = str(env.get("name", "")).upper()
                if any(k in nm for k in ("SECRET", "PASSWORD", "TOKEN",
                                         "API_KEY", "ACCESS_KEY")):
                    plaintext = True
    transit = []
    for vol in b.children("volume"):
        e = vol.child("efs_volume_configuration")
        if e is not None:
            transit.append(_tri(e, "transit_encryption", "DISABLED"))
    return "ecs_task", {
        "plaintext_secret": plaintext if defs is not None else None,
        "efs_unencrypted_transit": True if any(
            str(t).upper() == "DISABLED" for t in transit)
        else (None if any(t is None for t in transit) else False),
    }


def _tf_eks_ext(b):
    enabled = _v(b.get("enabled_cluster_log_types"))
    enc = b.child("encryption_config")
    return "eks_cluster_ext", {
        "logging": bool(enabled) if not isinstance(
            b.get("enabled_cluster_log_types"), Expr) else None,
        "secrets_encrypted": enc is not None,
    }


def _tf_elasticache_redis(b):
    # encryption flags only: the reference adapts snapshot retention
    # for clusters, not replication groups (adapters/terraform/aws/
    # elasticache/adapt.go adaptReplicationGroup)
    return "elasticache_group", {
        "at_rest": _tri(b, "at_rest_encryption_enabled", False),
        "in_transit": _tri(b, "transit_encryption_enabled", False),
    }


def _tf_elasticache_cluster(b):
    engine = _v(b.get("engine"))
    return "elasticache_cluster", {
        "engine": engine,
        "backup_retention": _tri(b, "snapshot_retention_limit", 0),
    }


def _tf_es_domain(b):
    enc = b.child("encrypt_at_rest")
    n2n = b.child("node_to_node_encryption")
    ep = b.child("domain_endpoint_options")
    logs = b.children("log_publishing_options")
    audit = any(_v(l.get("log_type")) == "AUDIT_LOGS" for l in logs)
    return "elasticsearch_domain", {
        "at_rest": _tri(enc, "enabled", False) if enc else False,
        "in_transit": _tri(n2n, "enabled", False) if n2n else False,
        "enforce_https": _tri(ep, "enforce_https", False)
        if ep else False,
        "tls_policy": _tri(ep, "tls_security_policy",
                           "Policy-Min-TLS-1-0-2019-07")
        if ep else "Policy-Min-TLS-1-0-2019-07",
        "audit_logging": audit,
    }


def _tf_lb(b):
    internal = _tri(b, "internal", False)
    # absent -> provider default "application"; unresolved -> None
    lb_type = _tri(b, "load_balancer_type", "application")
    return "lb", {
        "internal": internal,
        # drop_invalid_header_fields only exists on ALBs; other (or
        # unknown) LB kinds must stay silent on AVD-AWS-0052
        "drop_invalid_headers": _tri(
            b, "drop_invalid_header_fields", False)
        if lb_type == "application" else None,
        "lb_type": lb_type,
    }


def _tf_classic_elb(b):
    return "lb", {
        "internal": _tri(b, "internal", False),
        "drop_invalid_headers": None,   # not a classic-ELB setting
        "lb_type": "classic",
    }


def _tf_lb_listener_ext(b):
    return "lb_listener_ext", {
        "protocol": _v(b.get("protocol")),
        "ssl_policy": _v(b.get("ssl_policy")),
    }


def _tf_emr_security_config(b):
    import json as _json

    raw = _v(b.get("configuration"))
    doc = None
    if isinstance(raw, str):
        try:
            doc = _json.loads(raw)
        except ValueError:
            doc = None
    at_rest = in_transit = local_disk = None
    if isinstance(doc, dict):
        enc = doc.get("EncryptionConfiguration") or {}
        at_rest = bool(enc.get("EnableAtRestEncryption"))
        in_transit = bool(enc.get("EnableInTransitEncryption"))
        local_disk = bool(
            (enc.get("AtRestEncryptionConfiguration") or {})
            .get("LocalDiskEncryptionConfiguration"))
    return "emr_security_config", {
        "at_rest": at_rest, "in_transit": in_transit,
        "local_disk": local_disk,
    }


def _tf_iam_password_policy(b):
    return "iam_password_policy", {
        "reuse_prevention": _tri(b, "password_reuse_prevention", 0),
        "require_lowercase": _tri(b, "require_lowercase_characters",
                                  False),
        "require_numbers": _tri(b, "require_numbers", False),
        "require_symbols": _tri(b, "require_symbols", False),
        "require_uppercase": _tri(b, "require_uppercase_characters",
                                  False),
        "max_age": _tri(b, "max_password_age", 0),
        "min_length": _tri(b, "minimum_password_length", 6),
    }


def _tf_kinesis(b):
    return "kinesis_stream", {
        "encrypted": _v(b.get("encryption_type")) == "KMS"
        if not isinstance(b.get("encryption_type"), Expr) else None,
    }


def _tf_kms(b):
    return "kms_key", {
        "rotation": _tri(b, "enable_key_rotation", False),
        "usage": _v(b.get("key_usage")) or "ENCRYPT_DECRYPT",
    }


def _tf_lambda(b):
    tracing = b.child("tracing_config")
    return "lambda_function", {
        "tracing": _tri(tracing, "mode", "PassThrough")
        if tracing else "PassThrough",
    }


def _tf_lambda_permission(b):
    return "lambda_permission", {
        "has_source_arn": b.get("source_arn") is not None,
        "principal": _v(b.get("principal")),
    }


def _tf_mq(b):
    logs = b.child("logs")
    return "mq_broker", {
        "general_logging": _tri(logs, "general", False)
        if logs else False,
        "audit_logging": _tri(logs, "audit", False) if logs else False,
        "public": _tri(b, "publicly_accessible", False),
    }


def _tf_msk(b):
    info = b.child("broker_node_group_info")  # noqa: F841
    enc = b.child("encryption_info")
    tls = None
    at_rest_kms = None
    if enc is not None:
        eit = enc.child("encryption_in_transit")
        tls = _tri(eit, "client_broker", "TLS") if eit else "TLS"
        at_rest_kms = bool(_v(enc.get(
            "encryption_at_rest_kms_key_arn"))) if not isinstance(
            enc.get("encryption_at_rest_kms_key_arn"), Expr) else None
    logging = False
    li = b.child("logging_info")
    if li is not None:
        bl = li.child("broker_logs")
        if bl is not None:
            for kind in ("cloudwatch_logs", "firehose", "s3"):
                c = bl.child(kind)
                if c is not None and _tri(c, "enabled", False) is True:
                    logging = True
    return "msk_cluster", {
        "client_broker": tls if enc is not None else "TLS_PLAINTEXT",
        "at_rest_cmk": at_rest_kms if enc is not None else False,
        "logging": logging,
    }


def _tf_neptune(b):
    exports = _v(b.get("enable_cloudwatch_logs_exports"))
    return "neptune_cluster", {
        "audit_logging": ("audit" in exports) if isinstance(
            exports, list) else (None if isinstance(
                b.get("enable_cloudwatch_logs_exports"), Expr)
                else False),
        "encrypted": _tri(b, "storage_encrypted", False),
    }


def _tf_rds_cluster(b):
    return "rds_cluster", {
        "encrypted": _tri(b, "storage_encrypted", False),
        "backup_retention": _tri(b, "backup_retention_period", 1),
    }


def _tf_rds_instance_ext(b):
    return "rds_instance_ext", {
        "backup_retention": _tri(b, "backup_retention_period", 0),
        "perf_insights": _tri(b, "performance_insights_enabled", False),
        "perf_insights_kms": bool(_v(b.get(
            "performance_insights_kms_key_id"))) if not isinstance(
            b.get("performance_insights_kms_key_id"), Expr) else None,
        "iam_auth": _tri(
            b, "iam_database_authentication_enabled", False),
        "deletion_protection": _tri(b, "deletion_protection", False),
    }


def _tf_redshift(b):
    return "redshift_cluster", {
        "encrypted": _tri(b, "encrypted", False),
        "cmk": bool(_v(b.get("kms_key_id"))) if not isinstance(
            b.get("kms_key_id"), Expr) else None,
        "public": _tri(b, "publicly_accessible", True),
        "in_vpc": b.get("cluster_subnet_group_name") is not None,
        "logging": _tri(b.child("logging"), "enable", False)
        if b.child("logging") else False,
    }


def _tf_ssm_secret(b):
    return "ssm_secret", {
        "cmk": bool(_v(b.get("kms_key_id"))) if not isinstance(
            b.get("kms_key_id"), Expr) else None,
    }


def _tf_workspaces(b):
    root = b.child("workspace_properties")  # noqa: F841
    return "workspaces_workspace", {
        "root_encrypted": _tri(b, "root_volume_encryption_enabled",
                               False),
        "user_encrypted": _tri(b, "user_volume_encryption_enabled",
                               False),
    }


_TF = {
    "aws_api_gateway_stage": _tf_apigw_stage,
    "aws_apigatewayv2_stage": _tf_apigw_v2_stage,
    "aws_api_gateway_method_settings": _tf_apigw_method_settings,
    "aws_api_gateway_domain_name": _tf_apigw_domain,
    "aws_athena_workgroup": _tf_athena_workgroup,
    "aws_athena_database": _tf_athena_database,
    "aws_cloudfront_distribution": _tf_cloudfront,
    "aws_cloudwatch_log_group": _tf_cw_log_group,
    "aws_codebuild_project": _tf_codebuild,
    "aws_config_configuration_aggregator": _tf_config_aggregator,
    "aws_docdb_cluster": _tf_docdb,
    "aws_dax_cluster": _tf_dax,
    "aws_dynamodb_table": _tf_dynamodb,
    "aws_launch_configuration": _tf_launch_config,
    "aws_launch_template": _tf_launch_template,
    "aws_instance": _tf_instance_ext,
    "aws_network_acl_rule": _tf_nacl_rule,
    "aws_ecr_repository": _tf_ecr,
    "aws_ecr_repository_policy": _tf_ecr_policy,
    "aws_ecs_cluster": _tf_ecs_cluster,
    "aws_ecs_task_definition": _tf_ecs_task,
    "aws_eks_cluster": _tf_eks_ext,
    "aws_elasticache_replication_group": _tf_elasticache_redis,
    "aws_elasticache_cluster": _tf_elasticache_cluster,
    "aws_elasticsearch_domain": _tf_es_domain,
    "aws_opensearch_domain": _tf_es_domain,
    "aws_lb": _tf_lb,
    "aws_alb": _tf_lb,
    "aws_elb": _tf_classic_elb,
    "aws_lb_listener": _tf_lb_listener_ext,
    "aws_alb_listener": _tf_lb_listener_ext,
    "aws_emr_security_configuration": _tf_emr_security_config,
    "aws_iam_account_password_policy": _tf_iam_password_policy,
    "aws_kinesis_stream": _tf_kinesis,
    "aws_kms_key": _tf_kms,
    "aws_lambda_function": _tf_lambda,
    "aws_lambda_permission": _tf_lambda_permission,
    "aws_mq_broker": _tf_mq,
    "aws_msk_cluster": _tf_msk,
    "aws_neptune_cluster": _tf_neptune,
    "aws_rds_cluster": _tf_rds_cluster,
    "aws_db_instance": _tf_rds_instance_ext,
    "aws_redshift_cluster": _tf_redshift,
    "aws_secretsmanager_secret": _tf_ssm_secret,
    "aws_workspaces_workspace": _tf_workspaces,
}


# -------------------------------------------------------- cloudformation


def adapt_cloudformation_aws_ext(resources: dict[str, dict]) -> list:
    from trivy_tpu.iac.checks.cloud import CloudResource

    out = []
    for name, res in resources.items():
        rtype = str(res.get("Type", ""))
        fn = _CFN.get(rtype)
        ctx_fn = _CFN_CTX.get(rtype)
        if fn is None and ctx_fn is None:
            continue
        props = res.get("Properties") or {}
        if ctx_fn is not None:
            # context adapters also see the full resource map (e.g. to
            # resolve launch-template references)
            adapted = ctx_fn(props, resources)
        else:
            adapted = fn(props)
        # an adapter may emit one (rtype, attrs) pair or several
        if isinstance(adapted, tuple):
            adapted = [adapted]
        for ct, attrs in adapted:
            out.append(CloudResource(
                type=ct, name=name, attrs=attrs,
                start_line=get_line(res), end_line=get_end_line(res)))
    return out


def _cfn_apigw_stage(p):
    return "apigateway_stage", {
        "access_logging": bool(p.get("AccessLogSetting")
                               or p.get("AccessLogSettings")),
        "xray": _cfn_tri(p, "TracingEnabled", False),
        "cache_encrypted": None,
    }


def _cfn_apigw_v2_stage(p):
    return "apigateway_stage", {
        "access_logging": bool(p.get("AccessLogSettings")),
        "xray": None,       # not a v2 property
        "cache_encrypted": None,
    }


def _cfn_cloudfront(p):
    cfg = p.get("DistributionConfig") or {}
    viewer = cfg.get("ViewerCertificate") or {}
    return "cloudfront_ext", {
        "logging": bool(cfg.get("Logging")),
        "waf": bool(cfg.get("WebACLId")),
        "minimum_protocol_version": cfn_scalar(
            viewer.get("MinimumProtocolVersion")) or "TLSv1",
    }


def _cfn_cw_log_group(p):
    return "cloudwatch_log_group", {
        "kms": bool(p.get("KmsKeyId")),
    }


def _cfn_codebuild(p):
    arts = [p.get("Artifacts") or {}] + list(
        p.get("SecondaryArtifacts") or [])
    disabled = [_cfn_tri(a, "EncryptionDisabled", False)
                for a in arts if isinstance(a, dict)]
    return "codebuild_project", {
        "encryption_disabled": True if any(d is True for d in disabled)
        else (None if any(d is None for d in disabled) else False),
    }


def _cfn_config_aggregator(p):
    srcs = list(p.get("AccountAggregationSources") or [])
    org = p.get("OrganizationAggregationSource")
    if isinstance(org, dict):
        srcs.append(org)
    all_regions = any(_cfn_tri(s, "AllAwsRegions", False) is True
                     for s in srcs if isinstance(s, dict))
    return "config_aggregator", {"all_regions": all_regions}


def _cfn_docdb(p):
    exports = p.get("EnableCloudwatchLogsExports")
    return "docdb_cluster", {
        "log_exports": exports if isinstance(exports, list) else [],
        "encrypted": _cfn_tri(p, "StorageEncrypted", False),
        "kms": bool(p.get("KmsKeyId")),
    }


def _cfn_dynamodb(p):
    sse = p.get("SSESpecification") or {}
    pitr = p.get("PointInTimeRecoverySpecification") or {}
    return "dynamodb_table", {
        "pitr": _cfn_tri(pitr, "PointInTimeRecoveryEnabled", False),
        "cmk": bool(sse.get("KMSMasterKeyId")),
    }


def _cfn_ecr(p):
    scan = p.get("ImageScanningConfiguration") or {}
    enc = p.get("EncryptionConfiguration") or {}
    return "ecr_repository", {
        "scan_on_push": _cfn_tri(scan, "ScanOnPush", False),
        "immutable": cfn_scalar(p.get("ImageTagMutability"))
        == "IMMUTABLE",
        "cmk": cfn_scalar(enc.get("EncryptionType")) == "KMS",
    }


def _cfn_ecs_cluster(p):
    insights = False
    for s in p.get("ClusterSettings") or []:
        if isinstance(s, dict) and \
                cfn_scalar(s.get("Name")) == "containerInsights":
            insights = cfn_scalar(s.get("Value")) == "enabled"
    return "ecs_cluster", {"container_insights": insights}


def _cfn_eks(p):
    enc = p.get("EncryptionConfig")
    logging = p.get("Logging") or {}
    enabled = []
    for t in ((logging.get("ClusterLogging") or {})
              .get("EnabledTypes") or []):
        if isinstance(t, dict):
            enabled.append(t.get("Type"))
    return "eks_cluster_ext", {
        "logging": bool(enabled),
        "secrets_encrypted": bool(enc),
    }


def _cfn_es(p):
    enc = p.get("EncryptionAtRestOptions") or {}
    n2n = p.get("NodeToNodeEncryptionOptions") or {}
    ep = p.get("DomainEndpointOptions") or {}
    return "elasticsearch_domain", {
        "at_rest": _cfn_tri(enc, "Enabled", False),
        "in_transit": _cfn_tri(n2n, "Enabled", False),
        "enforce_https": _cfn_tri(ep, "EnforceHTTPS", False),
        "tls_policy": cfn_scalar(ep.get("TLSSecurityPolicy"))
        or "Policy-Min-TLS-1-0-2019-07",
        "audit_logging": "AUDIT_LOGS" in (
            p.get("LogPublishingOptions") or {}),
    }


def _cfn_lb(p):
    # CFN default Scheme for ELBv2 is internet-facing
    scheme = cfn_scalar(p.get("Scheme")) or "internet-facing"
    attrs = {cfn_scalar(a.get("Key")): cfn_scalar(a.get("Value"))
             for a in p.get("LoadBalancerAttributes") or []
             if isinstance(a, dict)}
    return "lb", {
        "internal": scheme != "internet-facing",
        "drop_invalid_headers": attrs.get(
            "routing.http.drop_invalid_header_fields.enabled")
        in ("true", True),
        "lb_type": cfn_scalar(p.get("Type")) or "application",
    }


def _cfn_kinesis(p):
    enc = p.get("StreamEncryption") or {}
    return "kinesis_stream", {
        "encrypted": cfn_scalar(enc.get("EncryptionType")) == "KMS",
    }


def _cfn_kms(p):
    return "kms_key", {
        "rotation": _cfn_tri(p, "EnableKeyRotation", False),
        "usage": cfn_scalar(p.get("KeyUsage")) or "ENCRYPT_DECRYPT",
    }


def _cfn_lambda(p):
    tracing = p.get("TracingConfig") or {}
    return "lambda_function", {
        "tracing": cfn_scalar(tracing.get("Mode")) or "PassThrough",
    }


def _cfn_lambda_permission(p):
    return "lambda_permission", {
        "has_source_arn": p.get("SourceArn") is not None,
        "principal": cfn_scalar(p.get("Principal")),
    }


def _cfn_mq(p):
    logs = p.get("Logs") or {}
    return "mq_broker", {
        "general_logging": _cfn_tri(logs, "General", False),
        "audit_logging": _cfn_tri(logs, "Audit", False),
        "public": _cfn_tri(p, "PubliclyAccessible", False),
    }


def _cfn_msk(p):
    enc = p.get("EncryptionInfo") or {}
    transit = enc.get("EncryptionInTransit") or {}
    at_rest = enc.get("EncryptionAtRest") or {}
    logging = False
    li = ((p.get("LoggingInfo") or {}).get("BrokerLogs") or {})
    for kind in ("CloudWatchLogs", "Firehose", "S3"):
        if _cfn_tri(li.get(kind) or {}, "Enabled", False) is True:
            logging = True
    return "msk_cluster", {
        "client_broker": cfn_scalar(transit.get("ClientBroker"))
        or "TLS",
        "at_rest_cmk": bool(at_rest.get("DataVolumeKMSKeyId")),
        "logging": logging,
    }


def _cfn_neptune(p):
    return "neptune_cluster", {
        "audit_logging": "audit" in (
            p.get("EnableCloudwatchLogsExports") or []),
        "encrypted": _cfn_tri(p, "StorageEncrypted", False),
    }


def _cfn_rds_cluster(p):
    return "rds_cluster", {
        "encrypted": _cfn_tri(p, "StorageEncrypted", False),
        "backup_retention": _cfn_tri(p, "BackupRetentionPeriod", 1),
    }


def _cfn_rds_instance_ext(p):
    return "rds_instance_ext", {
        "backup_retention": _cfn_tri(p, "BackupRetentionPeriod", 0),
        "perf_insights": _cfn_tri(p, "EnablePerformanceInsights",
                                  False),
        "perf_insights_kms": bool(p.get("PerformanceInsightsKMSKeyId")),
        "iam_auth": _cfn_tri(
            p, "EnableIAMDatabaseAuthentication", False),
        "deletion_protection": _cfn_tri(p, "DeletionProtection", False),
    }


def _cfn_redshift(p):
    return "redshift_cluster", {
        "encrypted": _cfn_tri(p, "Encrypted", False),
        "cmk": bool(p.get("KmsKeyId")),
        "public": _cfn_tri(p, "PubliclyAccessible", True),
        "in_vpc": p.get("ClusterSubnetGroupName") is not None,
        "logging": bool(p.get("LoggingProperties")),
    }


def _cfn_ssm_secret(p):
    return "ssm_secret", {"cmk": bool(p.get("KmsKeyId"))}


def _cfn_workspaces(p):
    return "workspaces_workspace", {
        "root_encrypted": _cfn_tri(p, "RootVolumeEncryptionEnabled",
                                   False),
        "user_encrypted": _cfn_tri(p, "UserVolumeEncryptionEnabled",
                                   False),
    }


def _cfn_device_encs(devs) -> list:
    encs = []
    if isinstance(devs, list):
        for d in devs:
            if isinstance(d, dict):
                ebs = d.get("Ebs") or {}
                encs.append(_cfn_tri(ebs if isinstance(ebs, dict) else {},
                                     "Encrypted", False))
    return encs


def _cfn_find_launch_template(lt: dict, resources: dict) -> dict | None:
    """Resolve Properties.LaunchTemplate -> the referenced
    AWS::EC2::LaunchTemplate's LaunchTemplateData (reference
    findRelatedLaunchTemplate: by LaunchTemplateName string match, else
    by LaunchTemplateId as a logical id; unresolvable refs fall
    through)."""
    name = lt.get("LaunchTemplateName")
    if isinstance(name, str):
        for res in resources.values():
            if str(res.get("Type", "")) != "AWS::EC2::LaunchTemplate":
                continue
            props = res.get("Properties") or {}
            if props.get("LaunchTemplateName") == name:
                data = props.get("LaunchTemplateData")
                return data if isinstance(data, dict) else {}
    ltid = lt.get("LaunchTemplateId")
    if isinstance(ltid, dict):
        # canonical same-template reference: {"Ref": "LogicalId"}
        ref = ltid.get("Ref")
        ltid = ref if isinstance(ref, str) else None
    if isinstance(ltid, str) and ltid in resources:
        res = resources[ltid]
        if str(res.get("Type", "")) == "AWS::EC2::LaunchTemplate":
            props = res.get("Properties") or {}
            data = props.get("LaunchTemplateData")
            return data if isinstance(data, dict) else {}
    return None


def _cfn_ec2_instance(p, resources=None):
    """AWS::EC2::Instance (reference adapters/cloudformation/aws/ec2/
    instance.go): an instance config comes from its launch template
    when one resolves; otherwise CloudFormation cannot express metadata
    options, so IMDS stays at the provider default (optional tokens —
    the check fires), and the first BlockDeviceMappings entry is the
    root device with a missing list materializing an unencrypted
    root.

    After a template resolve the reference OVERLAYS the instance's own
    BlockDeviceMappings on top of the replacement (the first entry
    overrides the root device) — and its adaptLaunchTemplate reads
    BlockDeviceMappings from top-level Properties, not
    LaunchTemplateData, so a template effectively contributes only
    MetadataOptions: an instance with no mappings of its own still
    materializes an unencrypted root."""
    tokens = None  # None = not configured -> IMDS check fires
    lt = p.get("LaunchTemplate")
    data = None
    if isinstance(lt, dict) and resources:
        data = _cfn_find_launch_template(lt, resources)
    if data is not None:
        # the reference replaces the instance wholesale with the
        # template's adaptation (instance = launchTemplate.Instance)...
        opts = data.get("MetadataOptions")
        if isinstance(opts, dict):
            tokens = _cfn_tri(opts, "HttpTokens", "optional")
        else:
            tokens = "optional"
    # ...then always applies the instance's own BlockDeviceMappings
    # (instance.go overlay loop) — the template side carries none (see
    # docstring), so the instance's list is the only block-device source
    encs = _cfn_device_encs(p.get("BlockDeviceMappings"))
    if not encs:
        encs.append(False)  # materialized unencrypted root
    unenc = (True if any(e is False for e in encs)
             else (None if any(e is None for e in encs) else False))
    return [
        ("ec2_instance_ext", {"unencrypted_block_device": unenc}),
        ("ec2_instance", {"http_tokens": tokens}),
    ]


def _cfn_num(p: dict, key: str, default):
    """Numeric CFN property: absent -> default, unresolved -> None —
    without _cfn_tri's bool coercion (0 must stay 0, not become False
    and slip past numeric checks' bool guards)."""
    v = p.get(key)
    if v is None:
        return default
    if isinstance(v, dict):
        v = cfn_scalar(v)
        if v is None:
            return None
    if isinstance(v, bool):
        return None
    if isinstance(v, str):
        try:
            return float(v)
        except ValueError:
            return None
    return v if isinstance(v, (int, float)) else None


def _cfn_elasticache_group(p):
    # the reference adapts only the two encryption flags for
    # replication groups (adapters/cloudformation/aws/elasticache/
    # replication_group.go); snapshot retention is a CLUSTER concern
    return "elasticache_group", {
        "at_rest": _cfn_tri(p, "AtRestEncryptionEnabled", False),
        "in_transit": _cfn_tri(p, "TransitEncryptionEnabled", False),
    }


def _cfn_elasticache_cluster(p):
    """AWS::ElastiCache::CacheCluster (reference adapters/
    cloudformation/aws/elasticache/cluster.go)."""
    return "elasticache_cluster", {
        "engine": cfn_scalar(p.get("Engine")),
        "backup_retention": _cfn_num(p, "SnapshotRetentionLimit", 0),
    }


# adapters that need the whole resource map (cross-resource resolution)
_CFN_CTX = {
    "AWS::EC2::Instance": _cfn_ec2_instance,
}

_CFN = {
    "AWS::ElastiCache::ReplicationGroup": _cfn_elasticache_group,
    "AWS::ElastiCache::CacheCluster": _cfn_elasticache_cluster,
    "AWS::ApiGateway::Stage": _cfn_apigw_stage,
    "AWS::ApiGatewayV2::Stage": _cfn_apigw_v2_stage,
    "AWS::CloudFront::Distribution": _cfn_cloudfront,
    "AWS::Logs::LogGroup": _cfn_cw_log_group,
    "AWS::CodeBuild::Project": _cfn_codebuild,
    "AWS::Config::ConfigurationAggregator": _cfn_config_aggregator,
    "AWS::DocDB::DBCluster": _cfn_docdb,
    "AWS::DynamoDB::Table": _cfn_dynamodb,
    "AWS::ECR::Repository": _cfn_ecr,
    "AWS::ECS::Cluster": _cfn_ecs_cluster,
    "AWS::EKS::Cluster": _cfn_eks,
    "AWS::Elasticsearch::Domain": _cfn_es,
    "AWS::OpenSearchService::Domain": _cfn_es,
    "AWS::ElasticLoadBalancingV2::LoadBalancer": _cfn_lb,
    "AWS::Kinesis::Stream": _cfn_kinesis,
    "AWS::KMS::Key": _cfn_kms,
    "AWS::Lambda::Function": _cfn_lambda,
    "AWS::Lambda::Permission": _cfn_lambda_permission,
    "AWS::AmazonMQ::Broker": _cfn_mq,
    "AWS::MSK::Cluster": _cfn_msk,
    "AWS::Neptune::DBCluster": _cfn_neptune,
    "AWS::RDS::DBCluster": _cfn_rds_cluster,
    "AWS::RDS::DBInstance": _cfn_rds_instance_ext,
    "AWS::Redshift::Cluster": _cfn_redshift,
    "AWS::SecretsManager::Secret": _cfn_ssm_secret,
    "AWS::WorkSpaces::Workspace": _cfn_workspaces,
}


# ----------------------------------------------------------------- checks


# (id, title, severity, rtype, service, test, resolution)
SPECS = [
    # --- API Gateway (providers/aws/apigateway)
    ("AVD-AWS-0001", "API Gateway stage has no access logging", "MEDIUM",
     "apigateway_stage", "api-gateway",
     _fail_if("access_logging", (False,),
              "Access logging is not configured"),
     "Enable access logging on the stage"),
    ("AVD-AWS-0002", "API Gateway stage cache is unencrypted", "MEDIUM",
     "apigateway_method_settings", "api-gateway",
     _fail_if("cache_encrypted", (False,),
              "Cache data is not encrypted"),
     "Enable cache encryption"),
    ("AVD-AWS-0003", "API Gateway stage X-Ray tracing is disabled",
     "LOW", "apigateway_stage", "api-gateway",
     _fail_if("xray", (False,), "X-Ray tracing is not enabled"),
     "Enable X-Ray tracing"),
    ("AVD-AWS-0004", "API Gateway domain uses an outdated TLS policy",
     "HIGH", "apigateway_domain", "api-gateway",
     _fail_if("security_policy", ("TLS_1_0",),
              "Domain name uses TLS 1.0"),
     "Use TLS_1_2 as the security policy"),
    # --- Athena
    ("AVD-AWS-0006", "Athena database/workgroup is unencrypted", "HIGH",
     ("athena_workgroup", "athena_database"), "athena",
     _fail_if("encrypted", (False,),
              "Results/database encryption is not configured"),
     "Configure encryption for the workgroup and database"),
    ("AVD-AWS-0007", "Athena workgroup does not enforce its "
     "configuration", "HIGH", "athena_workgroup", "athena",
     _fail_if("enforce", (False,),
              "Workgroup configuration can be overridden by clients"),
     "Set enforce_workgroup_configuration = true"),
    # --- CloudFront
    ("AVD-AWS-0010", "CloudFront distribution has no access logging",
     "MEDIUM", "cloudfront_ext", "cloudfront",
     _fail_if("logging", (False,), "Access logging is not configured"),
     "Add a logging_config block"),
    ("AVD-AWS-0011", "CloudFront distribution has no WAF", "HIGH",
     "cloudfront_ext", "cloudfront",
     _fail_if("waf", (False,), "No Web ACL is associated"),
     "Associate a WAF web ACL"),
    ("AVD-AWS-0013", "CloudFront uses an outdated SSL/TLS protocol",
     "HIGH", "cloudfront_ext", "cloudfront",
     _fail_if("minimum_protocol_version",
              ("TLSv1", "TLSv1_2016", "TLSv1.1_2016", "SSLv3"),
              "Viewer certificate allows pre-TLS1.2 protocols"),
     "Set minimum_protocol_version to TLSv1.2_2021"),
    # --- CloudWatch
    ("AVD-AWS-0017", "CloudWatch log group is not CMK-encrypted", "LOW",
     "cloudwatch_log_group", "cloudwatch",
     _fail_if("kms", (False,),
              "Log group is not encrypted with a customer key"),
     "Set kms_key_id on the log group"),
    # --- CodeBuild
    ("AVD-AWS-0018", "CodeBuild project artifacts are unencrypted",
     "HIGH", "codebuild_project", "codebuild",
     _fail_if("encryption_disabled", (True,),
              "Artifact encryption is disabled"),
     "Do not set encryption_disabled"),
    # --- Config
    ("AVD-AWS-0019", "Config aggregator does not cover all regions",
     "HIGH", "config_aggregator", "config",
     _fail_if("all_regions", (False,),
              "Aggregator does not aggregate all regions"),
     "Set all_regions = true on the aggregation source"),
    # --- DocumentDB
    ("AVD-AWS-0020", "DocumentDB cluster does not export logs",
     "MEDIUM", "docdb_cluster", "documentdb",
     lambda a: None if a.get("log_exports") is None else (
         "Neither audit nor profiler log export is enabled"
         if not any(x in ("audit", "profiler")
                    for x in a["log_exports"]) else False),
     "Enable audit/profiler CloudWatch log exports"),
    ("AVD-AWS-0021", "DocumentDB cluster storage is unencrypted",
     "HIGH", "docdb_cluster", "documentdb",
     _fail_if("encrypted", (False,), "Storage is not encrypted"),
     "Set storage_encrypted = true"),
    ("AVD-AWS-0022", "DocumentDB cluster is not CMK-encrypted", "LOW",
     "docdb_cluster", "documentdb",
     _fail_if("kms", (False,),
              "Cluster is not encrypted with a customer key"),
     "Set kms_key_id"),
    # --- DynamoDB
    ("AVD-AWS-0023", "DAX cluster is unencrypted", "HIGH",
     "dax_cluster", "dynamodb",
     _fail_if("encrypted", (False,),
              "Server-side encryption is not enabled"),
     "Enable server_side_encryption"),
    ("AVD-AWS-0024", "DynamoDB table has no point-in-time recovery",
     "MEDIUM", "dynamodb_table", "dynamodb",
     _fail_if("pitr", (False,),
              "Point-in-time recovery is not enabled"),
     "Enable point_in_time_recovery"),
    ("AVD-AWS-0025", "DynamoDB table is not CMK-encrypted", "LOW",
     "dynamodb_table", "dynamodb",
     _fail_if("cmk", (False,),
              "Server-side encryption does not use a customer key"),
     "Set server_side_encryption.kms_key_arn"),
    # --- EC2
    ("AVD-AWS-0008", "Launch configuration has an unencrypted block "
     "device", "HIGH", "launch_config", "ec2",
     _fail_if("unencrypted_block_device", (True,),
              "Block device is not encrypted"),
     "Encrypt every block device"),
    ("AVD-AWS-0009", "Launch template has an unencrypted block device",
     "HIGH", "launch_template", "ec2",
     _fail_if("unencrypted_block_device", (True,),
              "Block device is not encrypted"),
     "Encrypt every block device mapping"),
    ("AVD-AWS-0131", "EC2 instance has an unencrypted block device",
     "HIGH", "ec2_instance_ext", "ec2",
     _fail_if("unencrypted_block_device", (True,),
              "Root or EBS block device is not encrypted"),
     "Set encrypted = true on block devices"),
    ("AVD-AWS-0102", "Network ACL rule allows all protocols",
     "CRITICAL", "network_acl_rule", "ec2",
     lambda a: None if a.get("protocol") is None or
     a.get("action") is None else (
         "Rule allows every protocol"
         if a["action"] == "allow" and a["protocol"] in ("-1", "all")
         else False),
     "Restrict the rule to required protocols"),
    ("AVD-AWS-0105", "Network ACL rule allows ingress from the public "
     "internet", "CRITICAL", "network_acl_rule", "ec2",
     lambda a: None if a.get("cidr") is None or a.get("action") is None
     else ("Rule allows public ingress"
           if a["action"] == "allow" and not a.get("egress")
           and a["cidr"] in ("0.0.0.0/0", "::/0") else False),
     "Restrict ingress CIDR ranges"),
    # --- ECR
    ("AVD-AWS-0030", "ECR repository does not scan images on push",
     "HIGH", "ecr_repository", "ecr",
     _fail_if("scan_on_push", (False,),
              "Image scanning on push is disabled"),
     "Enable image_scanning_configuration.scan_on_push"),
    ("AVD-AWS-0031", "ECR repository allows mutable tags", "HIGH",
     "ecr_repository", "ecr",
     _fail_if("immutable", (False,), "Image tags are mutable"),
     "Set image_tag_mutability = IMMUTABLE"),
    ("AVD-AWS-0032", "ECR repository policy is public", "HIGH",
     "ecr_policy", "ecr",
     lambda a: None if a.get("document") is None else (
         "Repository policy allows any principal" if any(
             s.get("Effect") == "Allow" and
             (s.get("Principal") == "*" or (
                 isinstance(s.get("Principal"), dict) and
                 s["Principal"].get("AWS") == "*"))
             for s in (a["document"].get("Statement") or [])
             if isinstance(s, dict)) else False),
     "Scope the repository policy to known principals"),
    ("AVD-AWS-0033", "ECR repository is not CMK-encrypted", "LOW",
     "ecr_repository", "ecr",
     _fail_if("cmk", (False,),
              "Repository is not encrypted with a customer key"),
     "Use encryption_configuration with KMS"),
    # --- ECS
    ("AVD-AWS-0034", "ECS cluster has no container insights", "LOW",
     "ecs_cluster", "ecs",
     _fail_if("container_insights", (False,),
              "Container insights are not enabled"),
     "Enable the containerInsights setting"),
    ("AVD-AWS-0035", "ECS task EFS volume disables in-transit "
     "encryption", "HIGH", "ecs_task", "ecs",
     _fail_if("efs_unencrypted_transit", (True,),
              "EFS volume transit encryption is disabled"),
     "Enable transit_encryption"),
    ("AVD-AWS-0036", "ECS task definition holds a plaintext secret",
     "CRITICAL", "ecs_task", "ecs",
     _fail_if("plaintext_secret", (True,),
              "Environment variable looks like a hardcoded secret"),
     "Use SSM/Secrets Manager references"),
    # --- EKS
    ("AVD-AWS-0038", "EKS control plane logging is disabled", "MEDIUM",
     "eks_cluster_ext", "eks",
     _fail_if("logging", (False,),
              "No control-plane log types are enabled"),
     "Enable enabled_cluster_log_types"),
    ("AVD-AWS-0039", "EKS secrets are not encrypted", "HIGH",
     "eks_cluster_ext", "eks",
     _fail_if("secrets_encrypted", (False,),
              "No encryption_config for cluster secrets"),
     "Add an encryption_config with a KMS key"),
    # --- ElastiCache
    ("AVD-AWS-0045", "ElastiCache group disables at-rest encryption",
     "HIGH", "elasticache_group", "elasticache",
     _fail_if("at_rest", (False,),
              "At-rest encryption is not enabled"),
     "Set at_rest_encryption_enabled = true"),
    ("AVD-AWS-0051", "ElastiCache group disables in-transit "
     "encryption", "HIGH", "elasticache_group", "elasticache",
     _fail_if("in_transit", (False,),
              "In-transit encryption is not enabled"),
     "Set transit_encryption_enabled = true"),
    ("AVD-AWS-0050", "ElastiCache group has no backup retention",
     "MEDIUM", ("elasticache_group", "elasticache_cluster"),
     "elasticache",
     lambda a: None if a.get("backup_retention") is None else (
         False if str(a.get("engine", "redis")) == "memcached"
         else "Snapshot retention is 0"
         if isinstance(a["backup_retention"], (int, float)) and
         not isinstance(a["backup_retention"], bool) and
         a["backup_retention"] < 1 else False),
     "Set snapshot_retention_limit"),
    # --- Elasticsearch / OpenSearch
    ("AVD-AWS-0048", "ES domain is not encrypted at rest", "HIGH",
     "elasticsearch_domain", "elastic-search",
     _fail_if("at_rest", (False,),
              "Encryption at rest is not enabled"),
     "Enable encrypt_at_rest"),
    ("AVD-AWS-0043", "ES domain has no node-to-node encryption", "HIGH",
     "elasticsearch_domain", "elastic-search",
     _fail_if("in_transit", (False,),
              "Node-to-node encryption is not enabled"),
     "Enable node_to_node_encryption"),
    ("AVD-AWS-0046", "ES domain does not enforce HTTPS", "CRITICAL",
     "elasticsearch_domain", "elastic-search",
     _fail_if("enforce_https", (False,),
              "Unencrypted HTTP access is allowed"),
     "Set enforce_https = true"),
    ("AVD-AWS-0126", "ES domain uses an outdated TLS policy", "HIGH",
     "elasticsearch_domain", "elastic-search",
     _fail_if("tls_policy", ("Policy-Min-TLS-1-0-2019-07",),
              "TLS policy allows TLS 1.0"),
     "Use Policy-Min-TLS-1-2-2019-07"),
    ("AVD-AWS-0042", "ES domain audit logging is disabled", "MEDIUM",
     "elasticsearch_domain", "elastic-search",
     _fail_if("audit_logging", (False,),
              "AUDIT_LOGS publishing is not enabled"),
     "Enable AUDIT_LOGS log publishing"),
    # --- ELB
    ("AVD-AWS-0053", "Load balancer is internet-facing", "HIGH",
     "lb", "elb",
     lambda a: None if a.get("internal") is None else (
         "Load balancer is exposed to the internet"
         if a["internal"] is False else False),
     "Set internal = true unless public exposure is required"),
    ("AVD-AWS-0052", "ALB does not drop invalid headers", "HIGH",
     "lb", "elb",
     lambda a: None if a.get("drop_invalid_headers") is None else (
         "Invalid HTTP headers are not dropped"
         if a["drop_invalid_headers"] is False
         and a.get("lb_type") == "application" else False),
     "Set drop_invalid_header_fields = true"),
    ("AVD-AWS-0047", "Load balancer listener uses an outdated SSL "
     "policy", "HIGH", "lb_listener_ext", "elb",
     _fail_if("ssl_policy",
              ("ELBSecurityPolicy-2015-05",
               "ELBSecurityPolicy-TLS-1-0-2015-04",
               "ELBSecurityPolicy-2016-08"),
              "Listener allows outdated TLS versions"),
     "Use ELBSecurityPolicy-TLS-1-2-2017-01 or newer"),
    # --- EMR
    ("AVD-AWS-0137", "EMR security configuration disables local-disk "
     "encryption", "HIGH", "emr_security_config", "emr",
     _fail_if("local_disk", (False,),
              "Local disk encryption is not configured"),
     "Configure LocalDiskEncryptionConfiguration"),
    ("AVD-AWS-0138", "EMR security configuration disables in-transit "
     "encryption", "HIGH", "emr_security_config", "emr",
     _fail_if("in_transit", (False,),
              "In-transit encryption is disabled"),
     "Set EnableInTransitEncryption"),
    ("AVD-AWS-0139", "EMR security configuration disables at-rest "
     "encryption", "HIGH", "emr_security_config", "emr",
     _fail_if("at_rest", (False,),
              "At-rest encryption is disabled"),
     "Set EnableAtRestEncryption"),
    # --- IAM password policy
    ("AVD-AWS-0056", "Password policy does not prevent reuse", "MEDIUM",
     "iam_password_policy", "iam",
     _lt("reuse_prevention", 5,
         "Fewer than 5 previous passwords are remembered"),
     "Set password_reuse_prevention >= 5"),
    ("AVD-AWS-0058", "Password policy does not require lowercase",
     "MEDIUM", "iam_password_policy", "iam",
     _fail_if("require_lowercase", (False,),
              "Lowercase characters are not required"),
     "Set require_lowercase_characters = true"),
    ("AVD-AWS-0059", "Password policy does not require numbers",
     "MEDIUM", "iam_password_policy", "iam",
     _fail_if("require_numbers", (False,),
              "Numbers are not required"),
     "Set require_numbers = true"),
    ("AVD-AWS-0060", "Password policy does not require symbols",
     "MEDIUM", "iam_password_policy", "iam",
     _fail_if("require_symbols", (False,),
              "Symbols are not required"),
     "Set require_symbols = true"),
    ("AVD-AWS-0061", "Password policy does not require uppercase",
     "MEDIUM", "iam_password_policy", "iam",
     _fail_if("require_uppercase", (False,),
              "Uppercase characters are not required"),
     "Set require_uppercase_characters = true"),
    ("AVD-AWS-0062", "Password policy has no maximum age", "MEDIUM",
     "iam_password_policy", "iam",
     _lt("max_age", 1, "Passwords never expire"),
     "Set max_password_age (e.g. 90 days)"),
    ("AVD-AWS-0063", "Password policy minimum length is too short",
     "MEDIUM", "iam_password_policy", "iam",
     _lt("min_length", 14, "Minimum length is below 14 characters"),
     "Set minimum_password_length >= 14"),
    # --- Kinesis
    ("AVD-AWS-0064", "Kinesis stream is unencrypted", "HIGH",
     "kinesis_stream", "kinesis",
     _fail_if("encrypted", (False,),
              "Stream encryption is not KMS"),
     "Set encryption_type = KMS"),
    # --- KMS
    ("AVD-AWS-0065", "KMS key rotation is disabled", "MEDIUM",
     "kms_key", "kms",
     lambda a: None if a.get("rotation") is None else (
         "Automatic key rotation is not enabled"
         if a["rotation"] is False and
         a.get("usage") != "SIGN_VERIFY" else False),
     "Set enable_key_rotation = true"),
    # --- Lambda
    ("AVD-AWS-0066", "Lambda function has no X-Ray tracing", "LOW",
     "lambda_function", "lambda",
     _fail_if("tracing", ("PassThrough",),
              "Tracing mode is PassThrough"),
     "Set tracing_config mode = Active"),
    ("AVD-AWS-0067", "Lambda permission has no source ARN", "CRITICAL",
     "lambda_permission", "lambda",
     lambda a: None if a.get("principal") is None else (
         "Service principal permission without source_arn"
         if not a["has_source_arn"] and
         str(a["principal"]).endswith(".amazonaws.com") else False),
     "Restrict the permission with source_arn"),
    # --- MQ
    ("AVD-AWS-0070", "MQ broker general logging is disabled", "LOW",
     "mq_broker", "mq",
     _fail_if("general_logging", (False,),
              "General logging is not enabled"),
     "Enable logs.general"),
    ("AVD-AWS-0071", "MQ broker audit logging is disabled", "MEDIUM",
     "mq_broker", "mq",
     _fail_if("audit_logging", (False,),
              "Audit logging is not enabled"),
     "Enable logs.audit"),
    ("AVD-AWS-0072", "MQ broker is publicly accessible", "HIGH",
     "mq_broker", "mq",
     _fail_if("public", (True,), "Broker is publicly accessible"),
     "Set publicly_accessible = false"),
    # --- MSK
    ("AVD-AWS-0073", "MSK cluster broker logging is disabled", "LOW",
     "msk_cluster", "msk",
     _fail_if("logging", (False,),
              "No broker log destination is enabled"),
     "Enable logging_info broker logs"),
    ("AVD-AWS-0074", "MSK cluster allows plaintext client traffic",
     "HIGH", "msk_cluster", "msk",
     _fail_if("client_broker", ("PLAINTEXT", "TLS_PLAINTEXT"),
              "Client-broker encryption allows plaintext"),
     "Set encryption_in_transit client_broker = TLS"),
    ("AVD-AWS-0179", "MSK cluster is not CMK-encrypted at rest", "LOW",
     "msk_cluster", "msk",
     _fail_if("at_rest_cmk", (False,),
              "At-rest encryption does not use a customer key"),
     "Set encryption_at_rest_kms_key_arn"),
    # --- Neptune
    ("AVD-AWS-0075", "Neptune cluster audit logging is disabled",
     "MEDIUM", "neptune_cluster", "neptune",
     _fail_if("audit_logging", (False,),
              "Audit log export is not enabled"),
     "Add audit to enable_cloudwatch_logs_exports"),
    ("AVD-AWS-0076", "Neptune cluster storage is unencrypted", "HIGH",
     "neptune_cluster", "neptune",
     _fail_if("encrypted", (False,), "Storage is not encrypted"),
     "Set storage_encrypted = true"),
    # --- RDS
    ("AVD-AWS-0079", "RDS cluster storage is unencrypted", "HIGH",
     "rds_cluster", "rds",
     _fail_if("encrypted", (False,),
              "Cluster storage is not encrypted"),
     "Set storage_encrypted = true"),
    ("AVD-AWS-0077", "RDS has insufficient backup retention", "MEDIUM",
     "rds_instance_ext", "rds",
     _lt("backup_retention", 1, "Automated backups are disabled"),
     "Set backup_retention_period >= 1"),
    ("AVD-AWS-0078", "RDS performance insights are not CMK-encrypted",
     "LOW", "rds_instance_ext", "rds",
     lambda a: None if a.get("perf_insights") is None else (
         "Performance insights use the default key"
         if a["perf_insights"] is True and
         a.get("perf_insights_kms") is False else False),
     "Set performance_insights_kms_key_id"),
    ("AVD-AWS-0176", "RDS IAM database authentication is disabled",
     "MEDIUM", "rds_instance_ext", "rds",
     _fail_if("iam_auth", (False,),
              "IAM database authentication is not enabled"),
     "Set iam_database_authentication_enabled = true"),
    ("AVD-AWS-0177", "RDS deletion protection is disabled", "MEDIUM",
     "rds_instance_ext", "rds",
     _fail_if("deletion_protection", (False,),
              "Deletion protection is not enabled"),
     "Set deletion_protection = true"),
    # --- Redshift
    ("AVD-AWS-0084", "Redshift cluster is unencrypted", "HIGH",
     "redshift_cluster", "redshift",
     _fail_if("encrypted", (False,),
              "Cluster storage is not encrypted"),
     "Set encrypted = true"),
    ("AVD-AWS-0127", "Redshift cluster is not CMK-encrypted", "HIGH",
     "redshift_cluster", "redshift",
     lambda a: None if a.get("encrypted") is None else (
         "Encryption does not use a customer key"
         if a["encrypted"] is True and a.get("cmk") is False
         else False),
     "Set kms_key_id"),
    ("AVD-AWS-0085", "Redshift cluster is not deployed in a VPC",
     "HIGH", "redshift_cluster", "redshift",
     _fail_if("in_vpc", (False,),
              "No cluster subnet group is configured"),
     "Set cluster_subnet_group_name"),
    ("AVD-AWS-0083", "Redshift cluster is publicly accessible",
     "CRITICAL", "redshift_cluster", "redshift",
     _fail_if("public", (True,), "Cluster is publicly accessible"),
     "Set publicly_accessible = false"),
    # --- Secrets Manager / SSM
    ("AVD-AWS-0098", "Secrets Manager secret is not CMK-encrypted",
     "LOW", "ssm_secret", "ssm",
     _fail_if("cmk", (False,),
              "Secret is not encrypted with a customer key"),
     "Set kms_key_id on the secret"),
    # --- WorkSpaces
    ("AVD-AWS-0109", "WorkSpaces root volume is unencrypted", "HIGH",
     "workspaces_workspace", "workspaces",
     _fail_if("root_encrypted", (False,),
              "Root volume encryption is not enabled"),
     "Set root_volume_encryption_enabled = true"),
    ("AVD-AWS-0110", "WorkSpaces user volume is unencrypted", "HIGH",
     "workspaces_workspace", "workspaces",
     _fail_if("user_encrypted", (False,),
              "User volume encryption is not enabled"),
     "Set user_volume_encryption_enabled = true"),
]


register_specs(SPECS, provider="aws", file_types=_C)
