"""Dockerfile checks (reference trivy-checks checks/docker/*.rego;
IDs match the published DS rules)."""

from __future__ import annotations

import re

from trivy_tpu.iac.check import Cause, check

_D = ("dockerfile",)


def _cause(instr, msg, stage=None) -> Cause:
    return Cause(message=msg, resource=stage.name if stage else "",
                 start_line=instr.start_line, end_line=instr.end_line)


def _run_commands(df, stage):
    """Shell commands from RUN instructions, split on &&/;."""
    for instr in df.by_cmd("RUN", stage):
        for part in re.split(r"&&|;", instr.value.replace("\\\n", " ")):
            yield instr, part.strip()


@check("DS001", "':latest' tag used", severity="MEDIUM", file_types=_D,
       avd_id="AVD-DS-0001", provider="dockerfile", service="general",
       resolution="Add a tag to the image in the 'FROM' statement")
def latest_tag(ctx):
    out = []
    stage_names = {s.name for s in ctx.dockerfile.stages}
    for stage in ctx.dockerfile.stages:
        base = stage.base
        if base in stage_names and base != stage.name:
            continue  # references an earlier stage
        if base.lower() == "scratch" or base.startswith("$"):
            continue
        ref = base.rsplit("@", 1)[0]
        tag = ref.rsplit(":", 1)[1] if ":" in ref.split("/")[-1] else ""
        if "@" in base:
            continue  # digest-pinned
        if tag == "latest" or not tag:
            out.append(Cause(
                message=f"Specify a tag in the 'FROM' statement for image "
                        f"'{ref.split(':')[0]}'",
                resource=stage.name, start_line=stage.start_line,
                end_line=stage.start_line,
            ))
    return out


@check("DS002", "Image user should not be 'root'", severity="HIGH",
       file_types=_D, avd_id="AVD-DS-0002", provider="dockerfile",
       service="general",
       resolution="Add 'USER <non root user name>' line to the Dockerfile")
def root_user(ctx):
    df = ctx.dockerfile
    stage = df.final_stage
    if stage is None:
        return []
    users = df.by_cmd("USER", stage) or df.by_cmd("USER")
    if not users:
        return [Cause(
            message="Specify at least 1 USER command in Dockerfile with "
                    "non-root user as argument",
            resource=stage.name, start_line=stage.start_line,
            end_line=stage.start_line,
        )]
    last = users[-1]
    if last.value.split(":")[0].strip() in ("root", "0"):
        return [_cause(last, "Last USER command in Dockerfile should not "
                             "be 'root'", stage)]
    return []


@check("DS004", "Port 22 exposed", severity="MEDIUM", file_types=_D,
       avd_id="AVD-DS-0004", provider="dockerfile", service="general",
       resolution="Remove 'EXPOSE 22' statement from the Dockerfile")
def expose_ssh(ctx):
    out = []
    for instr in ctx.dockerfile.by_cmd("EXPOSE"):
        for port in instr.value.split():
            if port.split("/")[0] == "22":
                out.append(_cause(instr,
                                  "Port 22 should not be exposed in "
                                  "Dockerfile"))
    return out


@check("DS005", "ADD instead of COPY", severity="LOW", file_types=_D,
       avd_id="AVD-DS-0005", provider="dockerfile", service="general",
       resolution="Use COPY instead of ADD")
def add_instead_of_copy(ctx):
    out = []
    for instr in ctx.dockerfile.by_cmd("ADD"):
        v = instr.value
        # ADD is legitimate for remote URLs and auto-extracted archives
        if re.search(r"https?://", v) or re.search(
            r"\.(tar|tar\.\w+|tgz|tbz2|txz|zst)(\s|\"|$)", v
        ):
            continue
        out.append(_cause(instr, f"Consider using 'COPY {v}' command "
                                 f"instead of 'ADD {v}'"))
    return out


@check("DS010", "RUN using 'sudo'", severity="HIGH", file_types=_D,
       avd_id="AVD-DS-0010", provider="dockerfile", service="general",
       resolution="Don't use sudo in RUN")
def run_sudo(ctx):
    out = []
    for instr, cmd in _run_commands(ctx.dockerfile, None):
        if cmd.startswith("sudo ") or cmd == "sudo":
            out.append(_cause(instr, "Using 'sudo' in Dockerfile should "
                                     "be avoided"))
    return out


@check("DS012", "Duplicate stage alias", severity="CRITICAL",
       file_types=_D, avd_id="AVD-DS-0012", provider="dockerfile",
       service="general",
       resolution="Use unique aliases in multi-stage builds")
def duplicate_alias(ctx):
    seen = {}
    out = []
    for stage in ctx.dockerfile.stages:
        if stage.name != stage.base and stage.name in seen:
            out.append(Cause(
                message=f"Duplicate aliases '{stage.name}' are found in "
                        f"different FROM statements",
                resource=stage.name, start_line=stage.start_line,
                end_line=stage.start_line,
            ))
        seen[stage.name] = stage
    return out


@check("DS013", "'RUN cd ...' to change directory", severity="MEDIUM",
       file_types=_D, avd_id="AVD-DS-0013", provider="dockerfile",
       service="general", resolution="Use WORKDIR instead of 'RUN cd'")
def run_cd(ctx):
    out = []
    for instr, cmd in _run_commands(ctx.dockerfile, None):
        if re.match(r"cd\s+/", cmd):
            out.append(_cause(
                instr, f"RUN should not be used to change directory: "
                       f"'{cmd}'. Use 'WORKDIR' statement instead."))
    return out


@check("DS016", "Multiple CMD instructions", severity="CRITICAL",
       file_types=_D, avd_id="AVD-DS-0016", provider="dockerfile",
       service="general",
       resolution="Keep one CMD per stage")
def multiple_cmds(ctx):
    out = []
    for stage in ctx.dockerfile.stages:
        cmds = ctx.dockerfile.by_cmd("CMD", stage)
        for extra in cmds[:-1]:
            out.append(_cause(
                extra, "There are multiple CMD instructions; only "
                       "the last one takes effect", stage))
    return out


@check("DS017", "'RUN apt-get update' without matching install",
       severity="HIGH", file_types=_D, avd_id="AVD-DS-0017",
       provider="dockerfile", service="general",
       resolution="Combine apt-get update and install in one RUN")
def apt_update_alone(ctx):
    out = []
    for instr in ctx.dockerfile.by_cmd("RUN"):
        text = instr.value
        if re.search(r"apt(-get)?\s+update", text) and not re.search(
            r"apt(-get)?\s+(-\S+\s+)*install", text
        ):
            out.append(_cause(
                instr, "The instruction 'RUN <package-manager> update' "
                       "should always be followed by "
                       "'<package-manager> install' in the same RUN "
                       "statement"))
    return out


@check("DS021", "'apt-get install' without '-y'", severity="HIGH",
       file_types=_D, avd_id="AVD-DS-0021", provider="dockerfile",
       service="general",
       resolution="Add -y to apt-get install")
def apt_install_no_yes(ctx):
    out = []
    for instr, cmd in _run_commands(ctx.dockerfile, None):
        if re.search(r"apt(-get)?\s+(-\S+\s+)*install", cmd):
            if not re.search(r"(^|\s)(-y|--yes|--assume-yes|-qq)(\s|$)",
                             cmd):
                out.append(_cause(
                    instr, f"'-y' flag is missed: '{cmd}'"))
    return out


@check("DS024", "'apt-get dist-upgrade' used", severity="HIGH",
       file_types=_D, avd_id="AVD-DS-0024", provider="dockerfile",
       service="general",
       resolution="Remove apt-get dist-upgrade")
def dist_upgrade(ctx):
    out = []
    for instr, cmd in _run_commands(ctx.dockerfile, None):
        if re.search(r"apt-get\s+(-\S+\s+)*dist-upgrade", cmd):
            out.append(_cause(
                instr, "'apt-get dist-upgrade' should not be used in "
                       "Dockerfile"))
    return out


@check("DS025", "'apk add' without '--no-cache'", severity="HIGH",
       file_types=_D, avd_id="AVD-DS-0025", provider="dockerfile",
       service="general",
       resolution="Add --no-cache to apk add")
def apk_no_cache(ctx):
    out = []
    for instr, cmd in _run_commands(ctx.dockerfile, None):
        if re.search(r"apk\s+(-\S+\s+)*add", cmd) and \
                "--no-cache" not in cmd:
            out.append(_cause(
                instr, f"'--no-cache' is missed: '{cmd}'"))
    return out


@check("DS026", "No HEALTHCHECK defined", severity="LOW", file_types=_D,
       avd_id="AVD-DS-0026", provider="dockerfile", service="general",
       resolution="Add HEALTHCHECK instruction in your docker container "
                  "images")
def no_healthcheck(ctx):
    df = ctx.dockerfile
    if not df.stages:
        return []
    if df.by_cmd("HEALTHCHECK"):
        return []
    stage = df.final_stage
    return [Cause(
        message="Add HEALTHCHECK instruction in your docker container "
                "images",
        resource=stage.name, start_line=stage.start_line,
        end_line=stage.start_line,
    )]


@check("DS029", "'apt-get install' without '--no-install-recommends'",
       severity="HIGH", file_types=_D, avd_id="AVD-DS-0029",
       provider="dockerfile", service="general",
       resolution="Add --no-install-recommends to apt-get install")
def apt_no_recommends(ctx):
    out = []
    for instr, cmd in _run_commands(ctx.dockerfile, None):
        if re.search(r"apt-get\s+(-\S+\s+)*install", cmd) and \
                "--no-install-recommends" not in cmd:
            out.append(_cause(
                instr, f"'--no-install-recommends' flag is missed: "
                       f"'{cmd}'"))
    return out


# --------------------------------------------- breadth wave (r5): the
# remaining published DS rules (reference trivy-checks checks/docker)


@check("DS006", "COPY --from references its own FROM alias",
       severity="CRITICAL", file_types=_D, avd_id="AVD-DS-0006",
       provider="dockerfile", service="general",
       resolution="Reference a previous stage in COPY --from")
def copy_from_own_alias(ctx):
    out = []
    for stage in ctx.dockerfile.stages:
        for instr in ctx.dockerfile.by_cmd("COPY", stage):
            for flag in instr.flags:
                if flag.startswith("--from=") and \
                        flag[7:] == stage.name:
                    out.append(_cause(
                        instr, f"COPY '--from' references the current "
                               f"stage '{stage.name}'", stage))
    return out


@check("DS007", "Multiple ENTRYPOINT instructions in a stage",
       severity="CRITICAL", file_types=_D, avd_id="AVD-DS-0007",
       provider="dockerfile", service="general",
       resolution="Keep only one ENTRYPOINT per stage")
def multiple_entrypoints_ds007(ctx):
    out = []
    for stage in ctx.dockerfile.stages:
        eps = ctx.dockerfile.by_cmd("ENTRYPOINT", stage)
        if len(eps) > 1:
            out.append(_cause(
                eps[-1], f"There are {len(eps)} duplicate ENTRYPOINT "
                         f"instructions", stage))
    return out


@check("DS008", "Exposed port is out of range", severity="CRITICAL",
       file_types=_D, avd_id="AVD-DS-0008", provider="dockerfile",
       service="general", resolution="Use ports between 0 and 65535")
def port_out_of_range(ctx):
    out = []
    for stage in ctx.dockerfile.stages:
        for instr in ctx.dockerfile.by_cmd("EXPOSE", stage):
            for port in instr.value.split():
                num = port.split("/")[0]
                if num.isdigit() and not 0 <= int(num) <= 65535:
                    out.append(_cause(
                        instr, f"'EXPOSE' port {num} is out of range",
                        stage))
    return out


@check("DS009", "WORKDIR path is relative", severity="HIGH",
       file_types=_D, avd_id="AVD-DS-0009", provider="dockerfile",
       service="general", resolution="Use absolute WORKDIR paths")
def workdir_relative(ctx):
    out = []
    for stage in ctx.dockerfile.stages:
        for instr in ctx.dockerfile.by_cmd("WORKDIR", stage):
            path = instr.value.strip().strip('"').strip("'")
            if path and not path.startswith(("/", "$", "%")) \
                    and ":" not in path[:3]:    # windows C:\ paths
                out.append(_cause(
                    instr, f"WORKDIR path '{path}' should be absolute",
                    stage))
    return out


@check("DS011", "COPY with multiple sources needs a directory "
       "destination", severity="CRITICAL", file_types=_D,
       avd_id="AVD-DS-0011", provider="dockerfile", service="general",
       resolution="End the destination with / when copying multiple "
                  "sources")
def copy_multiple_sources(ctx):
    out = []
    for stage in ctx.dockerfile.stages:
        for instr in ctx.dockerfile.by_cmd("COPY", stage):
            arr = instr.json_array()
            parts = arr if arr is not None else instr.value.split()
            parts = [p for p in parts
                     if not p.startswith("--")]   # strip flags
            if len(parts) > 2 and not parts[-1].endswith("/") \
                    and not parts[-1] in (".", "./"):
                out.append(_cause(
                    instr, f"When copying multiple sources the "
                           f"destination '{parts[-1]}' must be a "
                           f"directory (end with /)", stage))
    return out


@check("DS014", "RUN uses both wget and curl", severity="LOW",
       file_types=_D, avd_id="AVD-DS-0014", provider="dockerfile",
       service="general",
       resolution="Standardize on either wget or curl")
def wget_and_curl(ctx):
    out = []
    for stage in ctx.dockerfile.stages:
        tools = set()
        first = None
        for instr, cmd in _run_commands(ctx.dockerfile, stage):
            tok = cmd.split()[:1]
            if tok and tok[0] in ("wget", "curl"):
                tools.add(tok[0])
                first = first or instr
        if {"wget", "curl"} <= tools and first is not None:
            out.append(_cause(
                first, "Both wget and curl are used — pick one",
                stage))
    return out


@check("DS015", "yum install without 'yum clean all'", severity="HIGH",
       file_types=_D, avd_id="AVD-DS-0015", provider="dockerfile",
       service="general",
       resolution="Add 'yum clean all' after yum install")
def yum_clean_missing(ctx):
    out = []
    for stage in ctx.dockerfile.stages:
        for instr in ctx.dockerfile.by_cmd("RUN", stage):
            text = instr.value
            if re.search(r"\byum\b[^|;&]*\binstall\b", text) and \
                    "clean all" not in text:
                out.append(_cause(
                    instr, "'yum install' without a following "
                           "'yum clean all'", stage))
    return out


@check("DS019", "zypper install without 'zypper clean'",
       severity="HIGH", file_types=_D, avd_id="AVD-DS-0019",
       provider="dockerfile", service="general",
       resolution="Add 'zypper clean' after zypper use")
def zypper_clean_missing(ctx):
    out = []
    for stage in ctx.dockerfile.stages:
        for instr in ctx.dockerfile.by_cmd("RUN", stage):
            text = instr.value
            if re.search(r"\bzypper\b[^|;&]*\b(install|in|remove|rm|"
                         r"source-install|si|patch)\b", text) and \
                    not re.search(r"\bzypper\s+(clean|cc)\b", text):
                out.append(_cause(
                    instr, "'zypper' use without a following "
                           "'zypper clean'", stage))
    return out


@check("DS020", "'zypper dist-upgrade' used", severity="HIGH",
       file_types=_D, avd_id="AVD-DS-0020", provider="dockerfile",
       service="general",
       resolution="Do not run full distribution upgrades in images")
def zypper_dist_upgrade(ctx):
    out = []
    for stage in ctx.dockerfile.stages:
        for instr, cmd in _run_commands(ctx.dockerfile, stage):
            if re.search(r"\bzypper\s+(dist-upgrade|dup)\b", cmd):
                out.append(_cause(
                    instr, "'zypper dist-upgrade' should not be used",
                    stage))
    return out


@check("DS022", "Deprecated MAINTAINER used", severity="LOW",
       file_types=_D, avd_id="AVD-DS-0022", provider="dockerfile",
       service="general",
       resolution="Use a LABEL maintainer= instead")
def maintainer_deprecated(ctx):
    out = []
    for stage in ctx.dockerfile.stages:
        for instr in ctx.dockerfile.by_cmd("MAINTAINER", stage):
            out.append(_cause(
                instr, "MAINTAINER is deprecated, use "
                       "'LABEL maintainer=...'", stage))
    return out


@check("DS023", "Multiple HEALTHCHECK instructions", severity="MEDIUM",
       file_types=_D, avd_id="AVD-DS-0023", provider="dockerfile",
       service="general",
       resolution="Keep a single HEALTHCHECK")
def multiple_healthchecks(ctx):
    hcs = ctx.dockerfile.by_cmd("HEALTHCHECK")
    if len(hcs) > 1:
        return [_cause(hcs[-1],
                       f"There are {len(hcs)} HEALTHCHECK "
                       f"instructions")]
    return []
