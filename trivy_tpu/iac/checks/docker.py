"""Dockerfile checks (reference trivy-checks checks/docker/*.rego;
IDs match the published DS rules)."""

from __future__ import annotations

import re

from trivy_tpu.iac.check import Cause, check

_D = ("dockerfile",)


def _cause(instr, msg, stage=None) -> Cause:
    return Cause(message=msg, resource=stage.name if stage else "",
                 start_line=instr.start_line, end_line=instr.end_line)


def _run_commands(df, stage):
    """Shell commands from RUN instructions, split on &&/;."""
    for instr in df.by_cmd("RUN", stage):
        for part in re.split(r"&&|;", instr.value.replace("\\\n", " ")):
            yield instr, part.strip()


@check("DS001", "':latest' tag used", severity="MEDIUM", file_types=_D,
       avd_id="AVD-DS-0001", provider="dockerfile", service="general",
       resolution="Add a tag to the image in the 'FROM' statement")
def latest_tag(ctx):
    out = []
    stage_names = {s.name for s in ctx.dockerfile.stages}
    for stage in ctx.dockerfile.stages:
        base = stage.base
        if base in stage_names and base != stage.name:
            continue  # references an earlier stage
        if base.lower() == "scratch" or base.startswith("$"):
            continue
        ref = base.rsplit("@", 1)[0]
        tag = ref.rsplit(":", 1)[1] if ":" in ref.split("/")[-1] else ""
        if "@" in base:
            continue  # digest-pinned
        if tag == "latest" or not tag:
            out.append(Cause(
                message=f"Specify a tag in the 'FROM' statement for image "
                        f"'{ref.split(':')[0]}'",
                resource=stage.name, start_line=stage.start_line,
                end_line=stage.start_line,
            ))
    return out


@check("DS002", "Image user should not be 'root'", severity="HIGH",
       file_types=_D, avd_id="AVD-DS-0002", provider="dockerfile",
       service="general",
       resolution="Add 'USER <non root user name>' line to the Dockerfile")
def root_user(ctx):
    df = ctx.dockerfile
    stage = df.final_stage
    if stage is None:
        return []
    users = df.by_cmd("USER", stage) or df.by_cmd("USER")
    if not users:
        return [Cause(
            message="Specify at least 1 USER command in Dockerfile with "
                    "non-root user as argument",
            resource=stage.name, start_line=stage.start_line,
            end_line=stage.start_line,
        )]
    last = users[-1]
    if last.value.split(":")[0].strip() in ("root", "0"):
        return [_cause(last, "Last USER command in Dockerfile should not "
                             "be 'root'", stage)]
    return []


@check("DS004", "Port 22 exposed", severity="MEDIUM", file_types=_D,
       avd_id="AVD-DS-0004", provider="dockerfile", service="general",
       resolution="Remove 'EXPOSE 22' statement from the Dockerfile")
def expose_ssh(ctx):
    out = []
    for instr in ctx.dockerfile.by_cmd("EXPOSE"):
        for port in instr.value.split():
            if port.split("/")[0] == "22":
                out.append(_cause(instr,
                                  "Port 22 should not be exposed in "
                                  "Dockerfile"))
    return out


@check("DS005", "ADD instead of COPY", severity="LOW", file_types=_D,
       avd_id="AVD-DS-0005", provider="dockerfile", service="general",
       resolution="Use COPY instead of ADD")
def add_instead_of_copy(ctx):
    out = []
    for instr in ctx.dockerfile.by_cmd("ADD"):
        v = instr.value
        # ADD is legitimate for remote URLs and auto-extracted archives
        if re.search(r"https?://", v) or re.search(
            r"\.(tar|tar\.\w+|tgz|tbz2|txz|zst)(\s|\"|$)", v
        ):
            continue
        out.append(_cause(instr, f"Consider using 'COPY {v}' command "
                                 f"instead of 'ADD {v}'"))
    return out


@check("DS010", "RUN using 'sudo'", severity="HIGH", file_types=_D,
       avd_id="AVD-DS-0010", provider="dockerfile", service="general",
       resolution="Don't use sudo in RUN")
def run_sudo(ctx):
    out = []
    for instr, cmd in _run_commands(ctx.dockerfile, None):
        if cmd.startswith("sudo ") or cmd == "sudo":
            out.append(_cause(instr, "Using 'sudo' in Dockerfile should "
                                     "be avoided"))
    return out


@check("DS012", "Duplicate stage alias", severity="CRITICAL",
       file_types=_D, avd_id="AVD-DS-0012", provider="dockerfile",
       service="general",
       resolution="Use unique aliases in multi-stage builds")
def duplicate_alias(ctx):
    seen = {}
    out = []
    for stage in ctx.dockerfile.stages:
        if stage.name != stage.base and stage.name in seen:
            out.append(Cause(
                message=f"Duplicate aliases '{stage.name}' are found in "
                        f"different FROM statements",
                resource=stage.name, start_line=stage.start_line,
                end_line=stage.start_line,
            ))
        seen[stage.name] = stage
    return out


@check("DS013", "'RUN cd ...' to change directory", severity="MEDIUM",
       file_types=_D, avd_id="AVD-DS-0013", provider="dockerfile",
       service="general", resolution="Use WORKDIR instead of 'RUN cd'")
def run_cd(ctx):
    out = []
    for instr, cmd in _run_commands(ctx.dockerfile, None):
        if re.match(r"cd\s+/", cmd):
            out.append(_cause(
                instr, f"RUN should not be used to change directory: "
                       f"'{cmd}'. Use 'WORKDIR' statement instead."))
    return out


@check("DS016", "Multiple ENTRYPOINT instructions", severity="CRITICAL",
       file_types=_D, avd_id="AVD-DS-0016", provider="dockerfile",
       service="general",
       resolution="Keep one ENTRYPOINT per stage")
def multiple_entrypoints(ctx):
    out = []
    for stage in ctx.dockerfile.stages:
        eps = ctx.dockerfile.by_cmd("ENTRYPOINT", stage)
        for extra in eps[:-1]:
            out.append(_cause(
                extra, "There are multiple ENTRYPOINT instructions; only "
                       "the last one takes effect", stage))
    return out


@check("DS017", "'RUN apt-get update' without matching install",
       severity="HIGH", file_types=_D, avd_id="AVD-DS-0017",
       provider="dockerfile", service="general",
       resolution="Combine apt-get update and install in one RUN")
def apt_update_alone(ctx):
    out = []
    for instr in ctx.dockerfile.by_cmd("RUN"):
        text = instr.value
        if re.search(r"apt(-get)?\s+update", text) and not re.search(
            r"apt(-get)?\s+(-\S+\s+)*install", text
        ):
            out.append(_cause(
                instr, "The instruction 'RUN <package-manager> update' "
                       "should always be followed by "
                       "'<package-manager> install' in the same RUN "
                       "statement"))
    return out


@check("DS021", "'apt-get install' without '-y'", severity="HIGH",
       file_types=_D, avd_id="AVD-DS-0021", provider="dockerfile",
       service="general",
       resolution="Add -y to apt-get install")
def apt_install_no_yes(ctx):
    out = []
    for instr, cmd in _run_commands(ctx.dockerfile, None):
        if re.search(r"apt(-get)?\s+(-\S+\s+)*install", cmd):
            if not re.search(r"(^|\s)(-y|--yes|--assume-yes|-qq)(\s|$)",
                             cmd):
                out.append(_cause(
                    instr, f"'-y' flag is missed: '{cmd}'"))
    return out


@check("DS024", "'apt-get dist-upgrade' used", severity="HIGH",
       file_types=_D, avd_id="AVD-DS-0024", provider="dockerfile",
       service="general",
       resolution="Remove apt-get dist-upgrade")
def dist_upgrade(ctx):
    out = []
    for instr, cmd in _run_commands(ctx.dockerfile, None):
        if re.search(r"apt-get\s+(-\S+\s+)*dist-upgrade", cmd):
            out.append(_cause(
                instr, "'apt-get dist-upgrade' should not be used in "
                       "Dockerfile"))
    return out


@check("DS025", "'apk add' without '--no-cache'", severity="HIGH",
       file_types=_D, avd_id="AVD-DS-0025", provider="dockerfile",
       service="general",
       resolution="Add --no-cache to apk add")
def apk_no_cache(ctx):
    out = []
    for instr, cmd in _run_commands(ctx.dockerfile, None):
        if re.search(r"apk\s+(-\S+\s+)*add", cmd) and \
                "--no-cache" not in cmd:
            out.append(_cause(
                instr, f"'--no-cache' is missed: '{cmd}'"))
    return out


@check("DS026", "No HEALTHCHECK defined", severity="LOW", file_types=_D,
       avd_id="AVD-DS-0026", provider="dockerfile", service="general",
       resolution="Add HEALTHCHECK instruction in your docker container "
                  "images")
def no_healthcheck(ctx):
    df = ctx.dockerfile
    if not df.stages:
        return []
    if df.by_cmd("HEALTHCHECK"):
        return []
    stage = df.final_stage
    return [Cause(
        message="Add HEALTHCHECK instruction in your docker container "
                "images",
        resource=stage.name, start_line=stage.start_line,
        end_line=stage.start_line,
    )]


@check("DS029", "'apt-get install' without '--no-install-recommends'",
       severity="HIGH", file_types=_D, avd_id="AVD-DS-0029",
       provider="dockerfile", service="general",
       resolution="Add --no-install-recommends to apt-get install")
def apt_no_recommends(ctx):
    out = []
    for instr, cmd in _run_commands(ctx.dockerfile, None):
        if re.search(r"apt-get\s+(-\S+\s+)*install", cmd) and \
                "--no-install-recommends" not in cmd:
            out.append(_cause(
                instr, f"'--no-install-recommends' flag is missed: "
                       f"'{cmd}'"))
    return out
