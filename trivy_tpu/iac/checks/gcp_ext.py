"""GCP check breadth: additional google_* service families (reference
pkg/iac/providers/google/{bigquery,compute,dns,gke,iam,kms,sql}/ and
pkg/iac/adapters/terraform/google/*/adapt.go). Declarative layout as in
aws_ext; IDs/severities follow the public AVD registry
(avd.aquasec.com/misconfig/google)."""

from __future__ import annotations

from trivy_tpu.iac.checks.spec import (
    fail_if as _fail_if,
    register_specs,
    tf_value as _v,
    tri as _tri,
)
from trivy_tpu.iac.parsers.hcl import Block

_C = ("terraform", "terraformplan")


def adapt_terraform_gcp_ext(blocks: list[Block]) -> list:
    from trivy_tpu.iac.checks.cloud import CloudResource

    out = []
    for b in blocks:
        if b.type != "resource" or len(b.labels) < 2:
            continue
        fn = _TF.get(b.labels[0])
        if fn is None:
            continue
        rtype, attrs = fn(b)
        out.append(CloudResource(
            type=rtype, name=f"{b.labels[0]}.{b.labels[1]}",
            attrs=attrs, start_line=b.start_line, end_line=b.end_line))
    return out


def _tf_bq_dataset(b):
    members = []
    for a in b.children("access"):
        members.append(_v(a.get("special_group")))
    return "bq_dataset", {"special_groups": members}


def _tf_compute_disk(b):
    enc = b.child("disk_encryption_key")
    has_key = False
    if enc is not None:
        has_key = bool(_v(enc.get("raw_key")) or
                       _v(enc.get("kms_key_self_link")) or
                       _v(enc.get("rsa_encrypted_key")))
    return "gcp_disk", {"cmk": has_key}


def _tf_instance_ext(b):
    sa = b.child("service_account")
    email = _v(sa.get("email")) if sa is not None else None
    return "gcp_instance_ext", {
        "default_sa": (email is None or str(email).endswith(
            "-compute@developer.gserviceaccount.com"))
        if not (sa is not None and "email" in sa.attrs and
                email is None) else None,
        "ip_forwarding": _tri(b, "can_ip_forward", False),
    }


def _tf_firewall_ext(b):
    return "gcp_firewall_ext", {
        "direction": _v(b.get("direction")) or "INGRESS",
        "destination_ranges": _v(b.get("destination_ranges")) or [],
        "has_deny": len(b.children("deny")) > 0,
        "has_allow": len(b.children("allow")) > 0,
    }


def _tf_dns_zone(b):
    dnssec = b.child("dnssec_config")
    state = _tri(dnssec, "state", "off") if dnssec else "off"
    keys = []
    if dnssec is not None:
        for spec in dnssec.children("default_key_specs"):
            keys.append(_v(spec.get("algorithm")))
    return "dns_zone", {
        "dnssec": str(state).lower() == "on",
        "key_algorithms": keys,
        "visibility": _tri(b, "visibility", "public"),
    }


def _tf_gke_ext(b):
    meta = None
    legacy = None
    nc = b.child("node_config")
    if nc is not None:
        wm = nc.child("workload_metadata_config")
        meta = _tri(wm, "node_metadata",
                    _tri(wm, "mode", None)) if wm else None
        md = _v(nc.get("metadata"))
        if isinstance(md, dict):
            legacy = str(md.get(
                "disable-legacy-endpoints", "")).lower() \
                not in ("true", "1")
    auth = b.child("master_auth")
    basic_auth = False
    if auth is not None:
        basic_auth = bool(_v(auth.get("username")) or
                          _v(auth.get("password")))
    return "gke_cluster_ext", {
        "shielded_nodes": _tri(b, "enable_shielded_nodes", False),
        "legacy_metadata": legacy,
        "node_metadata_mode": meta,
        "basic_auth": basic_auth,
        "resource_labels": bool(_v(b.get("resource_labels"))),
    }


def _tf_project_iam(b):
    return "gcp_project_iam", {
        "role": _v(b.get("role")),
        "member": _v(b.get("member")),
    }


def _tf_kms_key(b):
    raw = b.get("rotation_period")
    if raw is None:
        seconds = 0                 # absent -> never rotated (fails)
    elif _v(raw) is None:
        seconds = None              # unresolved expression -> unknown
    else:
        seconds = None
        period = _v(raw)
        if isinstance(period, str) and period.endswith("s"):
            try:
                seconds = int(float(period[:-1]))
            except ValueError:
                seconds = None
    return "gcp_kms_key", {"rotation_seconds": seconds}


def _tf_sql_ext(b):
    settings = b.child("settings")
    backups = settings.child("backup_configuration") if settings \
        else None
    flags = {}
    if settings is not None:
        for f in settings.children("database_flags"):
            flags[_v(f.get("name"))] = _v(f.get("value"))
    return "gcp_sql_ext", {
        "backups": _tri(backups, "enabled", False)
        if backups else False,
        "flags": flags,
        "version": _v(b.get("database_version")),
    }


_TF = {
    "google_bigquery_dataset": _tf_bq_dataset,
    "google_compute_disk": _tf_compute_disk,
    "google_compute_instance": _tf_instance_ext,
    "google_compute_firewall": _tf_firewall_ext,
    "google_dns_managed_zone": _tf_dns_zone,
    "google_container_cluster": _tf_gke_ext,
    "google_project_iam_member": _tf_project_iam,
    "google_project_iam_binding": _tf_project_iam,
    "google_kms_crypto_key": _tf_kms_key,
    "google_sql_database_instance": _tf_sql_ext,
}

_MAX_ROTATION_S = 90 * 24 * 3600   # published AVD rule: 90 days

SPECS = [
    ("AVD-GCP-0046", "BigQuery dataset is publicly accessible",
     "CRITICAL", "bq_dataset", "bigquery",
     lambda a: None if a.get("special_groups") is None else (
         "Dataset grants access to allAuthenticatedUsers"
         if "allAuthenticatedUsers" in a["special_groups"] else False),
     "Remove allAuthenticatedUsers access grants"),
    ("AVD-GCP-0037", "Compute disk is not encrypted with a customer "
     "key", "LOW", "gcp_disk", "compute",
     _fail_if("cmk", (False,),
              "Disk has no customer-managed encryption key"),
     "Set disk_encryption_key"),
    ("AVD-GCP-0044", "Instance uses the default service account",
     "HIGH", "gcp_instance_ext", "compute",
     _fail_if("default_sa", (True,),
              "Compute default service account is used"),
     "Attach a dedicated service account"),
    ("AVD-GCP-0043", "Instance allows IP forwarding", "HIGH",
     "gcp_instance_ext", "compute",
     _fail_if("ip_forwarding", (True,), "can_ip_forward is enabled"),
     "Disable can_ip_forward"),
    ("AVD-GCP-0028", "Firewall allows egress to the public internet",
     "CRITICAL", "gcp_firewall_ext", "compute",
     lambda a: None if a.get("destination_ranges") is None else (
         "Egress rule allows 0.0.0.0/0"
         if str(a.get("direction", "")).upper() == "EGRESS" and
         a.get("has_allow") and
         "0.0.0.0/0" in a["destination_ranges"] else False),
     "Restrict egress destination ranges"),
    ("AVD-GCP-0013", "DNS zone DNSSEC is disabled", "MEDIUM",
     "dns_zone", "dns",
     lambda a: None if a.get("dnssec") is None else (
         "DNSSEC is not enabled on a public zone"
         if a["dnssec"] is False and
         a.get("visibility") == "public" else False),
     "Enable dnssec_config state = on"),
    ("AVD-GCP-0012", "DNS zone DNSSEC uses RSASHA1", "MEDIUM",
     "dns_zone", "dns",
     lambda a: None if a.get("key_algorithms") is None else (
         "DNSSEC key uses RSASHA1"
         if "rsasha1" in [str(x).lower()
                          for x in a["key_algorithms"]] else False),
     "Use a stronger signing algorithm"),
    ("AVD-GCP-0055", "GKE shielded nodes are disabled", "HIGH",
     "gke_cluster_ext", "gke",
     _fail_if("shielded_nodes", (False,),
              "enable_shielded_nodes is not set"),
     "Set enable_shielded_nodes = true"),
    ("AVD-GCP-0048", "GKE legacy metadata endpoints are enabled",
     "HIGH", "gke_cluster_ext", "gke",
     _fail_if("legacy_metadata", (True,),
              "disable-legacy-endpoints is not true"),
     "Set node metadata disable-legacy-endpoints = true"),
    ("AVD-GCP-0053", "GKE basic authentication is enabled", "HIGH",
     "gke_cluster_ext", "gke",
     _fail_if("basic_auth", (True,),
              "master_auth sets a static username/password"),
     "Remove master_auth basic credentials"),
    ("AVD-GCP-0063", "GKE cluster has no resource labels", "LOW",
     "gke_cluster_ext", "gke",
     _fail_if("resource_labels", (False,),
              "No resource labels are set"),
     "Set resource_labels"),
    ("AVD-GCP-0007", "Project IAM grants a primitive role", "MEDIUM",
     "gcp_project_iam", "iam",
     lambda a: None if a.get("role") is None else (
         f"Primitive role {a['role']} is granted"
         if a["role"] in ("roles/owner", "roles/editor",
                          "roles/viewer") else False),
     "Use fine-grained predefined or custom roles"),
    ("AVD-GCP-0065", "KMS key is not rotated every 90 days", "HIGH",
     "gcp_kms_key", "kms",
     lambda a: None if a.get("rotation_seconds") is None else (
         "Rotation period exceeds 90 days (or is unset)"
         if a["rotation_seconds"] == 0 or
         a["rotation_seconds"] > _MAX_ROTATION_S else False),
     "Set rotation_period <= 90 days (7776000s)"),
    ("AVD-GCP-0024", "Cloud SQL has no automated backups", "MEDIUM",
     "gcp_sql_ext", "sql",
     _fail_if("backups", (False,),
              "Automated backups are not enabled"),
     "Enable settings.backup_configuration"),
    ("AVD-GCP-0026", "Cloud SQL allows local infile", "MEDIUM",
     "gcp_sql_ext", "sql",
     lambda a: None if a.get("flags") is None else (
         "local_infile flag is on"
         if str(a["flags"].get("local_infile", "off")).lower() == "on"
         else False),
     "Set database flag local_infile = off"),
    ("AVD-GCP-0025", "Cloud SQL postgres does not log connections",
     "MEDIUM", "gcp_sql_ext", "sql",
     lambda a: None if a.get("flags") is None else (
         "log_connections flag is off"
         if str(a.get("version", "")).startswith("POSTGRES") and
         str(a["flags"].get("log_connections", "off")).lower()
         == "off" else False),
     "Set database flag log_connections = on"),
]


register_specs(SPECS, provider="google", file_types=_C)
