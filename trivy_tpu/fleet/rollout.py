"""Coordinated advisory-DB rollout across a replica fleet
(docs/fleet.md "Rollout state machine").

The controller drives the generations/last-good machinery (PR 2) as a
staged fleet-wide hot swap, beating the reference's "quiesce requests
for the whole refresh" model: every replica keeps serving its current
generation until the instant its own guarded swap lands.

State machine (one ``run_rollout`` call)::

    plan ──► canary ──► probe ──► roll ──► rescore ──► completed
              │           │         │
              └───────────┴─────────┴──► rollback ──► rolled_back

- **plan** — every endpoint must be ready (JSON /readyz); the target
  generation is whatever ``last-good`` points at in the shared DB
  root; the previous generation (the rollback anchor) is what the
  fleet currently serves. All endpoints already on target = noop.
- **canary** — one replica reloads first. The server's own guarded
  swap (PR 2) rejects an unloadable/invalid candidate, quarantines it
  and keeps serving last-good; the controller sees ``serving`` stay on
  the previous generation and declares the rollout rolled back without
  ever touching the rest of the fleet.
- **probe** — a probe set (captured scan requests) replays against the
  canary and against a replica still on the previous generation. Any
  byte diff is a regression: the target generation is quarantined,
  last-good repointed at the previous generation, the canary reloaded
  back. (Probes whose packages the refresh legitimately touched WILL
  diff — build the probe set from delta-untouched artifacts, see
  docs/fleet.md.)
- **roll** — remaining replicas reload one at a time, each verified
  (serving == target, /readyz ready) before the next; a failure rolls
  every already-swapped replica back.
- **rescore** — every reload during the roll carried
  ``rescore=false``, parking each replica's PR-9 advisory-delta
  re-score; the controller now consumes the parked swap on each
  monitor-enabled replica (/fleet/rescore). Monitor indexes are
  per-replica (each records the scans it served), so the fleet's
  journaled artifacts re-score once each, after the WHOLE fleet
  serves the new generation — not N uncoordinated mid-rollout sweeps
  against mixed generations.

Fault site ``fleet.rollout`` (``error`` fails the current stage — the
rollback ladder takes over; ``kill`` crashes the controller, leaving a
fleet that is EITHER fully on the old or partially on the new
generation, both serving correctly — re-running the rollout converges
it).
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request

import time
from dataclasses import dataclass, field

from trivy_tpu.db import generations
from trivy_tpu.fleet import slo as slo_mod
from trivy_tpu.fleet.endpoints import readyz_doc
from trivy_tpu.log import logger
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs import tracing
from trivy_tpu.resilience import faults
from trivy_tpu.rpc.server import SCAN_PATH

_log = logger("fleet.rollout")

ROLLOUT_SITE = "fleet.rollout"


class RolloutError(Exception):
    """A rollout stage failed in a way the ladder cannot absorb (bad
    arguments, unreachable fleet, failed rollback)."""


@dataclass
class Stage:
    name: str
    ok: bool
    detail: str
    seconds: float

    def doc(self) -> dict:
        return {"stage": self.name, "ok": self.ok,
                "detail": self.detail,
                "seconds": round(self.seconds, 3)}


@dataclass
class RolloutReport:
    outcome: str = "completed"  # completed | rolled_back | noop
    target: str | None = None
    previous: str | None = None
    canary: str | None = None
    stages: list = field(default_factory=list)
    probes: int = 0
    probe_diffs: int = 0
    rescored_on: list = field(default_factory=list)
    wall_s: float = 0.0

    def doc(self) -> dict:
        return {
            "outcome": self.outcome,
            "target": self.target,
            "previous": self.previous,
            "canary": self.canary,
            "probes": self.probes,
            "probe_diffs": self.probe_diffs,
            "rescored_on": self.rescored_on,
            "wall_s": round(self.wall_s, 3),
            "stages": [s.doc() for s in self.stages],
        }


# ------------------------------------------------------------ transport


def _post_json(url: str, token: str | None = None,
               body: dict | None = None,
               timeout: float = 300.0) -> tuple[int, dict]:
    """POST a JSON document, return (status, parsed reply). Generous
    timeout: a reload compiles the new generation's tensors."""
    headers = {"Content-Type": "application/json"}
    if token:
        headers["Trivy-Token"] = token
    req = urllib.request.Request(
        url, data=json.dumps(body or {}).encode(), headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as exc:
        with exc:
            raw = exc.read()
        try:
            return exc.code, json.loads(raw or b"{}")
        except ValueError:
            return exc.code, {
                "error": raw.decode("utf-8", "replace")[:200]}


#: Public alias: the fleet controller drives the same /fleet/* control
#: surface (drain, reresolve) the rollout state machine does, through
#: one transport helper.
post_json = _post_json


def _replay_probe(endpoint: str, probe: dict,
                  token: str | None) -> tuple[int, bytes]:
    """Replay one captured scan request, returning the raw response
    bytes (the zero-diff comparison unit). No gzip is offered, so two
    replicas on the same generation answer byte-identically."""
    headers = {"Content-Type": "application/json",
               "X-Trivy-Tpu-Wire": "internal"}
    if token:
        headers["Trivy-Token"] = token
    req = urllib.request.Request(
        endpoint.rstrip("/") + SCAN_PATH,
        data=json.dumps(probe, sort_keys=True).encode(),
        headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=120.0) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as exc:
        with exc:
            return exc.code, exc.read()


def fleet_status(endpoints: list[str],
                 token: str | None = None) -> list[dict]:
    """JSON /readyz per endpoint (unreachable replicas report
    ready=False with an 'unreachable' status)."""
    out = []
    for ep in endpoints:
        doc = readyz_doc(ep, token=token, timeout=10.0)
        if doc is None:
            doc = {"ready": False, "status": "unreachable"}
        out.append({"endpoint": ep.rstrip("/"), **doc})
    return out


def load_probes(path: str) -> list[dict]:
    """A probe file: a JSON array (or JSONL) of captured scan-request
    documents ({"target", "artifact_id", "blob_ids", "options"} — the
    wire format)."""
    with open(path, encoding="utf-8") as f:
        text = f.read().strip()
    if not text:
        return []
    if text.startswith("["):
        return json.loads(text)
    return [json.loads(line) for line in text.splitlines() if line.strip()]


# ------------------------------------------------------------ controller


def _fire_stage_faults() -> None:
    rules = faults.fire(ROLLOUT_SITE)
    faults.check_kill(ROLLOUT_SITE, rules=rules)
    for r in rules:
        if r.action == "error":
            raise RolloutError("injected fleet.rollout error")
        if r.action == "delay":
            time.sleep(r.param if r.param is not None else 0.05)


def run_rollout(db_root: str, endpoints: list[str],
                token: str | None = None,
                probes: list[dict] | None = None,
                rescore: bool = True,
                canary: str | None = None,
                on_event=None) -> RolloutReport:
    """Drive one staged fleet rollout; returns the report (outcome
    ``completed`` / ``rolled_back`` / ``noop``). Raises RolloutError
    only when the fleet is in no state to start (not ready, no
    promoted generation) or a rollback itself failed."""
    endpoints = [e.rstrip("/") for e in endpoints]
    if not endpoints:
        raise RolloutError("no endpoints")
    report = RolloutReport()
    t_start = time.monotonic()

    def emit(name: str, ok: bool, detail: str, t0: float) -> None:
        st = Stage(name, ok, detail, time.monotonic() - t0)
        report.stages.append(st)
        obs_metrics.FLEET_ROLLOUT_STAGE_SECONDS.observe(
            st.seconds, stage=name)
        # the durable ops record of this stage (docs/fleet.md "Event
        # catalog"): journaled when the controller runs with one, so a
        # crashed rollout's last completed stage is replayable
        slo_mod.emit_event("rollout_stage", stage=name, ok=ok,
                           detail=detail, target=report.target,
                           seconds=round(st.seconds, 3))
        _log.info("rollout stage", stage=name, ok=ok, detail=detail)
        if on_event is not None:
            on_event(st.doc())

    def reload_ep(ep: str, want_rescore: bool = False) -> dict:
        status, doc = _post_json(ep + "/fleet/reload", token=token,
                                 body={"rescore": want_rescore})
        if status != 200:
            raise RolloutError(
                f"{ep}/fleet/reload -> HTTP {status}: {doc}")
        slo_mod.emit_event("db_swap", endpoint=ep,
                           serving=doc.get("serving"),
                           reloaded=bool(doc.get("reloaded")),
                           degraded=str(doc.get("degraded") or ""))
        return doc

    def rollback(target_dir: str | None, rolled: list[str],
                 quarantine: bool = False) -> None:
        """Repoint last-good at the previous generation and reload
        every replica that already swapped. The target generation is
        quarantined only when there is EVIDENCE it is bad (a probe
        diff); a controller-level failure (unreachable replica,
        injected fault) leaves it installed for a re-staged retry."""
        t0 = time.monotonic()
        if quarantine and target_dir and os.path.isdir(target_dir):
            generations.quarantine(db_root, target_dir)
        prev_dir = (os.path.join(generations.generations_root(db_root),
                                 report.previous)
                    if report.previous else None)
        if prev_dir and os.path.isdir(prev_dir):
            generations.promote(db_root, prev_dir)
        elif rolled:
            raise RolloutError(
                "cannot roll back: previous generation "
                f"{report.previous!r} is gone and "
                f"{len(rolled)} replica(s) already swapped")
        bad = []
        for ep in rolled:
            doc = reload_ep(ep, want_rescore=False)
            if report.previous and doc.get("serving") != report.previous:
                bad.append(f"{ep} serves {doc.get('serving')}")
        if bad:
            raise RolloutError("rollback incomplete: " + "; ".join(bad))
        emit("rollback", True,
             f"fleet back on {report.previous}", t0)
        report.outcome = "rolled_back"
        obs_metrics.FLEET_ROLLOUTS.inc(outcome="rolled_back")

    with tracing.span("fleet.rollout"):
        # ------------------------------------------------------- plan
        t0 = time.monotonic()
        _fire_stage_faults()
        target_dir = generations.current_generation(db_root)
        if target_dir is None:
            raise RolloutError(
                f"DB root {db_root!r} has no promoted generation "
                "(last-good): stage and promote the refresh first")
        report.target = os.path.basename(target_dir)
        status = fleet_status(endpoints, token=token)
        not_ready = [s for s in status if not s.get("ready")]
        if not_ready:
            raise RolloutError(
                "fleet not ready, refusing to start: " + "; ".join(
                    f"{s['endpoint']}: {s.get('status')}"
                    for s in not_ready))
        serving = {s["endpoint"]: s.get("generation") for s in status}
        behind = [ep for ep in endpoints
                  if serving.get(ep) != report.target]
        prev = {serving[ep] for ep in behind if serving.get(ep)}
        if not behind:
            emit("plan", True,
                 f"fleet already serving {report.target}", t0)
            report.outcome = "noop"
            obs_metrics.FLEET_ROLLOUTS.inc(outcome="noop")
            report.wall_s = time.monotonic() - t_start
            return report
        if len(prev) > 1:
            raise RolloutError(
                f"fleet serves mixed generations {sorted(prev)}; "
                "re-run after converging (a previous rollout may have "
                "been interrupted)")
        report.previous = next(iter(prev)) if prev else None
        report.canary = canary.rstrip("/") if canary else behind[0]
        if report.canary not in behind:
            raise RolloutError(
                f"canary {report.canary} is not behind "
                f"(serves {serving.get(report.canary)})")
        emit("plan", True,
             f"{len(behind)}/{len(endpoints)} replica(s) to roll "
             f"{report.previous} -> {report.target}", t0)

        # ----------------------------------------------------- canary
        t0 = time.monotonic()
        _fire_stage_faults()
        try:
            doc = reload_ep(report.canary, want_rescore=False)
        except (RolloutError, OSError) as exc:
            emit("canary", False, str(exc), t0)
            rollback(target_dir, [])
            report.wall_s = time.monotonic() - t_start
            return report
        if doc.get("serving") != report.target or doc.get("degraded"):
            # the canary's own guarded swap rejected the candidate
            # (quarantined server-side); the fleet never saw it
            emit("canary", False,
                 f"candidate rejected: serving={doc.get('serving')} "
                 f"degraded={doc.get('degraded')!r}", t0)
            rollback(target_dir, [])
            report.wall_s = time.monotonic() - t_start
            return report
        emit("canary", True,
             f"{report.canary} serving {report.target}", t0)

        # ------------------------------------------------------ probe
        t0 = time.monotonic()
        if probes:
            report.probes = len(probes)
            reference = next(
                (ep for ep in endpoints
                 if ep != report.canary
                 and serving.get(ep) == report.previous), None)
            diffs = 0
            for probe in probes:
                _fire_stage_faults()
                with tracing.span("fleet.probe"):
                    c_status, c_bytes = _replay_probe(
                        report.canary, probe, token)
                    if reference is None:
                        ok = c_status == 200
                        r_status, r_bytes = c_status, c_bytes
                    else:
                        r_status, r_bytes = _replay_probe(
                            reference, probe, token)
                        ok = (c_status == r_status == 200
                              and c_bytes == r_bytes)
                if not ok:
                    diffs += 1
            report.probe_diffs = diffs
            if diffs:
                emit("probe", False,
                     f"{diffs}/{len(probes)} probe(s) diverged on the "
                     "canary: regression", t0)
                rollback(target_dir, [report.canary],
                         quarantine=True)
                report.wall_s = time.monotonic() - t_start
                return report
            emit("probe", True,
                 f"{len(probes)} probe(s) zero-diff"
                 + ("" if reference else " (no reference replica;"
                    " status-only check)"), t0)
        else:
            emit("probe", True, "no probe set supplied", t0)

        # ------------------------------------------------------- roll
        t0 = time.monotonic()
        rolled = [report.canary]
        for ep in behind:
            if ep == report.canary:
                continue
            try:
                _fire_stage_faults()
                doc = reload_ep(ep, want_rescore=False)
                ready = readyz_doc(ep, token=token) or {}
                if doc.get("serving") != report.target \
                        or doc.get("degraded") \
                        or not ready.get("ready"):
                    raise RolloutError(
                        f"{ep} unhealthy after reload: "
                        f"serving={doc.get('serving')} "
                        f"degraded={doc.get('degraded')!r} "
                        f"ready={ready.get('ready')}")
            except (RolloutError, OSError) as exc:
                emit("roll", False, str(exc), t0)
                rollback(target_dir, rolled)
                report.wall_s = time.monotonic() - t_start
                return report
            rolled.append(ep)
        emit("roll", True, f"{len(rolled)} replica(s) on "
             f"{report.target}", t0)

        # ---------------------------------------------------- rescore
        t0 = time.monotonic()
        if rescore:
            # every reload above carried rescore=false, parking each
            # replica's delta re-score; consume the parked swap on
            # every MONITOR-ENABLED replica now. Indexes are
            # per-replica (each records the scans IT served), so this
            # re-scores each journaled artifact once fleet-wide —
            # after the whole fleet serves the new generation, instead
            # of N uncoordinated mid-rollout sweeps.
            monitored = [s["endpoint"] for s in status
                         if s.get("monitor")]
            if not monitored:
                emit("rescore", True,
                     "no monitor-enabled replica; delta re-score "
                     "skipped", t0)
            else:
                triggered, failed = [], []
                for ep in monitored:
                    rc_status, rc_doc = _post_json(
                        ep + "/fleet/rescore", token=token)
                    if rc_status == 200 and rc_doc.get("rescored"):
                        triggered.append(ep)
                    else:
                        failed.append(f"{ep}: {rc_doc}")
                report.rescored_on = triggered
                if failed:
                    # the fleet serves the new generation correctly
                    # either way — a failed re-score trigger degrades
                    # to the next promote re-planning (PR 9 ladder)
                    emit("rescore", False,
                         "re-score trigger failed on "
                         + "; ".join(failed), t0)
                else:
                    emit("rescore", True,
                         f"delta re-score triggered on "
                         f"{len(triggered)} monitor replica(s), each "
                         "covering its own journaled slice", t0)
        else:
            emit("rescore", True, "rescore disabled by caller", t0)

    report.wall_s = time.monotonic() - t_start
    obs_metrics.FLEET_ROLLOUTS.inc(outcome="completed")
    return report
