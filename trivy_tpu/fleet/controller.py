"""Self-driving fleet: the SLO-driven remediation/autoscaling control
loop (docs/fleet.md "Self-driving fleet").

The fleet detects everything (burn-rate SLO engine, skew detector,
durable ops event journal) and can do everything (guarded drains, the
rollout state machine, ``spawn[:N]`` DCN workers, mesh re-resolve) —
this module closes the loop: a :class:`FleetController` consumes the
probe/SLI stream and drives the existing actuators under an explicit,
journaled policy:

- **autoscale** — replicas scale up against offered load and back down
  under a cost floor (``min_replicas``) with scale-down hysteresis
  (``scale_down_holds`` consecutive calm ticks), so one quiet minute
  never collapses the fleet;
- **drain-and-replace** — a replica whose probe history crosses the
  unhealthy-streak threshold is drained (the PR 2 graceful drain),
  retired from the routing set (PR 12 retire semantics), and replaced;
- **mesh re-resolve** — a replica reporting *sustained* host
  degradation is told to re-resolve its mesh topology over the
  surviving hosts (``POST /fleet/reresolve``) instead of serving the
  coordinator's host-mask fallback indefinitely;
- **hedge tuning** — the smart-client hedge budget follows the
  measured p99/p50 probe-latency skew: a skewed fleet earns a bigger
  hedge budget, a uniform one returns to the configured baseline.

Every decision is an **action** from the closed :data:`ACTIONS`
vocabulary.  An action is journaled twice in the controller's own
append log (``durability/appendlog``): an ``intent`` record *before*
acting and an ``applied`` record after.  Replay is idempotent: every
tick, an intent without its ``applied`` record — a crash leftover or
the previous tick's failed action — is *reconciled* against the live
fleet first — if the intended state already holds, the action is
marked ``reconciled`` and never re-fired; otherwise it is re-fired
exactly once.  Each action is also emitted onto the fleet ops event
bus as a ``controller_action`` event, so one journal replay tells the
whole story.

``dry_run`` journals and emits every decision without touching the
fleet — the rehearsal contract the bench gate proves.  Fault site
``fleet.controller`` (docs/resilience.md) fires between the intent
and the act: every injected failure degrades the controller to
"observe only", and the intent/reconcile protocol guarantees an
action is never applied twice.
"""

from __future__ import annotations

import json
import os
import subprocess

import time

from trivy_tpu import fleet as fleet_mod
from trivy_tpu.durability.appendlog import AppendLog, AppendLogError
from trivy_tpu.fleet import slo as slo_mod
from trivy_tpu.fleet import telemetry
from trivy_tpu.fleet.endpoints import readyz_doc
from trivy_tpu.log import logger
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs import tracing
from trivy_tpu.resilience import faults

_log = logger("fleet.controller")

CONTROLLER_SITE = "fleet.controller"

# ----------------------------------------------------- action registry

#: The closed controller action vocabulary: (kind, what one action
#: means).  Machine-checked three ways by the ``event-kind`` lint rule
#: — every kind passed to :func:`emit_action` in code is declared
#: here, every declared kind is emitted somewhere, and docs/fleet.md's
#: action catalog lists exactly this set.
ACTIONS: tuple[tuple[str, str], ...] = (
    ("scale_up", "offered load per ready replica crossed the "
     "scale-up threshold: one replica spawned (capped at "
     "max_replicas)"),
    ("scale_down", "offered load stayed under the scale-down "
     "threshold for the full hysteresis window: one replica drained "
     "and retired (floored at min_replicas — the cost floor)"),
    ("drain_replace", "a replica's unhealthy-probe streak crossed the "
     "policy threshold: drained, retired from the routing set, and "
     "replaced by a fresh spawn"),
    ("mesh_reresolve", "a replica reported sustained host "
     "degradation: told to re-resolve its mesh topology over the "
     "surviving hosts instead of serving the host-mask fallback"),
    ("hedge_tune", "the smart-client hedge budget was retuned from "
     "the measured p99/p50 probe-latency skew"),
)

ACTION_KINDS = frozenset(k for k, _ in ACTIONS)


def controller_enabled() -> bool:
    """The ``TRIVY_TPU_CONTROLLER`` kill switch (default on): 0
    restores the pre-feature path — the loop observes and decides
    nothing, exactly as if no controller ran."""
    return os.environ.get("TRIVY_TPU_CONTROLLER", "1") != "0"


def emit_action(kind: str, **fields) -> dict | None:
    """Publish one controller action onto the fleet ops event bus as a
    ``controller_action`` event.  Validates the kind against the
    ACTIONS registry (an unknown kind is a programming error, caught
    by the event-kind lint rule before it ever fires here)."""
    if kind not in ACTION_KINDS:
        raise ValueError(
            f"unknown controller action kind {kind!r} — declare it in "
            "fleet.controller.ACTIONS (and docs/fleet.md's action "
            "catalog)")
    return slo_mod.emit_event("controller_action", action=kind, **fields)


# ------------------------------------------------------------- policy

def _parse_float(raw: str, name: str, default: float) -> float:
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        _log.warn(f"malformed {name}; using default", value=raw)
        return default


def _parse_int(raw: str, name: str, default: int) -> int:
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        _log.warn(f"malformed {name}; using default", value=raw)
        return default


def _env_defaults() -> dict:
    """The ``TRIVY_TPU_CONTROLLER_*`` knob family (docs/knobs.md),
    read as literal env lookups so the env-knob rule can hold each
    one against the registry."""
    return {
        "min_replicas": _parse_int(
            os.environ.get("TRIVY_TPU_CONTROLLER_MIN_REPLICAS", ""),
            "TRIVY_TPU_CONTROLLER_MIN_REPLICAS", 1),
        "max_replicas": _parse_int(
            os.environ.get("TRIVY_TPU_CONTROLLER_MAX_REPLICAS", ""),
            "TRIVY_TPU_CONTROLLER_MAX_REPLICAS", 4),
        "scale_up_load": _parse_float(
            os.environ.get("TRIVY_TPU_CONTROLLER_SCALE_UP_LOAD", ""),
            "TRIVY_TPU_CONTROLLER_SCALE_UP_LOAD", 4.0),
        "scale_down_load": _parse_float(
            os.environ.get("TRIVY_TPU_CONTROLLER_SCALE_DOWN_LOAD", ""),
            "TRIVY_TPU_CONTROLLER_SCALE_DOWN_LOAD", 1.0),
        "scale_down_holds": _parse_int(
            os.environ.get("TRIVY_TPU_CONTROLLER_HOLDS", ""),
            "TRIVY_TPU_CONTROLLER_HOLDS", 3),
        "cooldown_s": _parse_float(
            os.environ.get("TRIVY_TPU_CONTROLLER_COOLDOWN_S", ""),
            "TRIVY_TPU_CONTROLLER_COOLDOWN_S", 30.0),
        "unhealthy_ticks": _parse_int(
            os.environ.get("TRIVY_TPU_CONTROLLER_UNHEALTHY_TICKS", ""),
            "TRIVY_TPU_CONTROLLER_UNHEALTHY_TICKS", 3),
        "degraded_ticks": _parse_int(
            os.environ.get("TRIVY_TPU_CONTROLLER_DEGRADED_TICKS", ""),
            "TRIVY_TPU_CONTROLLER_DEGRADED_TICKS", 3),
        "hedge_skew": _parse_float(
            os.environ.get("TRIVY_TPU_CONTROLLER_HEDGE_SKEW", ""),
            "TRIVY_TPU_CONTROLLER_HEDGE_SKEW", 4.0),
    }


class ControllerPolicy:
    """The explicit policy every decision is judged against.  Defaults
    come from the ``TRIVY_TPU_CONTROLLER_*`` knobs (docs/knobs.md);
    constructor arguments win (tests, the CLI's flags)."""

    def __init__(self, min_replicas: int | None = None,
                 max_replicas: int | None = None,
                 scale_up_load: float | None = None,
                 scale_down_load: float | None = None,
                 scale_down_holds: int | None = None,
                 cooldown_s: float | None = None,
                 unhealthy_ticks: int | None = None,
                 degraded_ticks: int | None = None,
                 hedge_skew: float | None = None,
                 hedge_budget_hi: float = 0.3):
        env = _env_defaults()
        self.min_replicas = max(
            min_replicas if min_replicas is not None
            else env["min_replicas"], 1)
        self.max_replicas = max(
            max_replicas if max_replicas is not None
            else env["max_replicas"], self.min_replicas)
        self.scale_up_load = (
            scale_up_load if scale_up_load is not None
            else env["scale_up_load"])
        self.scale_down_load = (
            scale_down_load if scale_down_load is not None
            else env["scale_down_load"])
        self.scale_down_holds = max(
            scale_down_holds if scale_down_holds is not None
            else env["scale_down_holds"], 1)
        self.cooldown_s = max(
            cooldown_s if cooldown_s is not None
            else env["cooldown_s"], 0.0)
        self.unhealthy_ticks = max(
            unhealthy_ticks if unhealthy_ticks is not None
            else env["unhealthy_ticks"], 1)
        self.degraded_ticks = max(
            degraded_ticks if degraded_ticks is not None
            else env["degraded_ticks"], 1)
        self.hedge_skew = (
            hedge_skew if hedge_skew is not None
            else env["hedge_skew"])
        self.hedge_budget_hi = min(max(hedge_budget_hi, 0.0), 1.0)

    def doc(self) -> dict:
        return {k: v for k, v in vars(self).items()}


# ------------------------------------------------------ action journal

class ActionJournal:
    """The controller's own durable decision log: an fsynced append
    log (``durability/appendlog``) of ``intent``/``applied`` record
    pairs keyed by a monotonically-assigned action id.

    The two-record protocol is the crash-safety contract: the intent
    hits the disk *before* the actuator is touched, the applied record
    after, so replay can always tell "decided but maybe not done"
    (intent without applied — reconcile before re-acting) from "done"
    (never re-act)."""

    HEADER = {"log": "controller-actions", "v": 1}

    def __init__(self, log: AppendLog, past: list[dict]):
        self._log = log
        self._next_id = 1 + max(
            (int(r.get("id", 0)) for r in past), default=0)
        self._applied = {int(r["id"]) for r in past
                         if r.get("phase") == "applied" and "id" in r}
        self._intents = {int(r["id"]): r for r in past
                         if r.get("phase") == "intent" and "id" in r}

    @classmethod
    def open(cls, path: str) -> "ActionJournal":
        """Open (or create) the journal and replay it — torn tail
        truncated, mid-file rot skipped — restoring the applied-id set
        and any pending intents."""
        if os.path.exists(path):
            try:
                log, past = AppendLog.replay(path)
            except AppendLogError:
                # the create-time header write itself tore: appends
                # from the rest of that run are intact line-bounded
                # records carrying the applied-id set — salvage them
                # rather than crash-loop the controller on restart
                log, past = AppendLog.salvage(path, dict(cls.HEADER))
            if log.header.get("log") != "controller-actions":
                log.close()
                raise AppendLogError(
                    f"{path} is not a controller action journal "
                    f"(header {log.header.get('log')!r})")
            return cls(log, past)
        return cls(AppendLog.create(path, dict(cls.HEADER)), [])

    @property
    def path(self) -> str:
        return self._log.path

    def pending(self) -> list[dict]:
        """Intents with no applied record yet — the crash leftovers a
        restarted controller must reconcile before acting again."""
        return [dict(r) for i, r in sorted(self._intents.items())
                if i not in self._applied]

    def intent(self, action: str, **fields) -> int:
        """Durably record the decision BEFORE acting; returns the
        action id the applied record must carry."""
        aid = self._next_id
        self._next_id += 1
        rec = {"phase": "intent", "id": aid, "action": action,
               "ts": round(time.time(), 3), **fields}
        self._log.append(rec)
        self._intents[aid] = rec
        return aid

    def applied(self, aid: int, outcome: str, **fields) -> None:
        """Durably record the action's resolution: ``applied`` /
        ``dry_run`` / ``reconciled`` / ``dropped``."""
        self._log.append({"phase": "applied", "id": aid,
                          "outcome": outcome,
                          "ts": round(time.time(), 3), **fields})
        self._applied.add(aid)

    def records(self) -> list[dict]:
        """Read-only replay of the whole journal from disk."""
        log, past = AppendLog.replay(self.path)
        log.close()
        return past

    def compact(self, keep_last: int = 256) -> None:
        """Drop all but the newest ``keep_last`` records (atomically —
        a crash mid-compact leaves the previous journal intact).
        Pending intents always survive compaction: reconcile state
        must never be rotated away."""
        past = self.records()
        keep = past[-keep_last:] if keep_last >= 0 else past
        pending_ids = {r["id"] for r in self.pending()}
        kept_ids = {r.get("id") for r in keep}
        keep = [r for r in past
                if r.get("id") in pending_ids
                and r.get("id") not in kept_ids] + keep
        self._log.rewrite(keep)

    def close(self) -> None:
        self._log.close()


# ---------------------------------------------------------- actuators

class ActuatorError(Exception):
    """An actuator could not perform the requested fleet action."""


class LocalFleetActuator:
    """An in-process fleet the controller can really drive: replica
    servers owned by a factory callable, an optional
    :class:`~trivy_tpu.fleet.endpoints.EndpointSet` kept in sync for
    routing/hedge tuning, and a pluggable offered-load signal.  The
    bench's ``--selfdrive`` rung and the controller tests run against
    this; a live deployment uses :class:`HttpFleetActuator`."""

    def __init__(self, factory, endpoint_set=None, load_fn=None,
                 token: str | None = None,
                 drain_timeout_s: float = 10.0):
        self._factory = factory
        self._servers: dict[str, object] = {}
        self._es = endpoint_set
        # no load_fn = no load signal (offered_load None): the
        # controller then never scales on load, same contract as the
        # HTTP actuator without a signal
        self._load_fn = load_fn
        self._token = token
        self._drain_timeout_s = drain_timeout_s

    # -- membership ---------------------------------------------------
    @property
    def urls(self) -> list[str]:
        return list(self._servers)

    def adopt(self, server) -> str:
        """Register an already-running replica server."""
        url = server.address
        self._servers[url] = server
        self._sync_endpoints()
        return url

    def _sync_endpoints(self) -> None:
        # an empty server map still syncs: retiring the LAST replica
        # must retire its endpoint too, not leave the set routing to
        # a dead URL
        if self._es is not None:
            self._es.set_endpoints(list(self._servers))

    # -- observation --------------------------------------------------
    def observe(self) -> dict:
        statuses = []
        for url in list(self._servers):
            t0 = time.monotonic()
            doc = readyz_doc(url, token=self._token)
            probe_s = time.monotonic() - t0
            statuses.append({
                "endpoint": url,
                "ready": bool(doc.get("ready")) if doc else False,
                "generation": doc.get("generation") if doc else None,
                "mesh": doc.get("mesh") if doc else None,
                "probe_s": probe_s,
            })
        load = (float(self._load_fn())
                if self._load_fn is not None else None)
        return {"statuses": statuses, "offered_load": load,
                "replicas": list(self._servers)}

    # -- actions ------------------------------------------------------
    def spawn_replica(self) -> str:
        srv = self._factory()
        url = srv.address
        self._servers[url] = srv
        self._sync_endpoints()
        return url

    def drain_replica(self, url: str) -> bool:
        srv = self._servers.get(url)
        if srv is None:
            return False
        try:
            srv.drain(self._drain_timeout_s)
        except Exception as exc:
            # a dead replica cannot drain; retiring it is the point
            _log.warn("drain failed; retiring anyway", url=url,
                      err=str(exc))
        return True

    def retire_replica(self, url: str) -> None:
        srv = self._servers.pop(url, None)
        self._sync_endpoints()
        if srv is not None:
            try:
                srv.shutdown()
            except Exception as exc:
                _log.warn("replica shutdown failed", url=url,
                          err=str(exc))

    def reresolve_mesh(self, url: str) -> dict:
        from trivy_tpu.fleet.rollout import post_json

        status, doc = post_json(url.rstrip("/") + "/fleet/reresolve",
                                token=self._token)
        if status != 200:
            raise ActuatorError(
                f"reresolve on {url} failed: HTTP {status} {doc}")
        return doc

    def set_hedge_budget(self, budget: float) -> bool:
        if self._es is None:
            return False
        self._es.set_hedge_budget(budget)
        return True

    def close(self) -> None:
        for url in list(self._servers):
            self.retire_replica(url)


class HttpFleetActuator:
    """A live fleet behind HTTP: observation via JSON ``/readyz``,
    drains via ``POST /fleet/drain``, mesh re-resolve via
    ``POST /fleet/reresolve``, and replica spawn via an operator-
    provided shell command whose stdout's last line is the new
    replica's URL (how the controller reaches whatever supervisor
    actually owns processes — systemd, k8s, a lab script).  Hedge
    tuning is advisory here: the budget lives in the scan *clients*,
    so the emitted action carries the recommendation.

    Offered load is a **real** signal or nothing: an operator-provided
    ``load_cmd`` (stdout's last line is a number) wins; otherwise the
    in-flight scan counts the replicas report in their ``/readyz``
    JSON (``inflight``) are summed.  With neither available the
    observation carries ``offered_load=None`` and the controller
    refuses to scale on load — a proxy like "how many replicas look
    down" is *not* load, and scaling down on it would drain a healthy
    idle-looking fleet."""

    def __init__(self, urls: list[str], token: str | None = None,
                 spawn_cmd: str | None = None,
                 load_cmd: str | None = None,
                 drain_timeout_s: float = 30.0):
        self._urls = [u.rstrip("/") for u in urls]
        self._token = token
        self._spawn_cmd = spawn_cmd
        self._load_cmd = load_cmd
        self._drain_timeout_s = drain_timeout_s

    @property
    def urls(self) -> list[str]:
        return list(self._urls)

    def _command_load(self) -> float | None:
        """Run the operator's load command; its stdout's last
        non-empty line must be a number.  Any failure means "no load
        signal this tick" (None), never a fabricated zero."""
        try:
            proc = subprocess.run(
                self._load_cmd, shell=True, capture_output=True,
                text=True, timeout=60.0)
        except (subprocess.TimeoutExpired, OSError) as exc:
            _log.warn("load command failed; no load signal this tick",
                      err=str(exc))
            return None
        if proc.returncode != 0:
            _log.warn("load command failed; no load signal this tick",
                      rc=proc.returncode,
                      stderr=proc.stderr.strip()[:200])
            return None
        lines = [ln.strip() for ln in proc.stdout.splitlines()
                 if ln.strip()]
        try:
            return float(lines[-1]) if lines else None
        except ValueError:
            _log.warn("load command printed no number on its last "
                      "stdout line; no load signal this tick",
                      line=lines[-1][:80])
            return None

    def observe(self) -> dict:
        statuses = []
        inflight: list[float] = []
        for url in self._urls:
            t0 = time.monotonic()
            doc = readyz_doc(url, token=self._token)
            probe_s = time.monotonic() - t0
            if doc and doc.get("inflight") is not None:
                try:
                    inflight.append(float(doc["inflight"]))
                except (TypeError, ValueError):
                    pass
            statuses.append({
                "endpoint": url,
                "ready": bool(doc.get("ready")) if doc else False,
                "generation": doc.get("generation") if doc else None,
                "mesh": doc.get("mesh") if doc else None,
                "probe_s": probe_s,
            })
        if self._load_cmd:
            load = self._command_load()
        elif inflight:
            load = sum(inflight)
        else:
            load = None  # no genuine signal: never scale on a proxy
        return {"statuses": statuses, "offered_load": load,
                "replicas": list(self._urls)}

    def spawn_replica(self) -> str:
        if not self._spawn_cmd:
            raise ActuatorError(
                "no --spawn-cmd configured: the controller cannot "
                "create replicas on this fleet")
        try:
            proc = subprocess.run(
                self._spawn_cmd, shell=True, capture_output=True,
                text=True, timeout=300.0)
        except (subprocess.TimeoutExpired, OSError) as exc:
            # a hung or unlaunchable spawn command must degrade the
            # loop to observe-only (tick catches ActuatorError), not
            # kill it
            raise ActuatorError(
                f"spawn command did not complete: {exc}") from exc
        if proc.returncode != 0:
            raise ActuatorError(
                f"spawn command failed (rc {proc.returncode}): "
                f"{proc.stderr.strip()[:200]}")
        lines = [ln.strip() for ln in proc.stdout.splitlines()
                 if ln.strip()]
        if not lines or "://" not in lines[-1]:
            raise ActuatorError(
                "spawn command printed no replica URL on its last "
                "stdout line")
        url = lines[-1].rstrip("/")
        self._urls.append(url)
        return url

    def drain_replica(self, url: str) -> bool:
        from trivy_tpu.fleet.rollout import post_json

        status, doc = post_json(
            url.rstrip("/") + "/fleet/drain", token=self._token,
            body={"timeout_s": self._drain_timeout_s},
            timeout=self._drain_timeout_s + 30.0)
        if status != 200:
            _log.warn("drain request failed; retiring anyway",
                      url=url, status=status, reply=doc)
        return True

    def retire_replica(self, url: str) -> None:
        url = url.rstrip("/")
        self._urls = [u for u in self._urls if u != url]

    def reresolve_mesh(self, url: str) -> dict:
        from trivy_tpu.fleet.rollout import post_json

        status, doc = post_json(url.rstrip("/") + "/fleet/reresolve",
                                token=self._token)
        if status != 200:
            raise ActuatorError(
                f"reresolve on {url} failed: HTTP {status} {doc}")
        return doc

    def set_hedge_budget(self, budget: float) -> bool:
        return False  # client-side knob; the emitted action advises


# --------------------------------------------------------- controller

class _Decision:
    """One action the policy wants this tick, with the callable that
    performs it and the predicate replay uses to reconcile a crashed
    attempt against live state."""

    def __init__(self, action: str, fields: dict, apply_fn,
                 holds_fn=None):
        self.action = action
        self.fields = fields
        self.apply_fn = apply_fn
        self.holds_fn = holds_fn or (lambda obs: False)


class FleetController:
    """The control loop.  One :meth:`tick` = observe → reconcile any
    crash-pending intents → decide under the policy → act, with every
    action journaled (intent before, applied after) and emitted as a
    ``controller_action`` ops event.  ``dry_run`` journals and emits
    without acting."""

    def __init__(self, actuator, policy: ControllerPolicy | None = None,
                 journal_path: str | None = None, dry_run: bool = False,
                 clock=time.monotonic):
        self.actuator = actuator
        self.policy = policy or ControllerPolicy()
        self.dry_run = bool(dry_run)
        self._clock = clock
        self.journal = (ActionJournal.open(journal_path)
                        if journal_path else None)
        self._last_action_ts: dict[str, float] = {}
        self._calm_ticks = 0
        self._unhealthy: dict[str, int] = {}
        self._degraded: dict[str, int] = {}
        self._hedge_budget = fleet_mod.hedge_budget()
        self._hedge_baseline = self._hedge_budget
        self.ticks = 0

    # ----------------------------------------------------- fault site
    @staticmethod
    def _fire_site() -> str | None:
        """Run the ``fleet.controller`` fault ladder at the action
        boundary (between the journaled intent and the act): ``kill``
        crashes the controller there, ``delay`` stalls it, ``error``
        aborts the action (reconciled next tick), ``drop`` skips the
        act.  Returns the action-degrading verdict, if any."""
        rules = faults.fire(CONTROLLER_SITE)
        faults.check_kill(CONTROLLER_SITE, rules=rules)
        verdict = None
        for r in rules:
            if r.action == "delay":
                time.sleep(r.param if r.param is not None else 0.05)
            elif r.action == "error":
                verdict = "error"
            elif r.action == "drop" and verdict is None:
                verdict = "drop"
        return verdict

    # ------------------------------------------------------ execution
    def _execute(self, d: _Decision, outcome_hint: str | None = None,
                 aid: int | None = None) -> dict:
        """Run one decision through the intent → fault site → act →
        applied protocol.  ``aid`` is set when re-firing a replayed
        intent (no second intent record)."""
        kind = d.action
        if aid is None and self.journal is not None:
            aid = self.journal.intent(kind, **d.fields)
        verdict = self._fire_site()
        if verdict == "error":
            # the action is NOT applied; the intent stays pending and
            # the next tick reconciles it before any re-fire
            obs_metrics.CONTROLLER_ACTIONS.inc(kind=kind,
                                               outcome="failed")
            raise ActuatorError(
                f"injected controller error at {CONTROLLER_SITE}")
        outcome = outcome_hint
        result: dict = {}
        if verdict == "drop":
            outcome = "dropped"
        elif self.dry_run:
            outcome = "dry_run"
        elif outcome is None:
            result = d.apply_fn() or {}
            outcome = "applied"
        if self.journal is not None and aid is not None:
            self.journal.applied(aid, outcome, **result)
        # lint: allow[event-kind] dispatch funnel; every kind reaching here is a literal from a _Decision site, validated against ACTION_KINDS
        emit_action(kind, outcome=outcome, **d.fields)
        obs_metrics.CONTROLLER_ACTIONS.inc(kind=kind, outcome=outcome)
        self._last_action_ts[kind] = self._clock()
        _log.info("controller action", action=kind, outcome=outcome,
                  **d.fields)
        return {"action": kind, "outcome": outcome, **d.fields}

    def _cooled(self, kind: str) -> bool:
        last = self._last_action_ts.get(kind)
        return (last is None
                or self._clock() - last >= self.policy.cooldown_s)

    # ----------------------------------------------------- reconcile
    def _reconcile(self, obs: dict) -> list[dict]:
        """Every tick, before deciding: every intent without an
        applied record — a crashed restart's leftovers *or* the
        previous tick's failed action — is checked against the live
        fleet.  Holds already → ``reconciled`` (never re-fired);
        otherwise re-fired exactly once under the same journaled id.
        Running this each tick (not just at start) means a mid-run
        failed intent is resolved while the observation is still
        fresh, instead of lingering unsealed until an arbitrarily
        later restart re-fires it against a fleet the policy has
        legitimately moved on."""
        if self.journal is None:
            return []
        done = []
        for rec in self.journal.pending():
            d = self._rebuild_decision(rec, obs)
            if d is None:
                self.journal.applied(rec["id"], "reconciled",
                                     reason="stale intent")
                continue
            if d.holds_fn(obs):
                self.journal.applied(rec["id"], "reconciled")
                # lint: allow[event-kind] replayed intents carry kinds a _Decision site journaled; validated against ACTION_KINDS
                emit_action(rec["action"], outcome="reconciled",
                            **d.fields)
                obs_metrics.CONTROLLER_ACTIONS.inc(
                    kind=rec["action"], outcome="reconciled")
                done.append({"action": rec["action"],
                             "outcome": "reconciled", **d.fields})
            else:
                try:
                    done.append(self._execute(d, aid=rec["id"]))
                except ActuatorError as exc:
                    # still pending; the next tick reconciles again
                    _log.warn("re-fired intent failed; still pending",
                              action=rec["action"], err=str(exc))
                    done.append({"action": rec["action"],
                                 "outcome": "failed",
                                 "error": str(exc), **d.fields})
        return done

    def _rebuild_decision(self, rec: dict, obs: dict):
        kind = rec.get("action")
        fields = {k: v for k, v in rec.items()
                  if k not in ("phase", "id", "ts", "action")}
        if kind in ("scale_up", "scale_down"):
            want = int(rec.get("want", 0))
            if not want:
                return None
            up = kind == "scale_up"
            return _Decision(
                kind, fields,
                (self._apply_scale_up if up
                 else lambda: self._apply_scale_down(
                     rec.get("target") or self._pick_scale_down(obs))),
                holds_fn=lambda o: (len(o["replicas"]) >= want if up
                                    else len(o["replicas"]) <= want))
        if kind == "drain_replace":
            target = rec.get("target")
            if not target:
                return None
            return _Decision(
                kind, fields,
                lambda: self._apply_drain_replace(target),
                holds_fn=lambda o: target not in o["replicas"])
        if kind == "mesh_reresolve":
            target = rec.get("target")
            if not target:
                return None
            # the server-side re-resolve is idempotent (no degraded
            # hosts -> no-op), so re-firing is always safe
            return _Decision(
                kind, fields,
                lambda: self.actuator.reresolve_mesh(target),
                holds_fn=lambda o: not self._degraded_hosts_of(
                    o, target))
        if kind == "hedge_tune":
            budget = rec.get("budget")
            if budget is None:
                return None
            return _Decision(
                kind, fields,
                lambda: self._apply_hedge(float(budget)),
                holds_fn=lambda o: self._hedge_budget == float(budget))
        return None

    # ------------------------------------------------------- decisions
    @staticmethod
    def _degraded_hosts_of(obs: dict, url: str) -> list:
        for s in obs["statuses"]:
            if s.get("endpoint") == url:
                return list((s.get("mesh") or {}).get("degraded_hosts")
                            or ())
        return []

    def _pick_scale_down(self, obs: dict) -> str | None:
        ready = [s["endpoint"] for s in obs["statuses"]
                 if s.get("ready")]
        return ready[-1] if len(ready) > 1 else None

    def _apply_scale_up(self) -> dict:
        return {"spawned": self.actuator.spawn_replica()}

    def _apply_scale_down(self, target: str | None) -> dict:
        if not target:
            return {"skipped": "no drainable replica"}
        self.actuator.drain_replica(target)
        self.actuator.retire_replica(target)
        return {"retired": target}

    def _apply_drain_replace(self, target: str) -> dict:
        self.actuator.drain_replica(target)
        self.actuator.retire_replica(target)
        self._unhealthy.pop(target, None)
        return {"retired": target,
                "spawned": self.actuator.spawn_replica()}

    def _apply_hedge(self, budget: float) -> dict:
        applied = self.actuator.set_hedge_budget(budget)
        self._hedge_budget = budget
        return {"client_applied": bool(applied)}

    def _decide(self, obs: dict) -> list[_Decision]:
        pol = self.policy
        statuses = obs["statuses"]
        replicas = obs["replicas"]
        n = len(replicas)
        out: list[_Decision] = []

        # -- drain-and-replace: probe-history threshold ---------------
        for s in statuses:
            url = s["endpoint"]
            if s.get("ready"):
                self._unhealthy.pop(url, None)
            else:
                self._unhealthy[url] = self._unhealthy.get(url, 0) + 1
        for url, streak in list(self._unhealthy.items()):
            if url not in replicas:
                self._unhealthy.pop(url, None)
                continue
            if streak >= pol.unhealthy_ticks \
                    and self._cooled("drain_replace"):
                out.append(_Decision(
                    "drain_replace",
                    {"target": url, "unhealthy_ticks": streak},
                    lambda u=url: self._apply_drain_replace(u),
                    holds_fn=lambda o, u=url: u not in o["replicas"]))
                break  # one replacement per tick; the loop is patient

        # -- autoscale under the cost floor ---------------------------
        ready_n = sum(1 for s in statuses if s.get("ready"))
        load = obs.get("offered_load")
        per_replica = (load / max(ready_n, 1)
                       if load is not None else None)
        if n < pol.min_replicas:
            # below the floor — the operator raised it, or a replica
            # died outside a drain: restore it regardless of load
            self._calm_ticks = 0
            if self._cooled("scale_up") \
                    and not any(d.action == "drain_replace"
                                for d in out):
                want = n + 1
                out.append(_Decision(
                    "scale_up",
                    {"want": want, "reason": "below_min_replicas"},
                    self._apply_scale_up,
                    holds_fn=lambda o, w=want: len(o["replicas"]) >= w))
        elif per_replica is None:
            # no genuine load signal this tick (actuator without a
            # load source, or its load command failed): hold the
            # replica count — scaling on a proxy would retire healthy
            # replicas.  The floor restore above, drain-and-replace,
            # mesh re-resolve and hedge tuning all still run.
            self._calm_ticks = 0
        elif per_replica > pol.scale_up_load:
            self._calm_ticks = 0
            if n < pol.max_replicas and self._cooled("scale_up") \
                    and not any(d.action == "drain_replace"
                                for d in out):
                want = n + 1
                out.append(_Decision(
                    "scale_up",
                    {"want": want,
                     "load_per_replica": round(per_replica, 2)},
                    self._apply_scale_up,
                    holds_fn=lambda o, w=want: len(o["replicas"]) >= w))
        elif per_replica < pol.scale_down_load:
            self._calm_ticks += 1
            if self._calm_ticks >= pol.scale_down_holds \
                    and n > pol.min_replicas \
                    and self._cooled("scale_down") \
                    and not any(d.action == "drain_replace"
                                for d in out):
                want = n - 1
                target = self._pick_scale_down(obs)
                out.append(_Decision(
                    "scale_down",
                    {"want": want, "target": target,
                     "calm_ticks": self._calm_ticks,
                     "load_per_replica": round(per_replica, 2)},
                    lambda t=target: self._apply_scale_down(t),
                    holds_fn=lambda o, w=want: len(o["replicas"]) <= w))
                self._calm_ticks = 0
        else:
            self._calm_ticks = 0

        # -- sustained host degradation: mesh re-resolve --------------
        for s in statuses:
            url = s["endpoint"]
            dhosts = list((s.get("mesh") or {}).get("degraded_hosts")
                          or ())
            if dhosts:
                self._degraded[url] = self._degraded.get(url, 0) + 1
            else:
                self._degraded.pop(url, None)
            if self._degraded.get(url, 0) >= pol.degraded_ticks \
                    and self._cooled("mesh_reresolve"):
                out.append(_Decision(
                    "mesh_reresolve",
                    {"target": url, "hosts": dhosts,
                     "degraded_ticks": self._degraded[url]},
                    lambda u=url: self.actuator.reresolve_mesh(u),
                    holds_fn=lambda o, u=url:
                        not self._degraded_hosts_of(o, u)))
                self._degraded[url] = 0

        # -- hedge budget from p99/p50 probe skew ---------------------
        q = telemetry.probe_quantiles(
            [s.get("probe_s") for s in statuses])
        if q:
            p50, p99, skew = q["p50_s"], q["p99_s"], q["skew"]
            want = None
            if skew >= pol.hedge_skew \
                    and self._hedge_budget != pol.hedge_budget_hi:
                want = pol.hedge_budget_hi
            elif skew < pol.hedge_skew / 2.0 \
                    and self._hedge_budget != self._hedge_baseline:
                want = self._hedge_baseline
            if want is not None and self._cooled("hedge_tune"):
                out.append(_Decision(
                    "hedge_tune",
                    {"budget": want, "skew": round(skew, 2),
                     "p50_s": round(p50, 4), "p99_s": round(p99, 4)},
                    lambda b=want: self._apply_hedge(b),
                    holds_fn=lambda o, b=want:
                        self._hedge_budget == b))
        return out

    # ------------------------------------------------------------ tick
    def tick(self) -> dict:
        """One control pass.  Returns the tick report: observations,
        reconciled leftovers, and the actions taken (or rehearsed
        under ``dry_run``)."""
        self.ticks += 1
        obs_metrics.CONTROLLER_TICKS.inc()
        if not controller_enabled():
            return {"enabled": False, "actions": [],
                    "reconciled": []}
        with tracing.span("fleet.control"):
            obs = self.actuator.observe()
            obs_metrics.CONTROLLER_REPLICAS.set(
                float(len(obs["replicas"])))
            reconciled = self._reconcile(obs)
            actions = []
            # a tick that reconciled crash-pending intents makes no
            # fresh decisions: the observation predates the re-fires,
            # and deciding on it could double an action the replay
            # just performed — wait one tick for a fresh observation
            for d in (self._decide(obs) if not reconciled else []):
                try:
                    actions.append(self._execute(d))
                except ActuatorError as exc:
                    _log.warn("controller action failed; will "
                              "reconcile next tick",
                              action=d.action, err=str(exc))
                    actions.append({"action": d.action,
                                    "outcome": "failed",
                                    "error": str(exc), **d.fields})
        return {"enabled": True, "replicas": obs["replicas"],
                "offered_load": obs["offered_load"],
                "reconciled": reconciled, "actions": actions}

    def run(self, interval_s: float = 5.0, max_ticks: int | None = None,
            stop=None, on_tick=None) -> int:
        """The blocking loop behind ``trivy-tpu fleet control``."""
        import threading

        stop = stop or threading.Event()
        done = 0
        while not stop.is_set():
            report = self.tick()
            done += 1
            if on_tick is not None:
                on_tick(report)
            if max_ticks is not None and done >= max_ticks:
                break
            if stop.wait(interval_s):
                break
        return done

    def close(self) -> None:
        if self.journal is not None:
            self.journal.close()


def render_report(report: dict) -> str:
    """One tick report as a JSON line (the CLI's stdout stream)."""
    return json.dumps(report, sort_keys=True)
