"""Fleet ops event bus, durable event journal, and SLO burn-rate
engine (docs/fleet.md "Fleet observability control plane").

Fleet-level operations — hedge races, failovers, breaker trips,
rollout stages, DB swaps, shard degradations, replica skew — used to
leave no queryable record: each was a log line at best. This module
gives them one spine:

- **EVENTS registry** — the closed vocabulary of fleet event kinds.
  The ``event-kind`` lint rule (docs/static-analysis.md) enforces, in
  both directions, that every kind emitted in code is declared here
  and documented in docs/fleet.md's event catalog.
- **event bus** — :func:`emit_event` validates the kind, stamps a
  wall-clock timestamp + monotone sequence number, counts it in
  ``trivy_tpu_fleet_events_total{kind}``, keeps it in a bounded
  in-memory ring (``events_since`` — the ``/events`` tail), and, when
  a journal is installed, appends it durably.
  ``TRIVY_TPU_FLEET_EVENTS=0`` is the kill switch: emission collapses
  to one env check (guarded <2% by bench_fleetobs).
- **OpsEventLog** — the fsynced JSONL journal over
  ``durability/appendlog.py``: durable-when-returned appends, replay
  that tolerates a torn tail (the signature crash artifact), so a
  controller restart replays the fleet's operational history intact.
- **SLOEngine** — multi-window burn-rate alerting over
  availability/latency SLIs: a request is *good* when it succeeded
  (and, when a latency SLO is set, answered under the threshold);
  burn rate = error_rate / (1 - target). An alert fires when BOTH the
  long and the short window of any configured pair exceed the pair's
  factor (the short window makes firing fast, the long window keeps
  it spike-proof), journals ``slo_burn state=firing``, and resolves —
  journaled again — once every long window is back under.
- **SkewDetector** — cross-replica consistency watch: mixed advisory
  generations among ready replicas ("Vexed by VEX"'s failure class),
  probe-latency outliers vs the fleet median, and per-replica mesh
  shard degradations, each emitted on the *transition*, not per probe.
"""

from __future__ import annotations

import json
import os

from trivy_tpu.analysis.witness import make_lock
import time
from collections import deque

from trivy_tpu.durability.appendlog import AppendLog, AppendLogError
from trivy_tpu.log import logger
from trivy_tpu.obs import metrics as obs_metrics

_log = logger("fleet.slo")

# ------------------------------------------------------ event registry

#: The closed fleet event vocabulary: (kind, what one record means).
#: Machine-checked three ways by the ``event-kind`` lint rule — every
#: kind emitted in code is declared here, every declared kind is
#: emitted somewhere, and docs/fleet.md's event catalog lists exactly
#: this set.
EVENTS: tuple[tuple[str, str], ...] = (
    ("failover", "a request was retried on a different replica after "
     "a transport-level failure on its first choice"),
    ("hedge", "a hedged dispatch resolved (outcome=won/lost/denied)"),
    ("breaker", "a per-replica circuit breaker changed state "
     "(closed/half-open/open)"),
    ("probe_health", "a replica's /readyz health verdict flipped "
     "(healthy=true/false) as seen by the background prober"),
    ("shard_degraded", "a replica reported mesh shard(s) degraded to "
     "the host oracle (or recovered)"),
    ("replica_skew", "cross-replica inconsistency: mixed advisory-DB "
     "generations among ready replicas, or a probe-latency outlier "
     "vs the fleet median"),
    ("rollout_stage", "one stage of a coordinated advisory-DB rollout "
     "finished (ok=true/false)"),
    ("db_swap", "a replica hot-swapped its advisory DB during a "
     "coordinated rollout (serving=<generation>)"),
    ("slo_burn", "a multi-window burn-rate alert changed state "
     "(state=firing/resolved) over the fleet SLIs"),
    ("controller_action", "the fleet controller decided (and, unless "
     "dry-run/dropped, performed) one action from the "
     "fleet.controller.ACTIONS vocabulary (action=<kind>, "
     "outcome=applied/dry_run/reconciled/dropped)"),
)

KINDS = frozenset(k for k, _ in EVENTS)

_RING_N = 1024

_bus_lock = make_lock("fleet.slo._bus_lock")
_ring: deque = deque(maxlen=_RING_N)
_seq = 0
_journal: "OpsEventLog | None" = None
_env_journal_checked = False


def events_enabled() -> bool:
    """The ``TRIVY_TPU_FLEET_EVENTS`` kill switch (default on): 0
    restores the pre-feature path — no ring, no journal, no counter."""
    return os.environ.get("TRIVY_TPU_FLEET_EVENTS", "1") != "0"


def _maybe_env_journal_locked() -> None:
    """The bus is PROCESS-LOCAL: a journal installed in the controller
    (``fleet serve``/``rollout --journal``) cannot see the scan
    client's failover/hedge/breaker events. ``TRIVY_TPU_FLEET_EVENTS_
    JOURNAL`` closes that gap — any process (the smart client
    included) lazily installs a journal at that path on its first
    emit. Use one path per process: concurrent writers interleave."""
    global _env_journal_checked, _journal
    if _env_journal_checked or _journal is not None:
        return
    _env_journal_checked = True
    path = os.environ.get("TRIVY_TPU_FLEET_EVENTS_JOURNAL", "")
    if not path:
        return
    global _seq
    try:
        _journal, past = OpsEventLog.open(path)
        top = max((int(d.get("seq", 0)) for d in past), default=0)
        if top > _seq:
            _seq = top  # resume past the replay, like install_journal
    except (AppendLogError, OSError) as exc:
        _log.warn("TRIVY_TPU_FLEET_EVENTS_JOURNAL unusable; events "
                  "stay in-memory", path=path, err=str(exc))


def emit_event(kind: str, **fields) -> dict | None:
    """Publish one fleet ops event. Validates the kind against the
    EVENTS registry (an unknown kind is a programming error, caught by
    the event-kind lint rule before it ever fires here), stamps
    ``ts``/``seq``, counts it, rings it, and — when a journal is
    installed — appends it durably. Returns the event document, or
    None under the kill switch."""
    if not events_enabled():
        return None
    if kind not in KINDS:
        raise ValueError(
            f"unknown fleet event kind {kind!r} — declare it in "
            "fleet.slo.EVENTS (and docs/fleet.md's event catalog)")
    global _seq
    doc = {"kind": kind, "ts": round(time.time(), 3), **fields}
    with _bus_lock:
        _maybe_env_journal_locked()
        _seq += 1
        doc["seq"] = _seq
        _ring.append(doc)
        journal = _journal
        if journal is not None:
            try:
                journal.append(doc)
            except AppendLogError as exc:
                # a failed journal append must never break the serving
                # path that emitted the event; the ring still has it
                _log.warn("fleet event journal append failed",
                          kind=kind, err=str(exc))
    obs_metrics.FLEET_EVENTS.inc(kind=kind)
    return doc


def events_since(seq: int) -> tuple[int, list[dict]]:
    """Ring tail: events with a sequence number > ``seq`` (oldest
    first) and the cursor to pass next time — the same contract as the
    monitor's /monitor/events ring."""
    with _bus_lock:
        out = [dict(d) for d in _ring if d.get("seq", 0) > seq]
        return _seq, out


def install_journal(path: str) -> list[dict]:
    """Make the bus durable: every future emit appends (fsynced) to
    the ops journal at ``path``. An existing journal is replayed first
    — torn tail truncated, mid-file rot skipped — and its surviving
    records are returned, so a restarted controller sees the fleet's
    operational history; the bus sequence resumes past the replay."""
    global _journal, _seq
    log, past = OpsEventLog.open(path)
    with _bus_lock:
        if _journal is not None:
            _journal.close()
        _journal = log
        top = max((int(d.get("seq", 0)) for d in past), default=0)
        if top > _seq:
            _seq = top
    return past


def uninstall_journal() -> None:
    global _journal
    with _bus_lock:
        if _journal is not None:
            _journal.close()
        _journal = None


def reset_bus() -> None:
    """Test hook: drop the ring and detach any journal."""
    global _seq, _env_journal_checked
    uninstall_journal()
    with _bus_lock:
        _ring.clear()
        _seq = 0
        _env_journal_checked = False


# ----------------------------------------------------- durable journal


class OpsEventLog:
    """The fleet ops journal: an fsynced JSONL append log whose records
    are event documents. Same durability contract as the scan journal
    (docs/durability.md): ``append`` returns only after the record hit
    the disk; ``open`` replays, truncating a torn tail."""

    HEADER = {"log": "fleet-events", "v": 1}

    def __init__(self, log: AppendLog):
        self._log = log

    @classmethod
    def open(cls, path: str) -> tuple["OpsEventLog", list[dict]]:
        """-> (journal ready for appends, replayed past events)."""
        if os.path.exists(path):
            try:
                log, past = AppendLog.replay(path)
                return cls(log), past
            except AppendLogError as exc:
                # unreadable/headerless: quarantine-by-rename would hide
                # evidence; refuse and let the operator choose a path
                raise AppendLogError(
                    f"fleet event journal {path} unusable: {exc}")
        return cls(AppendLog.create(path, dict(cls.HEADER))), []

    @staticmethod
    def read(path: str) -> list[dict]:
        """Read-only replay (the ``fleet events`` CLI): surviving
        event records, torn tail tolerated, file left untouched."""
        log, past = AppendLog.replay(path)
        log.close()
        return past

    def append(self, doc: dict) -> None:
        self._log.append(doc)

    def compact(self, keep_last: int = 512) -> list[dict]:
        """Rotate the journal in place: atomically rewrite it as
        header + the newest ``keep_last`` events (a crash mid-compact
        leaves the previous journal intact). Returns the kept events.
        Followers detect the rewrite (new inode / shrunk size) and
        resume from the sealed replay point — :class:`JournalTail`."""
        past = self.read(self.path)
        keep = past[-keep_last:] if keep_last >= 0 else past
        self._log.rewrite(keep)
        return keep

    def close(self) -> None:
        self._log.close()

    @property
    def path(self) -> str:
        return self._log.path


class JournalTail:
    """Incremental, rotation-proof follower for an ops journal — what
    ``trivy-tpu fleet events --follow`` runs on.

    Each :meth:`poll` parses only the bytes appended since the last
    one (no O(file) re-replay per second) and returns the events whose
    ``seq`` is beyond the last one delivered. When the journal is
    compacted or rotated underneath the tail — the file shrinks below
    the parse offset, or the path resolves to a new inode after an
    atomic rewrite — the stale fd is dropped, the sealed journal is
    replayed from its start, and delivery resumes from the sealed
    replay point: the ``seq`` cursor, which survives rotation because
    the bus sequence is monotone across compactions. A torn tail (a
    partially-appended record) is left buffered until the writer
    completes it, never delivered as garbage."""

    def __init__(self, path: str, since: int = 0):
        self._path = path
        self._fd = None
        self._ino = -1
        self._offset = 0
        self._buf = b""
        self.last_seq = int(since)

    def _drop_fd(self) -> None:
        if self._fd is not None:
            try:
                self._fd.close()
            except OSError:
                pass
        self._fd = None
        self._ino = -1
        self._offset = 0
        self._buf = b""

    def _ensure_fd(self) -> bool:
        """(Re)open the journal when absent, rotated (new inode), or
        truncated (compaction rewrote it shorter than our offset)."""
        try:
            st = os.stat(self._path)
        except OSError:
            self._drop_fd()
            return False
        if self._fd is not None and st.st_ino == self._ino \
                and st.st_size >= self._offset:
            return True
        rotated = self._fd is not None
        self._drop_fd()
        try:
            self._fd = open(self._path, "rb")
            self._ino = os.fstat(self._fd.fileno()).st_ino
        except OSError:
            self._drop_fd()
            return False
        if rotated:
            _log.debug("ops journal rotated; resuming from the "
                       "sealed replay point", path=self._path,
                       since=self.last_seq)
        return True

    def poll(self) -> list[dict]:
        """New events (``seq`` beyond the last delivered), oldest
        first. Empty when nothing new, the journal is missing, or only
        a torn tail arrived."""
        if not self._ensure_fd():
            return []
        self._fd.seek(self._offset)
        chunk = self._fd.read()
        self._offset += len(chunk)
        self._buf += chunk
        complete, nl, rest = self._buf.rpartition(b"\n")
        if not nl:
            return []  # torn tail only; wait for the writer
        self._buf = rest
        out = []
        for line in complete.split(b"\n"):
            if not line.strip():
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue  # mid-file rot: replay-equivalent skip
            if not isinstance(doc, dict) or doc.get("kind") == "header":
                continue
            seq = int(doc.get("seq", 0))
            if seq > self.last_seq:
                self.last_seq = max(self.last_seq, seq)
                out.append(doc)
        return out

    def close(self) -> None:
        self._drop_fd()


# --------------------------------------------------------- SLO engine

DEFAULT_SLO_TARGET = 0.999

#: (long window s, short window s, burn-rate factor) pairs — the
#: classic multiwindow shape: the short window makes the alert fire
#: fast, the long window keeps one spike from paging.
DEFAULT_WINDOWS: tuple[tuple[float, float, float], ...] = (
    (300.0, 60.0, 14.4),
    (3600.0, 300.0, 6.0),
)


def slo_target() -> float:
    """Availability SLO target (``TRIVY_TPU_FLEET_SLO_TARGET``,
    default 0.999). Clamped to (0, 1)."""
    raw = os.environ.get("TRIVY_TPU_FLEET_SLO_TARGET", "")
    if raw:
        try:
            v = float(raw)
            if 0.0 < v < 1.0:
                return v
        except ValueError:
            pass
        _log.warn("malformed TRIVY_TPU_FLEET_SLO_TARGET; using default",
                  value=raw)
    return DEFAULT_SLO_TARGET


def slo_latency_s() -> float | None:
    """Latency SLI threshold in seconds
    (``TRIVY_TPU_FLEET_SLO_LATENCY_MS``; unset = availability-only: a
    slow-but-correct answer still counts as good)."""
    raw = os.environ.get("TRIVY_TPU_FLEET_SLO_LATENCY_MS", "")
    if not raw:
        return None
    try:
        return max(float(raw), 0.0) / 1000.0
    except ValueError:
        _log.warn("malformed TRIVY_TPU_FLEET_SLO_LATENCY_MS; ignoring",
                  value=raw)
        return None


class SLOEngine:
    """Multi-window burn-rate evaluation over a stream of good/bad
    samples, bucketed per second.

    burn = (bad / total) / (1 - target); an alert FIRES when any
    configured (long, short, factor) pair has both windows' burn at or
    above the factor, and RESOLVES once every long window is back
    under its factor. Both transitions are emitted (and journaled) as
    ``slo_burn`` events. ``clock`` is injectable for deterministic
    tests; production uses the monotonic clock so an NTP step cannot
    shift a window."""

    def __init__(self, target: float | None = None,
                 latency_s: float | None = None,
                 windows=DEFAULT_WINDOWS,
                 name: str = "fleet-availability",
                 clock=time.monotonic):
        self.target = slo_target() if target is None else float(target)
        self.latency_s = (slo_latency_s() if latency_s is None
                          else latency_s)
        self.windows = tuple(windows)
        self.name = name
        self._clock = clock
        self._lock = make_lock("fleet.slo.SLOEngine._lock")
        self._buckets: deque = deque()  # (second:int, good:int, bad:int)
        self._max_window = max(w[0] for w in self.windows)
        self.firing = False

    # ------------------------------------------------------- recording

    def record(self, ok: bool, latency_s: float | None = None,
               now: float | None = None) -> None:
        """One request outcome. With a latency SLO configured, a
        successful-but-slow answer counts as bad (it burned budget)."""
        good = bool(ok)
        if good and self.latency_s is not None \
                and latency_s is not None and latency_s > self.latency_s:
            good = False
        self.record_counts(1 if good else 0, 0 if good else 1, now=now)

    def record_counts(self, good: int, bad: int,
                      now: float | None = None) -> None:
        """Fold pre-aggregated counts in (the fleet monitor records
        federated counter deltas this way)."""
        if good <= 0 and bad <= 0:
            return
        sec = int(self._clock() if now is None else now)
        with self._lock:
            if self._buckets and self._buckets[-1][0] == sec:
                s, g, b = self._buckets[-1]
                self._buckets[-1] = (s, g + good, b + bad)
            else:
                self._buckets.append((sec, good, bad))
            horizon = sec - self._max_window - 1
            while self._buckets and self._buckets[0][0] < horizon:
                self._buckets.popleft()

    # ------------------------------------------------------ evaluation

    def _window_burn(self, window_s: float, now: float) -> float:
        lo = now - window_s
        good = bad = 0
        for sec, g, b in self._buckets:
            if sec >= lo:
                good += g
                bad += b
        total = good + bad
        if total == 0:
            return 0.0
        budget = max(1.0 - self.target, 1e-9)
        return (bad / total) / budget

    def evaluate(self, now: float | None = None) -> dict:
        """Evaluate every window pair; emit ``slo_burn`` on a firing or
        resolving transition. Returns the current state document (also
        what the federation /profile endpoint embeds)."""
        now = self._clock() if now is None else now
        burns = []
        fired_by = None
        with self._lock:
            for long_s, short_s, factor in self.windows:
                lb = self._window_burn(long_s, now)
                sb = self._window_burn(short_s, now)
                burns.append({"long_s": long_s, "short_s": short_s,
                              "factor": factor,
                              "long_burn": round(lb, 2),
                              "short_burn": round(sb, 2)})
                if lb >= factor and sb >= factor and fired_by is None:
                    fired_by = burns[-1]
            calm = all(b["long_burn"] < b["factor"] for b in burns)
            was_firing = self.firing
            if fired_by is not None and not was_firing:
                self.firing = True
            elif was_firing and calm:
                self.firing = False
            transition = self.firing != was_firing
        if transition:
            if self.firing:
                emit_event("slo_burn", state="firing", slo=self.name,
                           target=self.target, window=fired_by)
            else:
                emit_event("slo_burn", state="resolved", slo=self.name,
                           target=self.target)
        return {"slo": self.name, "target": self.target,
                "firing": self.firing, "windows": burns}


# ------------------------------------------------------- skew detector


class SkewDetector:
    """Cross-replica consistency watch over health-probe results.
    Stateful on purpose: every condition is emitted when it appears
    and when it clears, never once per probe pass."""

    #: probe latency is an outlier when it exceeds the fleet median by
    #: this factor AND the absolute floor (tiny medians would otherwise
    #: flag scheduler noise)
    OUTLIER_FACTOR = 4.0
    OUTLIER_FLOOR_S = 0.05

    def __init__(self):
        self._mixed: str = ""            # last mixed-generation signature
        self._outliers: set = set()      # endpoints currently flagged
        self._degraded: dict = {}        # endpoint -> degraded shard sig

    def observe(self, statuses: list[dict]) -> None:
        """One probe pass over the fleet. Each status document:
        ``{"endpoint", "ready", "generation", "mesh", "probe_s"}`` —
        what ``EndpointSet.probe_health`` / ``fleet_status`` collect."""
        self._check_generations(statuses)
        self._check_latency(statuses)
        self._check_shards(statuses)

    def _check_generations(self, statuses: list[dict]) -> None:
        by_gen: dict[str, list[str]] = {}
        for s in statuses:
            if s.get("ready") and s.get("generation"):
                by_gen.setdefault(s["generation"], []).append(
                    s.get("endpoint", "?"))
        sig = ""
        if len(by_gen) > 1:
            sig = ";".join(f"{g}={','.join(sorted(eps))}"
                           for g, eps in sorted(by_gen.items()))
        if sig and sig != self._mixed:
            emit_event("replica_skew", reason="generation_mismatch",
                       generations={g: sorted(eps)
                                    for g, eps in by_gen.items()})
        elif not sig and self._mixed:
            emit_event("replica_skew", reason="generation_converged")
        self._mixed = sig

    def _check_latency(self, statuses: list[dict]) -> None:
        lats = sorted(s["probe_s"] for s in statuses
                      if s.get("probe_s") is not None)
        if len(lats) < 3:
            return  # a median of two is just the other replica
        median = lats[len(lats) // 2]
        threshold = max(median * self.OUTLIER_FACTOR,
                        self.OUTLIER_FLOOR_S)
        for s in statuses:
            ep = s.get("endpoint", "?")
            lat = s.get("probe_s")
            if lat is None:
                continue
            if lat > threshold and ep not in self._outliers:
                self._outliers.add(ep)
                emit_event("replica_skew", reason="latency_outlier",
                           endpoint=ep, probe_s=round(lat, 4),
                           fleet_median_s=round(median, 4))
            elif lat <= threshold and ep in self._outliers:
                self._outliers.discard(ep)
                emit_event("replica_skew", reason="latency_recovered",
                           endpoint=ep, probe_s=round(lat, 4))

    def _check_shards(self, statuses: list[dict]) -> None:
        for s in statuses:
            ep = s.get("endpoint", "?")
            mesh = s.get("mesh") or {}
            degraded = sorted(mesh.get("degraded") or ())
            # distributed MeshDB: a degraded peer HOST (its whole
            # advisory slice on the coordinator's host mask) is the
            # same ladder one level up — fold it into the transition
            # signature so host losses fire exactly once, like shards
            dhosts = sorted(mesh.get("degraded_hosts") or ())
            sig = ",".join(str(d) for d in degraded)
            if dhosts:
                sig += "|hosts:" + ",".join(str(h) for h in dhosts)
            prev = self._degraded.get(ep, "")
            if sig != prev:
                emit_event("shard_degraded", endpoint=ep,
                           shards=degraded, hosts=dhosts,
                           recovered=not sig)
                if sig:
                    self._degraded[ep] = sig
                else:
                    self._degraded.pop(ep, None)
