"""EndpointSet: client-side load balancing, failover, and hedging over
a replica set (docs/fleet.md).

One abstraction composes everything the single-server client already
had (keep-alive pooling, retry with decorrelated jitter, deadline
budgets, gzip negotiation — all unchanged inside ``rpc.client._Conn``)
with the fleet-level policies:

- **Load balancing** — round-robin over the healthy endpoints; health
  comes from each replica's ``/readyz`` (the machine-parseable JSON
  variant), probed by a background thread while the set is in use.
- **Per-replica circuit breakers** — a replica that keeps failing is
  skipped without burning an attempt on it; half-open probes re-admit
  it (``resilience.breaker``).
- **Failover** — a transport-level failure on one replica retries the
  request on the next one (scans and cache writes are idempotent:
  scans are read-only, ``PutBlob``/``PutArtifact`` are last-write-wins
  of identical content).
- **Hedged requests** — a scan left unanswered for ``hedge_s`` is
  dispatched a second time to another replica; the first response wins
  and the loser is discarded. Zero-diff by construction (scans are
  read-only against the same advisory generation), budget-capped so a
  uniformly slow fleet cannot double its own load.

A set of one endpoint (or ``TRIVY_TPU_FLEET=0``) routes through the
exact single-server code path, byte-for-byte.

Fault site ``fleet.endpoint.<index>`` (dynamic family, like ``rpc.*``):
``drop``/``error``/``timeout`` fail that endpoint's dispatch (failover
takes over), ``delay`` slows it (the hedging test bed).
"""

from __future__ import annotations

import concurrent.futures as futures
import json
import random
import threading
import urllib.error
import urllib.request

from trivy_tpu.analysis.witness import make_lock
import time

from trivy_tpu import fleet as fleet_mod
from trivy_tpu.fleet import slo as slo_mod
from trivy_tpu.log import logger
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs import tracing
from trivy_tpu.resilience import faults
from trivy_tpu.resilience.breaker import CircuitBreaker
from trivy_tpu.resilience.retry import (
    DeadlineExceeded,
    RetryPolicy,
    current_deadline,
)
from trivy_tpu.rpc.client import (
    DEFAULT_RETRY,
    RPCBackpressure,
    RPCError,
    RPCUnavailable,
    _Conn,
)
from trivy_tpu.rpc.server import SCAN_PATH

_log = logger("fleet.endpoints")

#: paths safe to hedge: read-only, so a duplicate dispatch cannot
#: change any state (cache writes are NOT hedged — they are idempotent
#: enough for failover, but duplicating them buys nothing)
HEDGE_PATHS = frozenset({SCAN_PATH})


class Endpoint:
    """One replica: its keep-alive transport, breaker, and health."""

    __slots__ = ("url", "conn", "breaker", "index", "healthy", "note",
                 "removed")

    def __init__(self, url: str, conn: _Conn, index: int):
        self.url = url.rstrip("/")
        self.conn = conn
        self.index = index
        self.breaker = CircuitBreaker(
            failure_threshold=3, recovery_s=10.0,
            name=f"fleet.endpoint.{index}")
        self.healthy = True   # assumed until a probe says otherwise
        self.note = ""
        self.removed = False


def readyz_doc(url: str, token: str | None = None,
               timeout: float = 2.0) -> dict | None:
    """One ``/readyz`` probe using the JSON variant (``Accept:
    application/json``). Returns the parsed document (which carries
    ``ready``/``status``/``generation``/...) for both ready (200) and
    not-ready (503) replies, or None when the endpoint is unreachable
    or speaks no JSON."""
    headers = {"Accept": "application/json"}
    if token:
        headers["Trivy-Token"] = token
    req = urllib.request.Request(url.rstrip("/") + "/readyz",
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as exc:
        with exc:
            raw = exc.read()
        try:
            return json.loads(raw)
        except ValueError:
            return None
    except (OSError, ValueError):
        return None


class EndpointSet:
    """N replicas behind one ``post()`` — the smart client.

    For compatibility with code that treats the transport as a single
    connection (tests, the gzip-capability probes), attribute access
    falls through to the FIRST endpoint's ``_Conn``."""

    def __init__(self, urls: list[str] | tuple[str, ...] | str,
                 token: str | None = None,
                 custom_headers: dict | None = None,
                 retry: RetryPolicy | None = None,
                 hedge_s: float | None = None,
                 hedge_budget: float | None = None,
                 health_interval_s: float | None = None):
        if isinstance(urls, str):
            urls = split_urls(urls)
        if not urls:
            raise ValueError("EndpointSet needs at least one URL")
        self._token = token
        self._custom_headers = custom_headers
        self.retry = retry or DEFAULT_RETRY
        self._rng = random.Random(self.retry.seed)
        self._lock = make_lock("fleet.endpoints._lock")
        self._next_index = 0
        self._eps: list[Endpoint] = []
        for u in urls:
            self._eps.append(self._new_endpoint(u))
        self._fleet_on = fleet_mod.enabled()
        self._hedge_s = (fleet_mod.hedge_s() if hedge_s is None
                         else max(hedge_s, 0.0))
        self._hedge_budget = (fleet_mod.hedge_budget()
                              if hedge_budget is None else hedge_budget)
        self._health_interval_s = (fleet_mod.health_interval_s()
                                   if health_interval_s is None
                                   else health_interval_s)
        self._rr = 0
        self._req_n = 0
        self._hedge_n = 0
        self._pool: futures.ThreadPoolExecutor | None = None
        self._prober: threading.Thread | None = None
        self._prober_stop = threading.Event()
        # deliberately unseeded (unlike the retry RNG): probe jitter
        # exists to DEcorrelate replicas, so every instance must differ
        self._probe_rng = random.Random()
        self._skew = slo_mod.SkewDetector()

    # compatibility fall-through: single-connection callers keep
    # reading transport internals (keep-alive socket, gzip capability)
    # off the primary endpoint
    def __getattr__(self, name: str):
        eps = self.__dict__.get("_eps")
        if not eps:
            raise AttributeError(name)
        return getattr(eps[0].conn, name)

    def _new_endpoint(self, url: str) -> Endpoint:
        conn = _Conn(url, self._token, self._custom_headers,
                     retry=self.retry)
        ep = Endpoint(url, conn, self._next_index)
        self._next_index += 1
        return ep

    # ------------------------------------------------------- membership

    @property
    def urls(self) -> list[str]:
        with self._lock:
            return [ep.url for ep in self._eps]

    def set_endpoints(self, urls: list[str] | str) -> None:
        """Reconfigure the replica set. Removed endpoints are RETIRED:
        every keep-alive socket is torn down (busy ones after their
        in-flight round trip) and the retired ``_Conn`` refuses new
        requests, so a stale thread-local cannot resurrect a replica
        that left the set."""
        if isinstance(urls, str):
            urls = split_urls(urls)
        removed: list[Endpoint] = []
        with self._lock:
            keep = {ep.url: ep for ep in self._eps}
            new_eps: list[Endpoint] = []
            for u in urls:
                u = u.rstrip("/")
                ep = keep.pop(u, None)
                new_eps.append(ep if ep is not None
                               else self._new_endpoint(u))
            removed = list(keep.values())
            self._eps = new_eps
        for ep in removed:
            ep.removed = True
            ep.conn.retire()
            obs_metrics.FLEET_ENDPOINT_HEALTH.set(
                0.0, endpoint=str(ep.index))
            _log.info("endpoint retired", url=ep.url)

    def _live(self) -> list[Endpoint]:
        with self._lock:
            return list(self._eps)

    # ----------------------------------------------------------- health

    def probe_health(self) -> None:
        """One synchronous health pass over the set (the background
        prober calls this; tests may too). Each probe is timed into
        ``trivy_tpu_fleet_probe_seconds{endpoint}``; the routable
        verdict (ready AND breaker admits) lands in
        ``trivy_tpu_fleet_replica_healthy{endpoint}``; health flips,
        shard degradations, and cross-replica skew (mixed advisory
        generations, probe-latency outliers) are emitted into the
        fleet event bus on the transition."""
        statuses = []
        for ep in self._live():
            was_healthy = ep.healthy
            t0 = time.monotonic()
            doc = readyz_doc(ep.url, token=self._token)
            probe_s = time.monotonic() - t0
            ep.healthy = bool(doc.get("ready")) if doc else False
            ep.note = (str(doc.get("status", "")) if doc
                       else "unreachable")
            obs_metrics.FLEET_PROBE_SECONDS.observe(
                probe_s, endpoint=str(ep.index))
            obs_metrics.FLEET_ENDPOINT_HEALTH.set(
                1.0 if ep.healthy else 0.0, endpoint=str(ep.index))
            routable = ep.healthy and ep.breaker.state != "open"
            obs_metrics.FLEET_REPLICA_HEALTHY.set(
                1.0 if routable else 0.0, endpoint=str(ep.index))
            if ep.healthy != was_healthy:
                slo_mod.emit_event("probe_health", endpoint=ep.url,
                                   healthy=ep.healthy, status=ep.note)
            statuses.append({
                "endpoint": ep.url,
                "ready": ep.healthy,
                "generation": doc.get("generation") if doc else None,
                "mesh": doc.get("mesh") if doc else None,
                "probe_s": probe_s,
            })
        if slo_mod.events_enabled():
            self._skew.observe(statuses)

    def _ensure_prober(self) -> None:
        if self._health_interval_s <= 0:
            return
        with self._lock:
            if self._prober is not None and self._prober.is_alive():
                return
            self._prober_stop = threading.Event()
            # lint: allow[tracing-capture] background health prober: no ambient scan context to carry
            t = threading.Thread(target=self._probe_loop, daemon=True,
                                 name="ttpu-fleet-health")
            self._prober = t
            # started INSIDE the lock: a concurrent first post must see
            # an alive prober, not replace a stored-but-unstarted one
            t.start()

    def _next_probe_delay(self, prev: float) -> float:
        """Decorrelated jitter over the configured probe interval
        (AWS's classic backoff shape, applied to a steady cadence):
        the next delay is uniform in [interval/2, min(prev*3,
        interval*1.5)], each replica's prober seeded independently.
        The window is centered on the configured interval so the MEAN
        cadence is exactly `_health_interval_s` — jitter spreads the
        probes, it must not silently slow probe cadence (and with it
        unhealthy-streak detection) below what was configured.
        Without it, a controller-driven fleet restart starts every
        replica's prober in the same instant and each pass probes the
        whole fleet simultaneously forever — a synchronized probe
        storm every interval. Jitter decorrelates the passes within a
        few cycles no matter how aligned they start."""
        base = self._health_interval_s
        lo = base / 2.0
        hi = min(max(prev, lo) * 3.0, base * 1.5)
        return lo + self._probe_rng.random() * max(hi - lo, 0.0)

    def _probe_loop(self) -> None:
        stop = self._prober_stop
        delay = self._next_probe_delay(self._health_interval_s)
        while not stop.wait(delay):
            try:
                self.probe_health()
            except Exception as exc:
                _log.warn("health probe pass failed", err=str(exc))
            delay = self._next_probe_delay(delay)

    def set_hedge_budget(self, budget: float) -> None:
        """Retune the hedge budget at runtime (the fleet controller's
        ``hedge_tune`` action). Clamped to [0, 1]; the spent-budget
        accounting carries over so a raise takes effect immediately
        and a cut throttles new hedges without cancelling in-flight
        ones."""
        with self._lock:
            self._hedge_budget = min(max(float(budget), 0.0), 1.0)

    # ---------------------------------------------------------- routing

    def _pick(self, exclude: Endpoint | None = None) -> Endpoint | None:
        """Next endpoint to try: round-robin over healthy replicas
        whose breaker admits a call; unhealthy-but-admitted replicas
        are the fallback (health probes can be stale — correctness
        never depends on them)."""
        eps = self._live()
        if exclude is not None:
            eps = [ep for ep in eps if ep is not exclude]
        if not eps:
            return None
        with self._lock:
            start = self._rr
            self._rr += 1
        ordered = [eps[(start + i) % len(eps)] for i in range(len(eps))]
        for ep in ordered:
            if ep.healthy and ep.breaker.allow():
                return ep
        for ep in ordered:
            if not ep.healthy and ep.breaker.allow():
                return ep
        return None

    # ------------------------------------------------------------- post

    def post(self, path: str, body: bytes, columnar=None,
             json_only: bool = False) -> bytes:
        # ``columnar``/``json_only`` pass through opaquely to each
        # replica's _Conn: capability is learned PER REPLICA, so a
        # mixed-capability fleet (mid-rollout) sends columnar only to
        # the replicas that advertised it (docs/performance.md)
        eps = self._live()
        if len(eps) == 1 or not self._fleet_on:
            # single replica (or the fleet kill switch): the exact
            # single-server client path, including its own retry loop
            return eps[0].conn.post(path, body, columnar=columnar,
                                    json_only=json_only)
        self._ensure_prober()
        with self._lock:
            self._req_n += 1
        deadline = current_deadline()
        delays = self.retry.delays(self._rng)
        last: Exception | None = None
        # at least one full cycle over the set: retry.attempts (3) must
        # not cap a 5-replica request below trying every replica once
        attempts = max(self.retry.attempts, len(eps))
        for attempt in range(attempts):
            if deadline is not None and deadline.expired:
                raise DeadlineExceeded(
                    f"fleet rpc {path}: deadline of "
                    f"{deadline.budget_s:.3f}s exhausted"
                    + (f" (last error: {last})" if last else ""),
                    budget_s=deadline.budget_s)
            ep = self._pick()
            if ep is None:
                raise RPCUnavailable(
                    f"fleet rpc {path}: no endpoint admits a call "
                    f"({self._state_note()}); last error: {last}")
            try:
                if path in HEDGE_PATHS and self._hedge_s > 0:
                    return self._hedged(ep, path, body, deadline,
                                        columnar=columnar,
                                        json_only=json_only)
                # failover retries (attempt >= 1) carry their attempt
                # identity in X-Trivy-Trace (kind "failover": the tree
                # still counts as a scan server-side — it is the
                # scan's only record — but the stitched trace shows
                # which retry produced it)
                return self._dispatch(
                    ep, path, body,
                    attempt=attempt if attempt else None,
                    attempt_kind="failover", columnar=columnar,
                    json_only=json_only)
            except RPCUnavailable as exc:
                last = exc
                obs_metrics.FLEET_FAILOVERS.inc()
                slo_mod.emit_event("failover", endpoint=ep.url,
                                   attempt=attempt, path=path,
                                   error=str(exc)[:200])
                _log.warn("endpoint failed; failing over",
                          url=ep.url, err=str(exc))
            if (attempt + 1) % max(len(eps), 1) == 0 \
                    and attempt < attempts - 1:
                # a full cycle failed: back off before going around
                # again (failing over to a DIFFERENT replica is free)
                delay = next(delays)
                if deadline is not None \
                        and deadline.remaining() <= delay:
                    raise DeadlineExceeded(
                        f"fleet rpc {path}: deadline leaves no room to "
                        f"retry (last error: {last})",
                        budget_s=deadline.budget_s)
                self.retry.sleep(delay)
        raise RPCUnavailable(
            f"fleet rpc {path} failed after {attempts} "
            f"endpoint attempts: {last}")

    def _state_note(self) -> str:
        return ", ".join(
            f"{ep.url}: {'removed' if ep.removed else ep.breaker.state}"
            f"{'' if ep.healthy else ' unhealthy'}"
            for ep in self._live())

    def _dispatch(self, ep: Endpoint, path: str, body: bytes,
                  attempt: int | None = None,
                  attempt_kind: str = "hedge", columnar=None,
                  json_only: bool = False) -> bytes:
        """One attempt on one endpoint, with breaker accounting. Only
        RPCUnavailable counts against the breaker — a deterministic
        4xx reply proves the replica is alive and answering, and so
        does a deliberate shed (RPCBackpressure: 503 + Retry-After).

        ``attempt`` (hedged or failover dispatches) tags the outgoing
        trace header with the dispatch identity so the server-side
        trace tree is attributable to THIS attempt; the plain
        single-dispatch path stays untagged, byte-identical."""
        obs_metrics.FLEET_REQUESTS.inc(endpoint=str(ep.index))
        state_before = ep.breaker.state
        try:
            for rule in faults.fire(f"fleet.endpoint.{ep.index}"):
                if rule.action == "delay":
                    time.sleep(rule.param if rule.param is not None
                               else 0.05)
                elif rule.action == "drop":
                    raise RPCUnavailable(
                        f"injected drop at endpoint {ep.index}")
                elif rule.action == "timeout":
                    raise RPCUnavailable(
                        f"injected timeout at endpoint {ep.index}")
                elif rule.action == "error":
                    raise RPCUnavailable(
                        f"injected HTTP {int(rule.param or 503)} at "
                        f"endpoint {ep.index}")
            if attempt is not None:
                with tracing.attempt_scope(attempt, ep.index,
                                           kind=attempt_kind):
                    out = ep.conn.post_once(path, body,
                                            columnar=columnar,
                                            json_only=json_only)
            else:
                out = ep.conn.post_once(path, body, columnar=columnar,
                                        json_only=json_only)
        except RPCBackpressure:
            # deliberate shed (503 + Retry-After from drain/overload):
            # the replica answered coherently, so this is backpressure,
            # not replica death — fail over without charging the
            # breaker, or an overloaded-but-healthy fleet cascades
            # into open breakers
            ep.breaker.record_success()
            self._breaker_event(ep, state_before)
            raise
        except RPCUnavailable:
            ep.breaker.record_failure()
            self._breaker_event(ep, state_before)
            raise
        except DeadlineExceeded:
            raise  # the caller's budget, not this endpoint's health
        except RPCError:
            ep.breaker.record_success()
            self._breaker_event(ep, state_before)
            raise
        ep.breaker.record_success()
        self._breaker_event(ep, state_before)
        return out

    @staticmethod
    def _breaker_event(ep: Endpoint, state_before: str) -> None:
        state = ep.breaker.state
        if state != state_before:
            slo_mod.emit_event("breaker", endpoint=ep.url,
                               breaker=f"fleet.endpoint.{ep.index}",
                               state=state, previous=state_before)

    # ---------------------------------------------------------- hedging

    def _ensure_pool(self) -> futures.ThreadPoolExecutor:
        with self._lock:
            if self._pool is None:
                self._pool = futures.ThreadPoolExecutor(
                    max_workers=max(4, 2 * len(self._eps)),
                    thread_name_prefix="ttpu-fleet")
            return self._pool

    def _hedge_allowed(self) -> bool:
        with self._lock:
            if self._hedge_n + 1 > self._hedge_budget * self._req_n:
                obs_metrics.FLEET_HEDGES.inc(outcome="denied")
                slo_mod.emit_event("hedge", outcome="denied")
                return False
            self._hedge_n += 1
            return True

    def _hedged(self, ep: Endpoint, path: str, body: bytes,
                deadline, columnar=None,
                json_only: bool = False) -> bytes:
        """Dispatch on ``ep``; if no response lands within the hedge
        delay, dispatch the same request to a second replica and take
        whichever answers first. The loser is not awaited — its worker
        finishes in the background and the response is discarded (its
        breaker bookkeeping still happens).

        Trace hygiene: each raced dispatch runs under its own
        ``fleet.attempt`` span (attempt index + endpoint) and tags its
        outgoing X-Trivy-Trace accordingly, so the server-side trees
        become attributable FRAGMENTS of this one scan instead of
        orphan roots; the instant the race resolves, the losing
        attempt's span is stamped ``cancelled`` (it is still open —
        that is WHY it lost), which is what marks the loser in the
        stitched cross-replica trace (fleet/telemetry.py)."""
        pool = self._ensure_pool()
        ctx = tracing.capture()
        lost: set[int] = set()  # endpoint indexes whose attempt lost

        def submit(target: Endpoint, attempt: int):
            def _go():
                with tracing.adopt(ctx):
                    with tracing.span("fleet.attempt",
                                      attempt=str(attempt),
                                      endpoint=str(target.index)) as s:
                        out = self._dispatch(target, path, body,
                                             attempt=attempt,
                                             columnar=columnar,
                                             json_only=json_only)
                        if s is not None and target.index in lost:
                            s.meta["cancelled"] = "1"
                        return out
            return pool.submit(_go)

        f1 = submit(ep, 0)
        wait_s = self._hedge_s
        if deadline is not None:
            wait_s = min(wait_s, max(deadline.remaining(), 0.001))
        done, _pending = futures.wait({f1}, timeout=wait_s)
        if f1 in done:
            exc = f1.exception()
            if exc is None:
                return f1.result()
            raise exc  # RPCUnavailable -> failover loop; rest propagate
        alt = self._pick(exclude=ep)
        if alt is None or not self._hedge_allowed():
            exc = f1.exception()  # blocks; bounded by the socket timeout
            if exc is None:
                return f1.result()
            raise exc
        # fetch_io attribution lane: waiting on the raced responses
        with tracing.span("fleet.hedge", endpoint=str(alt.index)) as hs:
            f2 = submit(alt, 1)
            by_future = {f1: ep, f2: alt}
            pending = {f1, f2}
            first_err: Exception | None = None
            while pending:
                done, pending = futures.wait(
                    pending, return_when=futures.FIRST_COMPLETED)
                # deterministic preference when both landed in one
                # wake-up: the primary answered, so the hedge "lost"
                for f in (x for x in (f1, f2) if x in done):
                    exc = f.exception()
                    if exc is None:
                        winner = by_future[f]
                        # every non-winning attempt is the loser —
                        # recorded FIRST (best-effort: the loser's
                        # attempt span reads this set when its own
                        # dispatch returns; the stitcher additionally
                        # derives the loser from the winner meta on
                        # this still-open hedge span, which is not
                        # subject to that race)
                        for other in by_future.values():
                            if other is not winner:
                                lost.add(other.index)
                        if hs is not None:
                            hs.meta["winner"] = str(winner.index)
                        outcome = "won" if f is f2 else "lost"
                        obs_metrics.FLEET_HEDGES.inc(outcome=outcome)
                        slo_mod.emit_event(
                            "hedge", outcome=outcome,
                            winner=winner.url,
                            loser=next((o.url for o in by_future.values()
                                        if o is not winner), None))
                        return f.result()
                    if first_err is None:
                        first_err = exc
            raise first_err

    # ---------------------------------------------------------- closing

    def close(self) -> None:
        """Close every idle keep-alive socket (same semantics as the
        single-connection client: the set stays usable, pooled callers
        share it). Stops the health prober and the hedge pool; both
        restart lazily on next use."""
        self._prober_stop.set()
        with self._lock:
            self._prober = None
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        for ep in self._live():
            ep.conn.close()


def split_urls(url: str) -> list[str]:
    """``http://a:1,http://b:2`` -> endpoint list (whitespace ok)."""
    return [u.strip() for u in url.split(",") if u.strip()]
