"""Cross-SERVER layer-analysis dedupe over the redis cache backend
(docs/fleet.md "Shared artifact cache tier").

The in-process ``LayerSingleflight`` (PR 6) makes concurrent scans on
ONE server analyze each unique layer once; its TTL mode gates the RPC
server's MissingBlobs endpoint for concurrent remote clients of that
server. This module extends the same claim protocol across a replica
set: when M servers share one redis cache tier, a layer claim lives in
redis (``SET NX`` with a TTL and the claimant's identity), so a client
of server B parks on a layer a client of server A is analyzing right
now — fleet-wide, each unique layer is analyzed exactly once.

Semantics mirror ``LayerSingleflight`` deliberately:

- first claimer leads; the claim key carries the holder identity (the
  scan's trace id), so a RETRIED request re-leads its own claim
  instead of waiting on itself;
- the claim expires after ``ttl_s`` (leader died mid-analysis): the
  next claimer takes over;
- a follower waits (bounded by the caller's budget) for either the
  blob to land in the shared cache (leader's PutBlob — success) or
  the claim to vanish without a blob (leader failed — the follower
  re-claims and analyzes);
- correctness never depends on the gate: every rung of the failure
  ladder degrades to "this caller analyzes the layer itself".

The fake-redis test server and a real redis both speak the three
commands used here: ``SET key val NX EX n`` / ``GET`` / ``DEL``.
"""

from __future__ import annotations

import os

import time

from trivy_tpu.cache.redis import REDIS_PREFIX, RedisError
from trivy_tpu.log import logger
from trivy_tpu.obs import metrics as obs_metrics

_log = logger("fleet.dedupe")

CLAIM_PREFIX = f"{REDIS_PREFIX}::claim::"
POLL_S = 0.05


class _RemoteSlot:
    """Follower's handle on another server's in-flight layer analysis.
    Duck-types the ``LayerSingleflight`` slot surface the server's
    MissingBlobs gate consumes: ``slot.event.wait(budget)`` plus the
    ``done`` / ``ok`` verdict fields."""

    __slots__ = ("_gate", "_blob", "done", "ok")

    def __init__(self, gate: "RedisLayerGate", blob_id: str):
        self._gate = gate
        self._blob = blob_id
        self.done = False
        self.ok = False

    @property
    def event(self) -> "_RemoteSlot":
        return self

    def wait(self, budget_s: float) -> bool:
        """Poll until the blob lands (ok), the claim vanishes without a
        blob (leader failed — not ok), or the budget runs out."""
        deadline = time.monotonic() + max(budget_s, 0.0)
        while True:
            try:
                if self._gate.blob_present(self._blob):
                    self.done = self.ok = True
                    return True
                if self._gate.claim_holder(self._blob) is None:
                    # claim expired/released with no blob: leader died
                    self.done, self.ok = True, False
                    return True
            except RedisError as exc:
                # a flaky cache tier must not wedge the scan: treat as
                # "leader unknown" and let the caller analyze
                _log.warn("redis claim poll failed; degrading",
                          err=str(exc))
                self.done, self.ok = True, False
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            time.sleep(min(POLL_S, remaining))


class RedisLayerGate:
    """``LayerSingleflight``-shaped claim registry backed by redis, so
    the claim set is shared by every server on the cache tier."""

    def __init__(self, cache, ttl_s: float = 300.0):
        self._cache = cache          # RedisCache (owns the RespClient)
        self.ttl_s = ttl_s
        self._anon = f"srv-{os.getpid()}-{id(self):x}"

    # ------------------------------------------------------- primitives

    def _client(self):
        return self._cache._client

    @staticmethod
    def _key(blob_id: str) -> str:
        return CLAIM_PREFIX + blob_id

    def blob_present(self, blob_id: str) -> bool:
        return bool(self._client().execute(
            "EXISTS", f"{REDIS_PREFIX}::blob::{blob_id}"))

    def claim_holder(self, blob_id: str) -> str | None:
        raw = self._client().execute("GET", self._key(blob_id))
        if raw is None:
            return None
        return raw.decode() if isinstance(raw, bytes) else str(raw)

    # --------------------------------------------------------- protocol

    def claim(self, blob_id: str, src_cache=None,
              holder=None) -> tuple[object, bool]:
        """-> (slot, is_leader); mirrors LayerSingleflight.claim."""
        ident = holder or self._anon
        key = self._key(blob_id)
        try:
            ok = self._client().execute(
                "SET", key, ident, "NX", "EX", str(int(self.ttl_s)))
            if ok is not None:
                obs_metrics.FLEET_DEDUPE_CLAIMS.inc(outcome="leader")
                return _RemoteSlot(self, blob_id), True
            cur = self.claim_holder(blob_id)
            if cur is None:
                # expired between SET and GET: take it over
                self._client().execute(
                    "SET", key, ident, "EX", str(int(self.ttl_s)))
                obs_metrics.FLEET_DEDUPE_CLAIMS.inc(outcome="expired")
                return _RemoteSlot(self, blob_id), True
            if holder is not None and cur == holder:
                # a retried request re-leads its own claim (extend TTL)
                self._client().execute(
                    "SET", key, ident, "EX", str(int(self.ttl_s)))
                obs_metrics.FLEET_DEDUPE_CLAIMS.inc(outcome="leader")
                return _RemoteSlot(self, blob_id), True
        except RedisError as exc:
            # gate down ≠ scan down: caller analyzes (duplicate work,
            # correct results)
            _log.warn("redis claim failed; caller analyzes",
                      blob=blob_id, err=str(exc))
            return _RemoteSlot(self, blob_id), True
        obs_metrics.FLEET_DEDUPE_CLAIMS.inc(outcome="follower")
        return _RemoteSlot(self, blob_id), False

    def reclaim(self, blob_id: str, holder=None) -> None:
        """Take over a claim whose holder is presumed dead (a waiter
        timed out on it): overwrite with a fresh TTL so later callers
        park on this caller's live analysis, not the ghost's."""
        try:
            self._client().execute(
                "SET", self._key(blob_id), holder or self._anon,
                "EX", str(int(self.ttl_s)))
            obs_metrics.FLEET_DEDUPE_CLAIMS.inc(outcome="reclaim")
        except RedisError as exc:
            _log.warn("redis reclaim failed", blob=blob_id,
                      err=str(exc))

    def complete(self, blob_id: str) -> None:
        """A PutBlob landed in the shared cache: release the claim so
        followers (polling the blob key) resolve and later claimers
        lead cheaply."""
        try:
            self._client().execute("DEL", self._key(blob_id))
        except RedisError as exc:
            _log.warn("redis claim release failed (TTL will expire it)",
                      blob=blob_id, err=str(exc))

    def inflight(self) -> int:
        """Fleet-wide count of live claims (diagnostics)."""
        try:
            cursor, n = "0", 0
            while True:
                reply = self._client().execute(
                    "SCAN", cursor, "MATCH", CLAIM_PREFIX + "*",
                    "COUNT", "100")
                cursor = (reply[0].decode()
                          if isinstance(reply[0], bytes)
                          else str(reply[0]))
                n += len(reply[1] or [])
                if cursor == "0":
                    return n
        except RedisError:
            return 0


def maybe_distributed_gate(cache, ttl_s: float = 300.0):
    """A RedisLayerGate when `cache` is the redis backend (the shared
    cache tier of a replica set), else None (the in-process gate
    stays)."""
    from trivy_tpu.cache.redis import RedisCache

    if isinstance(cache, RedisCache):
        return RedisLayerGate(cache, ttl_s=ttl_s)
    return None
