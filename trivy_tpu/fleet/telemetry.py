"""Fleet metrics/attribution federation + cross-replica trace
stitching (docs/fleet.md "Fleet observability control plane",
docs/observability.md "Fleet observability").

Every observability signal PRs 3 and 11 built — the metric registry,
the attribution lanes, the flight recorder — is per-process. This
module composes N replicas' signals into ONE fleet view:

- **Metrics federation** — scrape every replica's ``/metrics`` (the
  OpenMetrics variant, so histogram exemplars survive) and merge into
  one exposition: every per-replica series is re-emitted with a
  ``replica`` label, and counter/histogram families additionally get
  an aggregate series (no ``replica`` label) whose value is the SUM of
  the per-replica scrapes — counters summed, histogram buckets merged
  bound-for-bound. Gauges are never summed (two breakers in state 1 do
  not make a state-2 breaker). The single-server exposition itself is
  untouched byte-for-byte: federation happens in the scraper.
- **Attribution federation** — merge every replica's
  ``/debug/profile`` lane totals into a fleet-wide roofline verdict
  ("bound by <lane>") with per-replica sub-reports.
- **Trace stitching** — pull every replica's flight recorder
  (``/debug/flight``) and join the fragments of hedged/failed-over
  requests — tagged with their attempt identity by the smart client
  (obs.tracing.attempt_scope) — into ONE Chrome trace: one process row
  per replica, the losing attempt marked ``cancelled``, and no orphan
  roots (fragments whose client-side parent is absent get a
  synthesized ``fleet.stitch`` container instead of dangling).
- **FederationServer** — the token-gated control-plane endpoint
  (``trivy-tpu fleet serve``): ``/metrics`` (federated exposition),
  ``/profile`` (fleet attribution + SLO state), ``/flight`` (stitched
  trace), ``/events`` (the ops event ring/journal tail).
- **FleetMonitor** — the control-plane loop: health-probes the fleet
  (skew detection via fleet.slo.SkewDetector), folds federated
  availability deltas into the SLO engine, and evaluates burn-rate
  alerts each tick.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

from trivy_tpu.analysis.witness import make_lock
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from trivy_tpu.fleet import slo as slo_mod
from trivy_tpu.log import logger
from trivy_tpu.obs.metrics import _fmt

_log = logger("fleet.telemetry")

OPENMETRICS_ACCEPT = "application/openmetrics-text"


class FederationError(Exception):
    """A replica scrape failed or an exposition did not parse."""


# ------------------------------------------------------------- scraping


def _get(url: str, token: str | None = None, accept: str | None = None,
         timeout: float = 10.0) -> bytes:
    headers = {}
    if token:
        headers["Trivy-Token"] = token
    if accept:
        headers["Accept"] = accept
    req = urllib.request.Request(url, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.read()
    except urllib.error.HTTPError as exc:
        with exc:
            detail = exc.read().decode("utf-8", "replace")[:200]
        raise FederationError(f"{url} -> HTTP {exc.code}: {detail}")
    except (OSError, ValueError) as exc:
        raise FederationError(f"{url} unreachable: {exc}")


def scrape_metrics(url: str, token: str | None = None,
                   timeout: float = 10.0) -> str:
    """One replica's ``/metrics`` in the OpenMetrics flavor (exemplars
    preserved); the replica's default 0.0.4 bytes are never involved."""
    return _get(url.rstrip("/") + "/metrics", token=token,
                accept=OPENMETRICS_ACCEPT, timeout=timeout).decode()


def fetch_profile(url: str, token: str | None = None,
                  timeout: float = 10.0) -> dict:
    return json.loads(_get(url.rstrip("/") + "/debug/profile",
                           token=token, timeout=timeout))


def fetch_flight(url: str, token: str | None = None,
                 timeout: float = 10.0) -> dict:
    return json.loads(_get(url.rstrip("/") + "/debug/flight",
                           token=token, timeout=timeout))


def fetch_usage(url: str, token: str | None = None,
                timeout: float = 10.0) -> dict:
    return json.loads(_get(url.rstrip("/") + "/debug/usage",
                           token=token, timeout=timeout))


# -------------------------------------------------------------- parsing


@dataclass
class Sample:
    name: str                      # full sample name (incl. _bucket…)
    labels: tuple                  # ((k, v), ...) sorted
    value: float
    exemplar: str = ""             # raw OpenMetrics exemplar suffix


@dataclass
class Family:
    name: str                      # family (metadata) name
    kind: str = "untyped"
    help: str = ""
    samples: list = field(default_factory=list)


def _parse_labels(text: str) -> tuple:
    """``a="x",b="y"`` -> ((a, x), (b, y)) sorted; handles escapes."""
    out = []
    i = 0
    n = len(text)
    while i < n:
        eq = text.index("=", i)
        key = text[i:eq].strip().lstrip(",").strip()
        i = eq + 1
        if text[i] != '"':
            raise FederationError(f"bad label value near {text[i:]!r}")
        i += 1
        buf = []
        while i < n:
            c = text[i]
            if c == "\\" and i + 1 < n:
                nxt = text[i + 1]
                buf.append({"n": "\n", "\\": "\\", '"': '"'}
                           .get(nxt, "\\" + nxt))
                i += 2
                continue
            if c == '"':
                i += 1
                break
            buf.append(c)
            i += 1
        out.append((key, "".join(buf)))
        while i < n and text[i] in ", ":
            i += 1
    return tuple(sorted(out))


def parse_exposition(text: str) -> list:
    """Prometheus 0.0.4 / OpenMetrics text -> ordered ``Family`` list.
    Exemplar suffixes (``# {...} v ts``) ride along verbatim on their
    sample so federation re-emits them untouched."""
    families: list[Family] = []
    by_name: dict[str, Family] = {}

    def family(name: str) -> Family:
        fam = by_name.get(name)
        if fam is None:
            fam = by_name[name] = Family(name)
            families.append(fam)
        return fam

    current: Family | None = None
    for line in text.splitlines():
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                fam = family(parts[2])
                if parts[1] == "TYPE":
                    fam.kind = parts[3] if len(parts) > 3 else "untyped"
                    current = fam
                else:
                    fam.help = parts[3] if len(parts) > 3 else ""
                    current = fam
            continue  # comments, # EOF, # UNIT
        exemplar = ""
        body = line
        if " # " in line:  # OpenMetrics exemplar suffix
            body, _sep, ex = line.partition(" # ")
            exemplar = "# " + ex
        if "{" in body:
            name = body[:body.index("{")]
            rest = body[body.index("{") + 1:]
            close = rest.rindex("}")
            labels = _parse_labels(rest[:close]) if rest[:close] else ()
            value_text = rest[close + 1:].strip()
        else:
            name, _sep, value_text = body.partition(" ")
            labels = ()
        value_text = value_text.split()[0] if value_text else "0"
        try:
            value = float(value_text)
        except ValueError:
            raise FederationError(f"bad sample line {line!r}")
        # samples attach to the family whose metadata most recently
        # opened (histogram _bucket/_sum/_count share one family);
        # a bare sample with no metadata opens its own
        fam = current
        if fam is None or not name.startswith(fam.name):
            fam = by_name.get(name) or family(name)
        fam.samples.append(Sample(name, labels, value, exemplar))
    return families


# ----------------------------------------------------------- federation

#: family kinds whose samples are monotone counts — safe (and
#: meaningful) to sum across replicas. "unknown" covers the legacy
#: ``*_seconds_sum`` counters the OpenMetrics renderer cannot name as
#: counter families; their summable suffix is checked per sample.
_SUMMABLE_KINDS = {"counter", "histogram"}
_SUMMABLE_SUFFIXES = ("_total", "_sum", "_count", "_bucket")


def _summable(fam: Family, sample: Sample) -> bool:
    if fam.kind in _SUMMABLE_KINDS:
        return True
    return fam.kind == "unknown" and sample.name.endswith(
        _SUMMABLE_SUFFIXES)


class Federation:
    """The merged fleet exposition + programmatic totals."""

    def __init__(self, replicas: list):
        self.replicas = list(replicas)          # replica labels, ordered
        self.families: list[Family] = []        # union, first-seen order
        self._by_name: dict[str, Family] = {}
        # (sample_name, labels) -> summed value across replicas
        self.totals: dict[tuple, float] = {}
        # (sample_name, labels) -> [(replica, Sample), ...]
        self._per_replica: dict[tuple, list] = {}

    def _family(self, src: Family) -> Family:
        fam = self._by_name.get(src.name)
        if fam is None:
            fam = self._by_name[src.name] = Family(
                src.name, src.kind, src.help)
            self.families.append(fam)
        return fam

    def add(self, replica: str, families: list) -> None:
        for src in families:
            fam = self._family(src)
            for s in src.samples:
                key = (s.name, s.labels)
                self._per_replica.setdefault(key, []).append((replica, s))
                if _summable(src, s):
                    self.totals[key] = self.totals.get(key, 0.0) + s.value

    def total(self, sample_name: str, **labels) -> float:
        key = (sample_name,
               tuple(sorted((k, str(v)) for k, v in labels.items())))
        return self.totals.get(key, 0.0)

    @staticmethod
    def _labels_text(labels: tuple) -> str:
        if not labels:
            return ""
        from trivy_tpu.obs.metrics import _escape

        return ("{" + ",".join(f'{k}="{_escape(v)}"'
                               for k, v in labels) + "}")

    def render(self, eof: bool = True) -> bytes:
        """The federated exposition: per family, the aggregate (summed)
        series first — no ``replica`` label — then every per-replica
        series with ``replica`` appended (exemplars preserved)."""
        out: list[str] = []
        for fam in self.families:
            out.append(f"# HELP {fam.name} {fam.help}".rstrip())
            out.append(f"# TYPE {fam.name} {fam.kind}")
            seen: set = set()
            per_replica_lines: list[str] = []
            for src_fam, key, entries in self._family_entries(fam):
                if key in seen:
                    continue
                seen.add(key)
                name, labels = key
                if key in self.totals:
                    out.append(
                        f"{name}{self._labels_text(labels)} "
                        f"{_fmt(self.totals[key])}")
                for replica, s in entries:
                    ltext = self._labels_text(
                        labels + (("replica", replica),))
                    suffix = f" {s.exemplar}" if s.exemplar else ""
                    per_replica_lines.append(
                        f"{name}{ltext} {_fmt(s.value)}{suffix}")
            out.extend(per_replica_lines)
        text = "\n".join(out) + "\n"
        if eof:
            text += "# EOF\n"
        return text.encode()

    def _family_entries(self, fam: Family):
        """Stable iteration of this family's (key, entries) in the
        order samples were first seen across the scrapes."""
        emitted: set = set()
        for key, entries in self._per_replica.items():
            name = key[0]
            if not self._belongs(fam, name) or key in emitted:
                continue
            emitted.add(key)
            yield fam, key, entries

    def _belongs(self, fam: Family, sample_name: str) -> bool:
        if sample_name == fam.name:
            return True
        if not sample_name.startswith(fam.name):
            return False
        rest = sample_name[len(fam.name):]
        # histogram/summary component or the OM counter `_total` suffix
        return rest in ("_bucket", "_sum", "_count", "_total")


def federate(scrapes: list) -> Federation:
    """``[(replica_label, exposition_text), ...]`` -> Federation."""
    fed = Federation([label for label, _ in scrapes])
    for label, text in scrapes:
        fed.add(label, parse_exposition(text))
    return fed


def federate_endpoints(endpoints: list, token: str | None = None,
                       timeout: float = 10.0) -> Federation:
    """Scrape + merge every replica's /metrics. A replica that fails
    to scrape is reported inside the exposition (its series are
    simply absent) rather than failing the whole federation — the
    operator is usually asking BECAUSE a replica is sick."""
    scrapes = []
    errors = {}
    for i, ep in enumerate(endpoints):
        try:
            scrapes.append((str(i), scrape_metrics(ep, token=token,
                                                   timeout=timeout)))
        except FederationError as exc:
            errors[str(i)] = str(exc)
            _log.warn("metrics scrape failed", endpoint=ep, err=str(exc))
    fed = federate(scrapes)
    fed.errors = errors  # type: ignore[attr-defined]
    return fed


# -------------------------------------------------- profile federation


def federate_profiles(profiles: list) -> dict:
    """``[(replica_label, /debug/profile doc), ...]`` -> the fleet
    attribution document: lane totals summed, one roofline verdict,
    per-replica sub-docs."""
    from trivy_tpu.obs.attrib import LANES

    busy = dict.fromkeys(LANES, 0.0)
    crit = dict.fromkeys(LANES, 0.0)
    wall = other = 0.0
    scans = roots = 0
    replicas = {}
    for label, doc in profiles:
        replicas[label] = doc
        wall += doc.get("wall_s", 0.0)
        other += doc.get("other_s", 0.0)
        scans += doc.get("scans", 0)
        roots += doc.get("roots", 0)
        for lane, row in (doc.get("lanes") or {}).items():
            if lane in busy:
                busy[lane] += row.get("busy_s", 0.0)
                crit[lane] += row.get("crit_s", 0.0)
    if roots == 0:
        verdict = "no traces observed"
    else:
        lane = max(crit, key=crit.get)
        if other >= crit[lane]:
            share = other / wall if wall else 0.0
            verdict = (f"bound by untracked time ({share:.0%} of wall "
                       "outside classified spans)")
        else:
            share = crit[lane] / wall if wall else 0.0
            verdict = (f"bound by {lane} ({share:.0%} of the critical "
                       "path)")
    return {
        "replicas": replicas,
        "fleet": {
            "scans": scans,
            "roots": roots,
            "wall_s": round(wall, 6),
            "other_s": round(other, 6),
            "lanes": {lane: {"busy_s": round(busy[lane], 6),
                             "crit_s": round(crit[lane], 6),
                             "crit_share": round(crit[lane] / wall, 4)
                             if wall else 0.0}
                      for lane in LANES},
            "verdict": verdict,
        },
    }


# ---------------------------------------------------- usage federation


def federate_usage(usages: list) -> dict:
    """``[(replica_label, /debug/usage doc), ...]`` -> the fleet usage
    document: per-tenant cost vectors summed across replicas (tenant
    hashes are replica-independent — the same token hashes identically
    everywhere, so cross-replica summing is exact), per-replica
    sub-docs, fleet totals, and a conservation roll-up that is the SUM
    of the replica-local comparisons (each replica checks its own
    tenant-lane-seconds against its own attribution spine; the fleet
    view just reports whether every replica held)."""
    tenants: dict[str, dict] = {}
    totals: dict = {"fields": {}, "lanes": {}}
    replicas = {}
    tenant_lane_s = attrib_lane_s = 0.0
    ok = True
    for label, doc in usages:
        replicas[label] = doc
        for tenant, rec in (doc.get("tenants") or {}).items():
            slot = tenants.setdefault(tenant, {"fields": {}, "lanes": {}})
            for k, v in (rec.get("fields") or {}).items():
                slot["fields"][k] = slot["fields"].get(k, 0.0) + v
                totals["fields"][k] = totals["fields"].get(k, 0.0) + v
            for k, v in (rec.get("lanes") or {}).items():
                slot["lanes"][k] = slot["lanes"].get(k, 0.0) + v
                totals["lanes"][k] = totals["lanes"].get(k, 0.0) + v
        cons = doc.get("conservation") or {}
        tenant_lane_s += cons.get("tenant_lane_s", 0.0)
        attrib_lane_s += cons.get("attrib_lane_s", 0.0)
        if cons and not cons.get("ok", True):
            ok = False
    return {
        "replicas": replicas,
        "fleet": {
            "tenants": tenants,
            "totals": totals,
            "conservation": {
                "tenant_lane_s": round(tenant_lane_s, 6),
                "attrib_lane_s": round(attrib_lane_s, 6),
                "ok": ok,
            },
        },
    }


def federate_usage_endpoints(endpoints: list, token: str | None = None,
                             timeout: float = 10.0) -> dict:
    """Fetch + merge every replica's /debug/usage; unreachable replicas
    are reported in ``errors`` instead of failing the federation."""
    usages = []
    errors = {}
    for ep in endpoints:
        ep = ep.rstrip("/")
        try:
            usages.append((ep, fetch_usage(ep, token=token,
                                           timeout=timeout)))
        except FederationError as exc:
            errors[ep] = str(exc)
            _log.warn("usage fetch failed", endpoint=ep, err=str(exc))
    doc = federate_usage(usages)
    doc["errors"] = errors
    return doc


# ------------------------------------------------------ trace stitching


def stitch_flight(docs: list, trace_id: str | None = None) -> dict:
    """``[(replica_label, /debug/flight chrome doc), ...]`` -> ONE
    Chrome trace document:

    - one process row per replica (``pid`` = replica ordinal, named via
      ``process_name`` metadata events), events deduplicated by span id
      (loopback test rigs share one recorder across replicas);
    - hedge/failover fragments — ``server.scan`` roots tagged with
      their attempt identity — joined to the client trace they belong
      to; the LOSING attempt's whole subtree is marked
      ``args.cancelled`` (the client stamps ``cancelled`` on its
      ``fleet.attempt`` span the moment the race resolves);
    - zero orphan roots: any trace whose fragments' client-side parent
      is not in the document gets a synthesized ``fleet.stitch``
      container spanning them, so nothing dangles;
    - optional ``trace_id`` filter: only that trace's events.
    """
    events: list[dict] = []
    seen_spans: set = set()
    replica_of: dict[str, int] = {}
    for ordinal, (label, doc) in enumerate(docs):
        replica_of[label] = ordinal
        for ev in doc.get("traceEvents", ()):
            args = ev.get("args") or {}
            span_id = args.get("span_id")
            if trace_id and args.get("trace_id") != trace_id:
                continue
            if span_id:
                if span_id in seen_spans:
                    continue
                seen_spans.add(span_id)
            ev = dict(ev, pid=ordinal, args=dict(args))
            events.append(ev)

    by_span = {e["args"]["span_id"]: e for e in events
               if e["args"].get("span_id")}
    children: dict[str, list] = {}
    for e in events:
        parent = e["args"].get("parent_id")
        if parent:
            children.setdefault(parent, []).append(e)

    # which attempt lost each race: the client's fleet.attempt spans
    # carry a best-effort `cancelled` stamp, and the fleet.hedge span
    # records the `winner` endpoint the instant the race resolves —
    # every same-trace hedged attempt on any OTHER endpoint is the
    # loser (this second source is immune to the loser's span closing
    # before the stamp lands)
    cancelled: set = set()
    hedged_eps: dict = {}  # trace_id -> {endpoint, ...} of attempts
    for e in events:
        args = e["args"]
        if e.get("name") == "server.scan" and args.get("attempt") \
                is not None and args.get("endpoint") is not None:
            hedged_eps.setdefault(args.get("trace_id"), set()).add(
                str(args["endpoint"]))
    for e in events:
        args = e["args"]
        if args.get("cancelled") and args.get("endpoint") is not None:
            cancelled.add((args.get("trace_id"), str(args["endpoint"])))
        if e.get("name") == "fleet.hedge" and args.get("winner") \
                is not None:
            tid = args.get("trace_id")
            for ep in hedged_eps.get(tid, ()):
                if ep != str(args["winner"]):
                    cancelled.add((tid, ep))

    def mark(ev: dict) -> int:
        ev["args"]["cancelled"] = "1"
        n = 1
        for child in children.get(ev["args"].get("span_id", ""), ()):
            n += mark(child)
        return n

    cancelled_events = 0
    fragments = 0
    for e in events:
        args = e["args"]
        if args.get("attempt") is None or e.get("name") != "server.scan":
            continue
        fragments += 1
        if (args.get("trace_id"), str(args.get("endpoint"))) in cancelled:
            cancelled_events += mark(e)

    # orphan adoption: group trace fragments whose parent span is not
    # in the doc; when the trace has no true local root either, a
    # synthesized container spans them so the stitched file never
    # shows a dangling root. orphan_roots counts what remains AFTER
    # adoption and synthesis — dangling events the stitcher could not
    # bind to anything (no trace id to group by) — so the zero-orphan
    # exit gates measure the stitcher's actual coverage
    traces: dict[str, list] = {}
    ungrouped: list = []
    for e in events:
        tid = e["args"].get("trace_id")
        if tid:
            traces.setdefault(tid, []).append(e)
        elif e["args"].get("parent_id") \
                and e["args"]["parent_id"] not in by_span:
            ungrouped.append(e)
    synthesized = []
    orphan_roots = len(ungrouped)
    for tid, group in traces.items():
        unresolved = [e for e in group
                      if e["args"].get("parent_id")
                      and e["args"]["parent_id"] not in by_span]
        has_root = any(not e["args"].get("parent_id") for e in group)
        if unresolved and not has_root:
            # pure remote fragments (client trace not in any pulled
            # recorder): bind them under one synthesized container so
            # the stitched file never shows a dangling root
            t0 = min(e["ts"] for e in group)
            t1 = max(e["ts"] + e.get("dur", 0) for e in group)
            synthesized.append({
                "name": "fleet.stitch",
                "ph": "X", "ts": t0, "dur": max(t1 - t0, 0),
                "pid": unresolved[0]["pid"], "tid": 0,
                "cat": "trivy_tpu",
                "args": {"trace_id": tid, "synthesized": "1",
                         "fragments": len(unresolved)},
            })
        # unresolved-with-root fragments are ADOPTED: the trace's own
        # (client) root anchors the view

    meta_events = [
        {"ph": "M", "name": "process_name", "pid": ordinal, "tid": 0,
         "args": {"name": f"replica {ordinal} ({label})"}}
        for label, ordinal in sorted(replica_of.items(),
                                     key=lambda kv: kv[1])
    ]
    return {
        "traceEvents": meta_events + events + synthesized,
        "displayTimeUnit": "ms",
        "stitch": {
            "replicas": len(docs),
            "traces": len(traces),
            "fragments": fragments,
            "cancelled_spans": cancelled_events,
            "synthesized_roots": len(synthesized),
            "orphan_roots": orphan_roots,
        },
    }


def stitch_endpoints(endpoints: list, token: str | None = None,
                     trace_id: str | None = None) -> dict:
    docs = []
    for ep in endpoints:
        try:
            docs.append((ep.rstrip("/"), fetch_flight(ep, token=token)))
        except FederationError as exc:
            _log.warn("flight fetch failed", endpoint=ep, err=str(exc))
    return stitch_flight(docs, trace_id=trace_id)


def probe_quantiles(latencies: list) -> dict:
    """p50/p99 over a list of probe latencies (seconds) — the skew
    signal the fleet controller tunes the hedge budget from.  Returns
    an empty dict when there are fewer than 3 samples (a quantile over
    1-2 probes is noise, not signal)."""
    lats = sorted(float(x) for x in latencies if x is not None)
    if len(lats) < 3:
        return {}
    p50 = lats[len(lats) // 2]
    p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))]
    return {"p50_s": p50, "p99_s": p99,
            "skew": p99 / max(p50, 1e-9)}


# -------------------------------------------------------- fleet monitor


class FleetMonitor:
    """The control-plane observation loop (one instance per
    ``trivy-tpu fleet serve`` / test): each ``tick`` health-probes the
    fleet, feeds the skew detector, folds the federated scan counters'
    deltas into the SLO engine as availability samples, and evaluates
    the burn-rate alerts."""

    def __init__(self, endpoints: list, token: str | None = None,
                 engine: "slo_mod.SLOEngine | None" = None):
        self.endpoints = [e.rstrip("/") for e in endpoints]
        self.token = token
        self.engine = engine or slo_mod.SLOEngine()
        self.skew = slo_mod.SkewDetector()
        self._last_counters: dict[str, tuple[float, float]] = {}
        self._health: dict[str, bool] = {}

    def _probe(self) -> list[dict]:
        from trivy_tpu.fleet.endpoints import readyz_doc

        statuses = []
        for ep in self.endpoints:
            t0 = time.monotonic()
            doc = readyz_doc(ep, token=self.token)
            lat = time.monotonic() - t0
            ready = bool(doc.get("ready")) if doc else False
            statuses.append({
                "endpoint": ep,
                "ready": ready,
                "generation": doc.get("generation") if doc else None,
                "mesh": doc.get("mesh") if doc else None,
                "probe_s": lat,
            })
            # health flips land in the journal (a replica outage is
            # the first thing an incident replay must show)
            if self._health.get(ep) != ready:
                if ep in self._health or not ready:
                    slo_mod.emit_event(
                        "probe_health", endpoint=ep, healthy=ready,
                        status=str((doc or {}).get(
                            "status", "unreachable")))
                self._health[ep] = ready
            # the probe itself is an availability sample: an
            # unreachable/unready replica burns budget even when no
            # client happens to be scanning
            self.engine.record(ready, latency_s=lat)
        return statuses

    def _record_scan_deltas(self) -> None:
        for i, ep in enumerate(self.endpoints):
            try:
                fams = parse_exposition(
                    scrape_metrics(ep, token=self.token))
            except FederationError:
                continue  # unreachability already sampled by the probe
            scans = errors = 0.0
            for fam in fams:
                for s in fam.samples:
                    if s.name == "trivy_tpu_scans_total":
                        scans += s.value
                    elif s.name == "trivy_tpu_scan_errors_total":
                        errors += s.value
            prev = self._last_counters.get(ep)
            self._last_counters[ep] = (scans, errors)
            if prev is None:
                continue
            d_scans = max(scans - prev[0], 0.0)
            d_errors = max(errors - prev[1], 0.0)
            self.engine.record_counts(int(d_scans - d_errors),
                                      int(d_errors))

    def tick(self, now: float | None = None) -> dict:
        statuses = self._probe()
        self.skew.observe(statuses)
        self._record_scan_deltas()
        state = self.engine.evaluate(now=now)
        return {"statuses": statuses, "slo": state}


# ----------------------------------------------------- federation server


def _make_fed_handler(server: "FederationServer"):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            _log.debug("http " + (fmt % args))

        def _reply(self, code: int, body: bytes,
                   ctype: str = "application/json"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _authed(self) -> bool:
            if not server.token:
                return True
            return self.headers.get("Trivy-Token") == server.token

        def do_GET(self):
            if self.path == "/healthz":
                self._reply(200, b"ok", "text/plain")
                return
            if not self._authed():
                self._reply(401, json.dumps(
                    {"error": "invalid token"}).encode())
                return
            try:
                if self.path.startswith("/metrics"):
                    fed = federate_endpoints(server.endpoints,
                                             token=server.upstream_token)
                    self._reply(200, fed.render(),
                                f"{OPENMETRICS_ACCEPT}; version=1.0.0; "
                                "charset=utf-8")
                elif self.path.startswith("/profile"):
                    profiles = []
                    for ep in server.endpoints:
                        try:
                            profiles.append((ep, fetch_profile(
                                ep, token=server.upstream_token)))
                        except FederationError:
                            pass
                    doc = federate_profiles(profiles)
                    if server.monitor is not None:
                        doc["slo"] = server.monitor.engine.evaluate()
                    self._reply(200, json.dumps(doc).encode())
                elif self.path.startswith("/usage"):
                    self._reply(200, json.dumps(federate_usage_endpoints(
                        server.endpoints,
                        token=server.upstream_token)).encode())
                elif self.path.startswith("/flight"):
                    self._reply(200, json.dumps(stitch_endpoints(
                        server.endpoints,
                        token=server.upstream_token)).encode())
                elif self.path.startswith("/events"):
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    try:
                        since = int((q.get("since") or ["0"])[0])
                    except ValueError:
                        self._reply(400, json.dumps(
                            {"error": "bad since cursor"}).encode())
                        return
                    nxt, events = slo_mod.events_since(since)
                    self._reply(200, json.dumps(
                        {"next": nxt, "events": events}).encode())
                else:
                    self._reply(404, json.dumps(
                        {"error": "not found"}).encode())
            except Exception as exc:  # surface, never kill the server
                _log.warn("federation request failed", path=self.path,
                          err=str(exc))
                self._reply(500, json.dumps({"error": str(exc)}).encode())

    return Handler


class FederationServer:
    """The fleet observability control plane's serving surface: a
    token-gated endpoint federating N replicas on demand. ``token``
    gates INCOMING requests; ``upstream_token`` authenticates the
    scrapes against the replicas (defaults to the same token)."""

    def __init__(self, endpoints: list, host: str = "localhost",
                 port: int = 0, token: str | None = None,
                 upstream_token: str | None = None,
                 monitor: FleetMonitor | None = None,
                 monitor_interval_s: float = 5.0):
        self.endpoints = [e.rstrip("/") for e in endpoints]
        self.token = token
        self.upstream_token = (token if upstream_token is None
                               else upstream_token)
        self.monitor = monitor
        self.monitor_interval_s = monitor_interval_s
        self.httpd = ThreadingHTTPServer((host, port),
                                         _make_fed_handler(self))
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._monitor_lock = make_lock(
            "fleet.telemetry.FederationServer._monitor_lock")

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> None:
        # lint: allow[tracing-capture] control-plane accept loop: no ambient scan context exists here
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        if self.monitor is not None and self.monitor_interval_s > 0:
            # lint: allow[tracing-capture] background monitor loop owns its own context; nothing to propagate
            w = threading.Thread(target=self._monitor_loop, daemon=True)
            w.start()
            self._threads.append(w)
        _log.info("federation endpoint listening", addr=self.address,
                  replicas=len(self.endpoints))

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.monitor_interval_s):
            try:
                with self._monitor_lock:
                    self.monitor.tick()
            except Exception as exc:
                _log.warn("fleet monitor tick failed", err=str(exc))

    def shutdown(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()
