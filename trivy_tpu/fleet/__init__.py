"""Fleet serving tier (docs/fleet.md): replicated scan servers behind a
smart client.

Three pieces compose the single-server subsystems into a deployment:

- :mod:`trivy_tpu.fleet.endpoints` — ``EndpointSet``, the one place
  where retry, failover, hedging, and client-side load balancing
  compose over N replica URLs (health from ``/readyz``, per-replica
  circuit breakers, budget-capped hedged requests for tail latency);
- :mod:`trivy_tpu.fleet.dedupe` — a distributed layer-analysis claim
  over the redis cache backend, so M replicas sharing one cache tier
  analyze each unique layer once fleet-wide (the cross-server story
  for the in-process ``LayerSingleflight``);
- :mod:`trivy_tpu.fleet.rollout` — the coordinated advisory-DB rollout
  controller: canary replica first, a zero-diff probe set, then roll
  the rest, automatic rollback on a ``/readyz`` regression or a probe
  diff, and the PR-9 delta re-score triggered exactly once fleet-wide;
- :mod:`trivy_tpu.fleet.telemetry` — the observability control plane:
  metrics + attribution federation over every replica's ``/metrics``
  and ``/debug/profile`` (counters summed, histogram buckets merged,
  ``replica`` label, exemplars preserved), cross-replica trace
  stitching of hedge/failover fragments into one Chrome trace, the
  token-gated federation endpoint, and the fleet monitor loop;
- :mod:`trivy_tpu.fleet.slo` — the fleet ops event bus (closed EVENTS
  vocabulary, durable fsynced journal with torn-tail-tolerant replay),
  the multi-window burn-rate SLO engine, and the replica-skew
  detector.

``TRIVY_TPU_FLEET=0`` is the kill switch: multi-URL clients pin to the
first endpoint through the exact single-server code path, and servers
keep the in-process layer gate even on a redis cache.
``TRIVY_TPU_FLEET_EVENTS=0`` kills the ops event bus alone.
"""

from __future__ import annotations

import os

from trivy_tpu.log import logger

_log = logger("fleet")

DEFAULT_HEDGE_MS = 75.0
DEFAULT_HEDGE_BUDGET = 0.1
DEFAULT_HEALTH_INTERVAL_S = 5.0


def enabled() -> bool:
    """The ``TRIVY_TPU_FLEET`` kill switch (default on)."""
    return os.environ.get("TRIVY_TPU_FLEET", "1") != "0"


def _parse_float(raw: str, name: str, default: float) -> float:
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        _log.warn(f"malformed {name}; using default", value=raw)
        return default


def hedge_s() -> float:
    """Hedge trigger delay in seconds (``TRIVY_TPU_FLEET_HEDGE_MS``):
    how long a scan request may sit unanswered on its primary replica
    before the same request is dispatched to a second one. 0 disables
    hedging."""
    raw = os.environ.get("TRIVY_TPU_FLEET_HEDGE_MS", "")
    return max(_parse_float(raw, "TRIVY_TPU_FLEET_HEDGE_MS",
                            DEFAULT_HEDGE_MS), 0.0) / 1000.0


def hedge_budget() -> float:
    """Max fraction of requests allowed to hedge
    (``TRIVY_TPU_FLEET_HEDGE_BUDGET``): bounds the duplicate-work cost
    so a globally slow fleet cannot double its own load."""
    raw = os.environ.get("TRIVY_TPU_FLEET_HEDGE_BUDGET", "")
    return min(max(_parse_float(raw, "TRIVY_TPU_FLEET_HEDGE_BUDGET",
                                DEFAULT_HEDGE_BUDGET), 0.0), 1.0)


def health_interval_s() -> float:
    """Period of the background ``/readyz`` health prober
    (``TRIVY_TPU_FLEET_HEALTH_INTERVAL_S``)."""
    raw = os.environ.get("TRIVY_TPU_FLEET_HEALTH_INTERVAL_S", "")
    return max(_parse_float(raw, "TRIVY_TPU_FLEET_HEALTH_INTERVAL_S",
                            DEFAULT_HEALTH_INTERVAL_S), 0.1)
