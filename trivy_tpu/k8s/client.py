"""Kubernetes API-server client (reference trivy-kubernetes uses
client-go; this is a stdlib equivalent speaking the REST API directly,
so cluster scans need no kubectl binary).

Auth comes from kubeconfig ($KUBECONFIG or ~/.kube/config): bearer
tokens, client certificate/key data (inline base64 or file paths), CA
bundles, and insecure-skip-tls-verify. In-cluster service-account
credentials (/var/run/secrets/kubernetes.io/serviceaccount) are used
when no kubeconfig exists — the same resolution order as client-go.
"""

from __future__ import annotations

import base64
import json
import os
import ssl
import tempfile
import urllib.error
import urllib.request

from trivy_tpu.log import logger

_log = logger("k8s")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# kind -> (api prefix, plural)
API_PATHS: dict[str, tuple[str, str]] = {
    "Pod": ("/api/v1", "pods"),
    "ReplicationController": ("/api/v1", "replicationcontrollers"),
    "Node": ("/api/v1", "nodes"),
    "Service": ("/api/v1", "services"),
    "ConfigMap": ("/api/v1", "configmaps"),
    "Deployment": ("/apis/apps/v1", "deployments"),
    "StatefulSet": ("/apis/apps/v1", "statefulsets"),
    "DaemonSet": ("/apis/apps/v1", "daemonsets"),
    "ReplicaSet": ("/apis/apps/v1", "replicasets"),
    "Job": ("/apis/batch/v1", "jobs"),
    "CronJob": ("/apis/batch/v1", "cronjobs"),
    "Role": ("/apis/rbac.authorization.k8s.io/v1", "roles"),
    "RoleBinding": ("/apis/rbac.authorization.k8s.io/v1", "rolebindings"),
    "ClusterRole": ("/apis/rbac.authorization.k8s.io/v1", "clusterroles"),
    "ClusterRoleBinding": (
        "/apis/rbac.authorization.k8s.io/v1", "clusterrolebindings"),
}


class KubeError(Exception):
    pass


def kubeconfig_path() -> str:
    return os.environ.get(
        "KUBECONFIG", os.path.join(os.path.expanduser("~"), ".kube",
                                   "config"))


def _b64_file(data: str, suffix: str, tmpdir: str) -> str:
    """Decode credential data into a file under a private (0700),
    process-lifetime temp dir — ssl wants paths, but decoded keys must
    not persist in /tmp after use."""
    fd, path = tempfile.mkstemp(suffix=suffix, dir=tmpdir)
    with os.fdopen(fd, "wb") as f:
        f.write(base64.b64decode(data))
    return path


class KubeClient:
    def __init__(self, context: str = "", config_path: str | None = None):
        self.server = ""
        self.token = ""
        self._ctx = ssl.create_default_context()
        path = config_path or kubeconfig_path()
        if os.path.exists(path):
            self._from_kubeconfig(path, context)
        elif os.path.exists(os.path.join(SA_DIR, "token")):
            self._from_service_account()
        else:
            raise KubeError(
                f"no kubeconfig at {path} and not running in-cluster")

    # ------------------------------------------------------------ auth

    def _from_kubeconfig(self, path: str, context: str) -> None:
        import yaml

        with open(path, encoding="utf-8") as f:
            cfg = yaml.safe_load(f) or {}
        by_name = lambda items: {i.get("name"): i for i in items or []}  # noqa: E731
        contexts = by_name(cfg.get("contexts"))
        clusters = by_name(cfg.get("clusters"))
        users = by_name(cfg.get("users"))
        ctx_name = context or cfg.get("current-context", "")
        ctx = (contexts.get(ctx_name) or {}).get("context") or {}
        cluster = (clusters.get(ctx.get("cluster")) or {}).get("cluster") \
            or {}
        user = (users.get(ctx.get("user")) or {}).get("user") or {}
        self.server = (cluster.get("server") or "").rstrip("/")
        if not self.server:
            raise KubeError(f"kubeconfig context {ctx_name!r} has no server")

        with tempfile.TemporaryDirectory(prefix="trivy-tpu-kube-") as tmp:
            os.chmod(tmp, 0o700)
            if cluster.get("insecure-skip-tls-verify"):
                self._ctx = ssl._create_unverified_context()
            elif cluster.get("certificate-authority-data"):
                ca = _b64_file(cluster["certificate-authority-data"],
                               ".crt", tmp)
                self._ctx = ssl.create_default_context(cafile=ca)
            elif cluster.get("certificate-authority"):
                self._ctx = ssl.create_default_context(
                    cafile=cluster["certificate-authority"])

            self.token = user.get("token", "")
            cert = user.get("client-certificate") or ""
            key = user.get("client-key") or ""
            if user.get("client-certificate-data"):
                cert = _b64_file(user["client-certificate-data"],
                                 ".crt", tmp)
            if user.get("client-key-data"):
                key = _b64_file(user["client-key-data"], ".key", tmp)
            if cert and key:
                self._ctx.load_cert_chain(cert, key)
            # ssl copies the cert/CA material into the context; the
            # decoded files are gone when this block exits

    def _from_service_account(self) -> None:
        with open(os.path.join(SA_DIR, "token"), encoding="utf-8") as f:
            self.token = f.read().strip()
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.server = f"https://{host}:{port}"
        ca = os.path.join(SA_DIR, "ca.crt")
        if os.path.exists(ca):
            self._ctx = ssl.create_default_context(cafile=ca)

    # ------------------------------------------------------------- api

    def _request(self, method: str, path: str, body: dict | None = None,
                 raw: bool = False):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.server + path, data=data,
                                     method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        ctx = self._ctx if self.server.startswith("https") else None
        try:
            with urllib.request.urlopen(req, timeout=30, context=ctx) as r:
                payload = r.read()
                return payload if raw else json.loads(payload)
        except urllib.error.HTTPError as e:
            raise KubeError(f"{method} {path}: HTTP {e.code}")
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise KubeError(f"{method} {path}: {e}")

    def get(self, path: str) -> dict:
        return self._request("GET", path)

    def post(self, path: str, body: dict) -> dict:
        return self._request("POST", path, body)

    def delete(self, path: str) -> dict:
        return self._request("DELETE", path)

    def pod_logs(self, namespace: str, pod: str) -> bytes:
        return self._request(
            "GET", f"/api/v1/namespaces/{namespace}/pods/{pod}/log",
            raw=True)

    def version(self) -> dict:
        return self.get("/version")

    def list(self, kind: str, namespace: str = "",
             selector: str = "") -> list[dict]:
        """All objects of `kind` (cluster-wide unless namespaced); each
        item gets apiVersion/kind filled in (list responses omit them)."""
        spec = API_PATHS.get(kind)
        if spec is None:
            raise KubeError(f"unsupported kind {kind!r}")
        prefix, plural = spec
        cluster_scoped = kind.startswith("Cluster") or kind == "Node"
        if namespace and not cluster_scoped:
            path = f"{prefix}/namespaces/{namespace}/{plural}"
        else:
            path = f"{prefix}/{plural}"
        if selector:
            from urllib.parse import quote

            path += f"?labelSelector={quote(selector)}"
        doc = self.get(path)
        api_version = prefix.rsplit("/", 1)[-1] if prefix == "/api/v1" \
            else prefix[len("/apis/"):]
        out = []
        for item in doc.get("items") or []:
            item.setdefault("kind", kind)
            item.setdefault("apiVersion",
                            "v1" if prefix == "/api/v1" else api_version)
            out.append(item)
        return out
