"""Kubernetes cluster scanning (reference pkg/k8s atop trivy-kubernetes):
resource enumeration, workload image extraction, misconfig + RBAC + infra
assessment, summary/json reporting."""
