"""RBAC assessment (reference pkg/k8s RBAC scanning via trivy-checks
ksv04x policies; the check identities mirror that set, the predicates are
authored against the Role/ClusterRole rule model)."""

from __future__ import annotations

from dataclasses import dataclass

from trivy_tpu.k8s.artifacts import KubeResource

_SEV = {"CRITICAL": 4, "HIGH": 3, "MEDIUM": 2, "LOW": 1, "UNKNOWN": 0}


@dataclass
class RbacFinding:
    id: str
    title: str
    severity: str
    message: str
    resource: str


def _rules(res: KubeResource) -> list[dict]:
    return [r for r in res.raw.get("rules") or [] if isinstance(r, dict)]


def _has(rule: dict, field: str, *values: str) -> bool:
    have = {str(v) for v in rule.get(field) or []}
    return bool(have & set(values))


def assess_rbac(resources: list[KubeResource]) -> list[RbacFinding]:
    out: list[RbacFinding] = []
    for res in resources:
        if res.kind in ("Role", "ClusterRole"):
            out.extend(_assess_role(res))
        elif res.kind in ("RoleBinding", "ClusterRoleBinding"):
            out.extend(_assess_binding(res))
    out.sort(key=lambda f: (-_SEV.get(f.severity, 0), f.resource, f.id))
    return out


def _assess_role(res: KubeResource) -> list[RbacFinding]:
    out = []
    for rule in _rules(res):
        wild_verb = _has(rule, "verbs", "*")
        wild_res = _has(rule, "resources", "*")
        if wild_verb and wild_res:
            out.append(RbacFinding(
                "KSV046", "Role permits full control of cluster resources",
                "CRITICAL",
                "Role permits wildcard verb on wildcard resource",
                res.fullname))
        elif wild_verb:
            out.append(RbacFinding(
                "KSV045", "Role permits wildcard verbs", "CRITICAL",
                f"Role permits all verbs on "
                f"{sorted(set(rule.get('resources') or []))}",
                res.fullname))
        elif wild_res:
            out.append(RbacFinding(
                "KSV044", "Role permits access to any resource", "CRITICAL",
                f"Role permits {sorted(set(rule.get('verbs') or []))} "
                f"on all resources", res.fullname))
        if _has(rule, "resources", "secrets") and \
                _has(rule, "verbs", "get", "list", "watch", "*"):
            out.append(RbacFinding(
                "KSV041", "Role permits viewing secrets", "CRITICAL",
                "Role permits get/list/watch of secrets", res.fullname))
        if _has(rule, "verbs", "escalate", "bind", "impersonate"):
            out.append(RbacFinding(
                "KSV047", "Role permits privilege escalation verbs",
                "CRITICAL",
                "Role permits escalate/bind/impersonate", res.fullname))
        if _has(rule, "resources", "pods/exec") and \
                _has(rule, "verbs", "create", "*"):
            out.append(RbacFinding(
                "KSV053", "Role permits exec into pods", "HIGH",
                "Role permits creating pod exec sessions", res.fullname))
        if _has(rule, "resources", "roles", "clusterroles",
                "rolebindings", "clusterrolebindings") and \
                _has(rule, "verbs", "create", "update", "patch", "*"):
            out.append(RbacFinding(
                "KSV050", "Role permits managing RBAC resources",
                "CRITICAL",
                "Role permits mutation of RBAC objects", res.fullname))
        if _has(rule, "resources", "pods") and \
                _has(rule, "verbs", "delete", "*") and \
                res.kind == "ClusterRole":
            out.append(RbacFinding(
                "KSV042", "ClusterRole permits deleting pods", "HIGH",
                "ClusterRole permits pod deletion cluster-wide",
                res.fullname))
    return out


def _assess_binding(res: KubeResource) -> list[RbacFinding]:
    out = []
    role_ref = res.raw.get("roleRef") or {}
    subjects = res.raw.get("subjects") or []
    if str(role_ref.get("name")) == "cluster-admin":
        for sub in subjects:
            sname = str((sub or {}).get("name", ""))
            skind = str((sub or {}).get("kind", ""))
            if sname in ("system:authenticated",
                         "system:unauthenticated", "system:anonymous"):
                out.append(RbacFinding(
                    "KSV051",
                    "cluster-admin bound to a system-wide group",
                    "CRITICAL",
                    f"cluster-admin granted to {sname}", res.fullname))
            elif skind == "ServiceAccount" and sname == "default":
                out.append(RbacFinding(
                    "KSV052",
                    "cluster-admin bound to the default service account",
                    "CRITICAL",
                    "cluster-admin granted to a default ServiceAccount",
                    res.fullname))
    return out
