"""Infra (control-plane) assessment — the node-collector analog
(reference pkg/k8s infra-assessment: trivy-checks KCV policies against
kubelet/apiserver configuration gathered by the node collector). Here the
component command lines are read from the static-pod manifests present in
the enumerated resources."""

from __future__ import annotations

from dataclasses import dataclass

from trivy_tpu.k8s.artifacts import INFRA_NAMES, KubeResource, _pod_spec


@dataclass
class InfraFinding:
    id: str
    title: str
    severity: str
    message: str
    resource: str


def _is_control_plane(res: KubeResource) -> bool:
    """Only pods that actually belong to the control plane are assessed —
    an application container that merely mentions "etcd" in its image
    must not trigger KCV checks.  Control-plane static pods live in
    kube-system and carry the kubeadm `component`/`tier` labels."""
    meta = res.raw.get("metadata") or {}
    if (meta.get("namespace") or res.namespace) == "kube-system":
        return True
    labels = meta.get("labels") or {}
    return labels.get("tier") == "control-plane" or \
        labels.get("component") in INFRA_NAMES


def _component_commands(res: KubeResource) -> list[tuple[str, list[str]]]:
    """-> [(component_name, full command argv)] for control-plane pods."""
    if not _is_control_plane(res):
        return []
    out = []
    spec = _pod_spec(res.raw)
    for c in spec.get("containers") or []:
        image = str((c or {}).get("image", ""))
        # component id = image basename sans tag, or exact container name
        image_base = image.rsplit("/", 1)[-1].split(":")[0].split("@")[0]
        name = str((c or {}).get("name", ""))
        for comp in INFRA_NAMES:
            if comp in (image_base, name):
                argv = [str(x) for x in (c.get("command") or [])]
                argv += [str(x) for x in (c.get("args") or [])]
                out.append((comp, argv))
                break
    return out


def _flag(argv: list[str], name: str) -> str | None:
    """--name=value or --name value; None when absent."""
    for i, a in enumerate(argv):
        if a.startswith(name + "="):
            return a.split("=", 1)[1]
        if a == name and i + 1 < len(argv):
            return argv[i + 1]
    return None


def assess_infra(resources: list[KubeResource]) -> list[InfraFinding]:
    out: list[InfraFinding] = []
    for res in resources:
        for comp, argv in _component_commands(res):
            if comp == "kube-apiserver":
                out.extend(_apiserver(argv, res.fullname))
            elif comp == "etcd":
                out.extend(_etcd(argv, res.fullname))
            elif comp == "kube-controller-manager":
                out.extend(_controller_manager(argv, res.fullname))
    return out


def _apiserver(argv, where) -> list[InfraFinding]:
    out = []
    if _flag(argv, "--anonymous-auth") == "true":
        out.append(InfraFinding(
            "KCV0001", "kube-apiserver permits anonymous auth", "HIGH",
            "--anonymous-auth=true", where))
    authz = _flag(argv, "--authorization-mode") or ""
    if authz and "RBAC" not in authz.split(","):
        out.append(InfraFinding(
            "KCV0009", "kube-apiserver authorization does not include "
                       "RBAC", "HIGH",
            f"--authorization-mode={authz}", where))
    if authz and "AlwaysAllow" in authz.split(","):
        out.append(InfraFinding(
            "KCV0007", "kube-apiserver authorizes all requests", "CRITICAL",
            "--authorization-mode includes AlwaysAllow", where))
    if _flag(argv, "--insecure-port") not in (None, "0"):
        out.append(InfraFinding(
            "KCV0016", "kube-apiserver serves on an insecure port", "HIGH",
            f"--insecure-port={_flag(argv, '--insecure-port')}", where))
    if _flag(argv, "--profiling") == "true":
        out.append(InfraFinding(
            "KCV0018", "kube-apiserver profiling enabled", "LOW",
            "--profiling=true", where))
    if _flag(argv, "--kubelet-certificate-authority") is None:
        out.append(InfraFinding(
            "KCV0005", "kube-apiserver does not verify kubelet "
                       "certificates", "MEDIUM",
            "--kubelet-certificate-authority not set", where))
    return out


def _etcd(argv, where) -> list[InfraFinding]:
    out = []
    if _flag(argv, "--client-cert-auth") != "true":
        out.append(InfraFinding(
            "KCV0042", "etcd does not require client certificates", "HIGH",
            "--client-cert-auth is not true", where))
    if _flag(argv, "--auto-tls") == "true":
        out.append(InfraFinding(
            "KCV0043", "etcd uses self-signed auto TLS", "MEDIUM",
            "--auto-tls=true", where))
    return out


def _controller_manager(argv, where) -> list[InfraFinding]:
    out = []
    if _flag(argv, "--use-service-account-credentials") != "true":
        out.append(InfraFinding(
            "KCV0027", "controller-manager does not use per-controller "
                       "service accounts", "MEDIUM",
            "--use-service-account-credentials is not true", where))
    if _flag(argv, "--profiling") == "true":
        out.append(InfraFinding(
            "KCV0028", "controller-manager profiling enabled", "LOW",
            "--profiling=true", where))
    return out
