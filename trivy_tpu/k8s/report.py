"""Cluster report writers (reference pkg/k8s/report: summary table with
per-resource severity counts, full json, and the `all` detail view)."""

from __future__ import annotations

import json
import sys

from trivy_tpu.k8s.scanner import ClusterReport

_SEVS = ["CRITICAL", "HIGH", "MEDIUM", "LOW", "UNKNOWN"]


def _count(findings, key=lambda f: f.severity) -> dict[str, int]:
    out = {s: 0 for s in _SEVS}
    for f in findings:
        out[key(f)] = out.get(key(f), 0) + 1
    return out


def _vuln_counts(rr) -> dict[str, int]:
    out = {s: 0 for s in _SEVS}
    for _img, rep in rr.image_reports:
        for res in rep.results:
            for v in res.vulnerabilities:
                out[str(v.severity)] = out.get(str(v.severity), 0) + 1
    return out


def to_dict(report: ClusterReport) -> dict:
    resources = []
    for rr in report.resources:
        entry = {
            "Namespace": rr.resource.namespace or "default",
            "Kind": rr.resource.kind,
            "Name": rr.resource.name,
            "Images": rr.images,
            "Misconfigurations": [m.to_dict()
                                  for m in rr.misconfigurations],
        }
        if rr.image_reports:
            entry["Vulnerabilities"] = [
                {"Image": img, "Report": rep.to_dict()}
                for img, rep in rr.image_reports
            ]
        resources.append(entry)
    return {
        "ClusterName": report.cluster_name,
        "Resources": resources,
        "RBACAssessment": [
            {"ID": f.id, "Title": f.title, "Severity": f.severity,
             "Message": f.message, "Resource": f.resource}
            for f in report.rbac
        ],
        "InfraAssessment": [
            {"ID": f.id, "Title": f.title, "Severity": f.severity,
             "Message": f.message, "Resource": f.resource}
            for f in report.infra
        ],
    }


def render_summary(report: ClusterReport) -> str:
    """The `--report summary` table: one row per resource with
    misconfig/vuln severity counts, then RBAC and infra sections."""
    out = [f"Summary Report for {report.cluster_name}", ""]

    def table(headers, rows):
        if not rows:
            return ["  (none)", ""]
        widths = [max(len(h), *(len(str(r[i])) for r in rows))
                  for i, h in enumerate(headers)]
        lines = ["  " + "  ".join(h.ljust(widths[i])
                                  for i, h in enumerate(headers))]
        lines.append("  " + "  ".join("-" * w for w in widths))
        for r in rows:
            lines.append("  " + "  ".join(str(r[i]).ljust(widths[i])
                                          for i in range(len(headers))))
        lines.append("")
        return lines

    rows = []
    for rr in sorted(report.resources,
                     key=lambda r: (r.resource.namespace, r.resource.kind,
                                    r.resource.name)):
        m = _count(rr.misconfigurations)
        v = _vuln_counts(rr)
        sev_cell = "/".join(str(m[s]) for s in _SEVS[:4])
        vuln_cell = "/".join(str(v[s]) for s in _SEVS[:4])
        rows.append([rr.resource.namespace or "default", rr.resource.kind,
                     rr.resource.name, vuln_cell, sev_cell])
    out.append("Workload Assessment (C/H/M/L)")
    out.extend(table(["Namespace", "Kind", "Name", "Vulns", "Misconfigs"],
                     rows))

    out.append("RBAC Assessment")
    out.extend(table(
        ["Severity", "ID", "Resource", "Title"],
        [[f.severity, f.id, f.resource, f.title] for f in report.rbac]))

    out.append("Infra Assessment")
    out.extend(table(
        ["Severity", "ID", "Resource", "Title"],
        [[f.severity, f.id, f.resource, f.title] for f in report.infra]))
    return "\n".join(out)


def render_all(report: ClusterReport) -> str:
    """`--report all`: summary plus each failing misconfiguration."""
    out = [render_summary(report), "", "Detailed Findings", "=" * 17, ""]
    for rr in report.resources:
        if not rr.misconfigurations:
            continue
        out.append(rr.resource.fullname)
        for m in rr.misconfigurations:
            out.append(f"  [{m.severity}] {m.id}: {m.message}")
        out.append("")
    return "\n".join(out)


def write_cluster_report(report: ClusterReport, fmt: str = "summary",
                         output: str | None = None) -> None:
    if fmt == "json":
        text = json.dumps(to_dict(report), indent=2)
    elif fmt == "all":
        text = render_all(report)
    else:
        text = render_summary(report)
    if output:
        # lint: allow[atomic-write] user-requested report stream, partial file is visible to the user
        with open(output, "w") as f:
            f.write(text + "\n")
    else:
        sys.stdout.write(text + "\n")
