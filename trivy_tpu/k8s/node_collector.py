"""Node collector (reference trivy-kubernetes node-collector: a per-node
Job gathers kubelet configuration, file permissions/ownership, and node
metadata that the API server does not expose; its stdout is a NodeInfo
JSON document assessed against the KCV node checks).

Three pieces, mirroring the reference flow
(pkg/k8s/commands/cluster.go:39-87 ListArtifactAndNodeInfo):
  collector_job(node, …)      -> the Job manifest dispatched per node
  collect_node_info(client,…) -> run the job, read the pod log, clean up
  assess_node_info(doc)       -> InfraFindings from the NodeInfo document

Offline path: `kind: NodeInfo` documents found among scanned manifests
are assessed directly, so air-gapped clusters can run the collector
out-of-band and feed its output to `trivy-tpu k8s <dir>`.
"""

from __future__ import annotations

import hashlib
import json
import re
import time

from trivy_tpu.k8s.infra import InfraFinding
from trivy_tpu.log import logger

_log = logger("node-collector")

DEFAULT_IMAGE = "ghcr.io/aquasecurity/node-collector:0.3.1"
DEFAULT_NAMESPACE = "trivy-temp"
JOB_LABEL = "trivy-tpu.node-collector"


def _node_tag(node: str) -> str:
    """Node name -> a value safe as both a Job-name fragment and a label
    value (<= 63 chars, DNS-ish charset). Long names keep a hash suffix
    so distinct nodes never collide after truncation."""
    clean = re.sub(r"[^a-z0-9-]+", "-", node.lower()).strip("-") or "node"
    if len(clean) <= 40 and clean == node:
        return clean
    digest = hashlib.sha1(node.encode()).hexdigest()[:8]
    return f"{clean[:40].rstrip('-')}-{digest}"


def collector_job(node: str, namespace: str = DEFAULT_NAMESPACE,
                  image: str = DEFAULT_IMAGE,
                  tolerations: list[dict] | None = None) -> dict:
    """Job manifest pinned to `node`, with the host mounts the collector
    reads (kubelet config, PKI, service files)."""
    mounts = {
        "var-lib-kubelet": "/var/lib/kubelet",
        "etc-kubernetes": "/etc/kubernetes",
        "etc-systemd": "/etc/systemd",
        "lib-systemd": "/lib/systemd",
    }
    tag = _node_tag(node)  # label-safe; nodeName keeps the raw name
    name = f"node-collector-{tag}"[:63].rstrip("-")
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": {"app": JOB_LABEL, "node": tag},
        },
        "spec": {
            "ttlSecondsAfterFinished": 300,
            "backoffLimit": 1,
            "template": {
                "metadata": {"labels": {"app": JOB_LABEL, "node": tag}},
                "spec": {
                    "nodeName": node,
                    "restartPolicy": "Never",
                    "hostPID": True,
                    "tolerations": tolerations or [
                        {"operator": "Exists", "effect": "NoSchedule"},
                    ],
                    "containers": [{
                        "name": "node-collector",
                        "image": image,
                        "args": ["k8s-node-collector"],
                        "securityContext": {"readOnlyRootFilesystem": True},
                        "volumeMounts": [
                            {"name": k, "mountPath": v, "readOnly": True}
                            for k, v in mounts.items()
                        ],
                    }],
                    "volumes": [
                        {"name": k, "hostPath": {"path": v}}
                        for k, v in mounts.items()
                    ],
                },
            },
        },
    }


def collect_node_info(client, node: str,
                      namespace: str = DEFAULT_NAMESPACE,
                      image: str = DEFAULT_IMAGE,
                      timeout_s: float = 120.0,
                      poll_s: float = 2.0) -> dict | None:
    """Run the collector Job on `node` and return its NodeInfo document
    (None on timeout/failure — node assessment is best-effort, the rest
    of the cluster scan proceeds)."""
    job = collector_job(node, namespace=namespace, image=image)
    path = f"/apis/batch/v1/namespaces/{namespace}/jobs"
    try:
        # the scratch namespace may not exist yet; 409 (exists) is fine
        try:
            client.post("/api/v1/namespaces",
                        {"apiVersion": "v1", "kind": "Namespace",
                         "metadata": {"name": namespace}})
        except Exception:
            pass
        client.post(path, job)
    except Exception as e:
        _log.warn("node-collector job create failed", node=node,
                  err=str(e))
        return None
    name = job["metadata"]["name"]
    selector = f"app={JOB_LABEL},node={_node_tag(node)}"
    deadline = time.monotonic() + timeout_s
    doc = None
    try:
        while time.monotonic() < deadline:
            pods = client.list("Pod", namespace=namespace,
                               selector=selector)
            failed = 0
            for pod in pods:
                phase = (pod.get("status") or {}).get("phase")
                if phase == "Succeeded":
                    raw = client.pod_logs(
                        namespace, pod["metadata"]["name"])
                    doc = json.loads(raw)
                    break
                if phase == "Failed":
                    failed += 1
            if doc is not None:
                break
            # backoffLimit=1 -> two attempts; only give up when both
            # pods failed (a Failed first attempt may still be retried)
            if failed >= 2:
                _log.warn("node-collector pods failed", node=node)
                return None
            time.sleep(poll_s)
    except Exception as e:
        _log.warn("node-collector failed", node=node, err=str(e))
        return None
    finally:
        try:
            client.delete(f"{path}/{name}"
                          "?propagationPolicy=Background")
        except Exception:
            pass
    if doc is None:
        _log.warn("node-collector timed out", node=node,
                  timeout_s=timeout_s)
    return doc


# ------------------------------------------------------------ assessment

# Spec of one KCV node check over the collector "info" map:
# (id, title, severity, info key, kind, expectation)
#   kind "perm":         every collected octal permission must be <= expect
#   kind "owner":        every collected owner string must equal expect
#   kind "eq":           first value stringified must equal expect
#   kind "ne":           first value must differ from expect (exact)
#   kind "not_contains": first value must not contain expect
#   kind "set":          a value must be present (non-empty)
_NODE_CHECKS: list[tuple] = [
    ("KCV0069", "kubelet.conf permissions too open", "HIGH",
     "kubeletConfFilePermissions", "perm", 0o644),
    ("KCV0070", "kubelet.conf not owned by root:root", "HIGH",
     "kubeletConfFileOwnership", "owner", "root:root"),
    ("KCV0073", "kubelet config.yaml permissions too open", "HIGH",
     "kubeletConfigYamlConfigurationFilePermission", "perm", 0o644),
    ("KCV0074", "kubelet config.yaml not owned by root:root", "HIGH",
     "kubeletConfigYamlConfigurationFileOwnership", "owner", "root:root"),
    ("KCV0067", "kubelet service file permissions too open", "HIGH",
     "kubeletServiceFilePermissions", "perm", 0o644),
    ("KCV0068", "kubelet service file not owned by root:root", "HIGH",
     "kubeletServiceFileOwnership", "owner", "root:root"),
    ("KCV0075", "client CA file permissions too open", "CRITICAL",
     "certificateAuthoritiesFilePermissions", "perm", 0o644),
    ("KCV0077", "kubelet permits anonymous auth", "CRITICAL",
     "kubeletAnonymousAuthArgumentSet", "eq", "false"),
    ("KCV0078", "kubelet authorization mode is AlwaysAllow", "CRITICAL",
     "kubeletAuthorizationModeArgumentSet", "not_contains", "AlwaysAllow"),
    ("KCV0079", "kubelet client CA file not configured", "CRITICAL",
     "kubeletClientCaFileArgumentSet", "set", None),
    ("KCV0080", "kubelet read-only port is enabled", "HIGH",
     "kubeletReadOnlyPortArgumentSet", "eq", "0"),
    ("KCV0081", "kubelet streaming connection never times out", "HIGH",
     "kubeletStreamingConnectionIdleTimeoutArgumentSet", "ne", "0"),
    ("KCV0082", "kubelet does not protect kernel defaults", "HIGH",
     "kubeletProtectKernelDefaultsArgumentSet", "eq", "true"),
    ("KCV0083", "kubelet does not manage iptables util chains", "HIGH",
     "kubeletMakeIptablesUtilChainsArgumentSet", "eq", "true"),
    ("KCV0090", "kubelet client certificate rotation disabled", "HIGH",
     "kubeletRotateCertificatesArgumentSet", "eq", "true"),
    ("KCV0091", "kubelet server certificate rotation disabled", "HIGH",
     "kubeletRotateKubeletServerCertificateArgumentSet", "eq", "true"),
]


def _values(info: dict, key: str) -> list:
    entry = info.get(key)
    if isinstance(entry, dict):
        vals = entry.get("values")
        return vals if isinstance(vals, list) else []
    if isinstance(entry, list):
        return entry
    return []


def _parse_perm(v) -> int | None:
    try:
        return int(str(v), 8)
    except (TypeError, ValueError):
        return None


def assess_node_info(doc: dict) -> list[InfraFinding]:
    """NodeInfo document (collector stdout) -> node-level findings."""
    info = doc.get("info") or {}
    node = str(doc.get("nodeName") or
               (doc.get("metadata") or {}).get("name") or "node")
    out: list[InfraFinding] = []
    for check_id, title, severity, key, kind, expect in _NODE_CHECKS:
        vals = _values(info, key)
        if not vals:
            if kind == "set" and key in info:
                out.append(InfraFinding(
                    check_id, title, severity, f"{key} is empty",
                    f"Node/{node}"))
            continue  # not collected -> unknown, stay silent
        if kind == "perm":
            for v in vals:
                perm = _parse_perm(v)
                if perm is not None and perm & ~int(expect):
                    out.append(InfraFinding(
                        check_id, title, severity,
                        f"{key}={oct(perm)[2:]} (want <= "
                        f"{oct(int(expect))[2:]})", f"Node/{node}"))
                    break
        elif kind == "owner":
            for v in vals:
                if str(v) != expect:
                    out.append(InfraFinding(
                        check_id, title, severity, f"{key}={v}",
                        f"Node/{node}"))
                    break
        elif kind == "eq":
            if str(vals[0]).lower() != str(expect):
                out.append(InfraFinding(
                    check_id, title, severity, f"{key}={vals[0]}",
                    f"Node/{node}"))
        elif kind == "ne":
            if str(vals[0]).lower() == str(expect).lower():
                out.append(InfraFinding(
                    check_id, title, severity, f"{key}={vals[0]}",
                    f"Node/{node}"))
        elif kind == "not_contains":
            if str(expect).lower() in str(vals[0]).lower():
                out.append(InfraFinding(
                    check_id, title, severity, f"{key}={vals[0]}",
                    f"Node/{node}"))
        elif kind == "set":
            if not any(str(v).strip() for v in vals):
                out.append(InfraFinding(
                    check_id, title, severity, f"{key} is empty",
                    f"Node/{node}"))
    return out
