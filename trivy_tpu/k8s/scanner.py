"""Cluster scan orchestration (reference pkg/k8s/scanner/scanner.go:
parallel pipeline over cluster artifacts; vuln scan per workload image,
misconfig scan per resource, RBAC + infra assessments merged into one
cluster report)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import yaml

from trivy_tpu.k8s.artifacts import (
    RBAC_KINDS,
    WORKLOAD_KINDS,
    KubeResource,
    load_cluster,
    load_manifests,
)
from trivy_tpu.k8s.infra import InfraFinding, assess_infra
from trivy_tpu.k8s.rbac import RbacFinding, assess_rbac
from trivy_tpu.log import logger
from trivy_tpu.utils.pipeline import run_pipeline

_log = logger("k8s")


@dataclass
class ResourceResult:
    resource: KubeResource = None
    misconfigurations: list = field(default_factory=list)  # Detected...
    images: list[str] = field(default_factory=list)
    # vulnerability results per image (populated when the image is
    # resolvable locally, e.g. an image-tar directory is given)
    image_reports: list = field(default_factory=list)


@dataclass
class ClusterReport:
    cluster_name: str = ""
    resources: list[ResourceResult] = field(default_factory=list)
    rbac: list[RbacFinding] = field(default_factory=list)
    infra: list[InfraFinding] = field(default_factory=list)


class ClusterScanner:
    """scan(target): target is a manifests dir/file or 'cluster' for a
    live kubeconfig-backed cluster."""

    def __init__(self, scanners: set[str] | None = None, workers: int = 5,
                 image_tar_dir: str | None = None, engine=None,
                 disable_node_collector: bool = False,
                 node_collector_namespace: str | None = None,
                 node_collector_image: str | None = None,
                 kube_client_factory=None):
        self.scanners = scanners or {"misconfig", "rbac", "infra"}
        self.workers = workers
        self.image_tar_dir = image_tar_dir
        self.engine = engine  # MatchEngine for image vuln scans
        self.disable_node_collector = disable_node_collector
        self.node_collector_namespace = node_collector_namespace
        self.node_collector_image = node_collector_image
        # injectable for tests; defaults to KubeClient(context=...)
        self.kube_client_factory = kube_client_factory

    def scan(self, target: str, context: str = "",
             namespace: str = "") -> ClusterReport:
        if target == "cluster":
            resources = load_cluster(context=context, namespace=namespace)
            name = context or "cluster"
        else:
            resources = load_manifests(target)
            name = os.path.basename(os.path.abspath(target))
        report = ClusterReport(cluster_name=name)
        workloads = [r for r in resources if r.kind in WORKLOAD_KINDS]
        others = [r for r in resources if r.kind not in WORKLOAD_KINDS]

        scannable = workloads + [
            r for r in others if r.kind not in RBAC_KINDS]
        if "misconfig" in self.scanners:
            report.resources = run_pipeline(
                scannable, self._scan_resource, workers=self.workers)
            report.resources = [r for r in report.resources
                                if r is not None]
        elif "vuln" in self.scanners:
            # vuln-only scans still need the workload rows to find images
            report.resources = [
                ResourceResult(resource=r, images=r.images)
                for r in scannable if r.images]
        if "rbac" in self.scanners:
            report.rbac = assess_rbac(resources)
        if "infra" in self.scanners:
            report.infra = assess_infra(resources)
            report.infra.extend(self._node_findings(resources, target,
                                                    context))
        if "vuln" in self.scanners and self.image_tar_dir:
            self._scan_images(report)
        return report

    def _node_findings(self, resources: list[KubeResource], target: str,
                       context: str) -> list[InfraFinding]:
        """Node-level KCV findings: NodeInfo documents found among the
        scanned manifests (out-of-band collector runs) are assessed
        directly; live cluster scans additionally dispatch the
        node-collector Job per node unless disabled."""
        from trivy_tpu.k8s.node_collector import (
            assess_node_info,
            collect_node_info,
        )

        out: list[InfraFinding] = []
        for res in resources:
            if res.kind == "NodeInfo":
                out.extend(assess_node_info(res.raw))
        if target != "cluster" or self.disable_node_collector:
            return out
        try:
            if self.kube_client_factory is not None:
                client = self.kube_client_factory()
            else:
                from trivy_tpu.k8s.client import KubeClient

                client = KubeClient(context=context)
            nodes = [n["metadata"]["name"] for n in client.list("Node")]
        except Exception as e:
            _log.warn("node-collector skipped", err=str(e))
            return out
        kwargs = {}
        if self.node_collector_namespace:
            kwargs["namespace"] = self.node_collector_namespace
        if self.node_collector_image:
            kwargs["image"] = self.node_collector_image

        def collect_one(node: str):
            doc = collect_node_info(client, node, **kwargs)
            return assess_node_info(doc) if doc else []

        for findings in run_pipeline(nodes, collect_one,
                                     workers=self.workers):
            out.extend(findings)
        return out

    # ------------------------------------------------------------ steps

    def _scan_resource(self, res: KubeResource) -> ResourceResult | None:
        from trivy_tpu.misconf.scanner import scan_config

        content = yaml.safe_dump(res.raw, sort_keys=False).encode()
        misconf = scan_config(res.fullname + ".yaml", content,
                              file_type="kubernetes")
        rr = ResourceResult(resource=res, images=res.images)
        if misconf is not None:
            rr.misconfigurations = misconf.failures
        elif not rr.images and res.kind not in WORKLOAD_KINDS:
            return None  # nothing checkable and nothing to report
        return rr

    def _scan_images(self, report: ClusterReport) -> None:
        """Scan workload images resolvable as local tars: an image
        `repo/name:tag` matches <image_tar_dir>/<name>_<tag>.tar or
        <name>.tar (registry pulls are the online path)."""
        distinct = sorted({img for rr in report.resources
                           for img in rr.images})

        def scan_one(img: str):
            tar = self._find_tar(img)
            if tar is None:
                return img, None
            try:
                return img, self._scan_image_tar(tar)
            except Exception as e:
                _log.warn("image scan failed", image=img, err=str(e))
                return img, None

        seen = dict(run_pipeline(distinct, scan_one, workers=self.workers))
        for rr in report.resources:
            for img in rr.images:
                rep = seen.get(img)
                if rep is not None:
                    rr.image_reports.append((img, rep))

    def _scan_image_tar(self, tar_path: str):
        from trivy_tpu.artifact.image import ImageArtifact
        from trivy_tpu.cache.cache import MemoryCache
        from trivy_tpu.scanner.local import LocalDriver
        from trivy_tpu.scanner.scan import Scanner
        from trivy_tpu.types.scan import ScanOptions

        cache = MemoryCache()
        artifact = ImageArtifact(tar_path, cache, from_tar=True,
                                 parallel=self.workers)
        driver = LocalDriver(self.engine, cache)
        return Scanner(driver, artifact).scan_artifact(ScanOptions())

    def _find_tar(self, image: str) -> str | None:
        if not self.image_tar_dir:
            return None
        name = image.rsplit("/", 1)[-1]
        exact = os.path.join(self.image_tar_dir,
                             name.replace(":", "_") + ".tar")
        if os.path.exists(exact):
            return exact
        # tag-less fallback only when the workload itself pins no tag
        # (or the default "latest") — a versioned ref must match exactly,
        # otherwise we would attribute the wrong image's findings to it
        tag = name.split(":", 1)[1] if ":" in name else ""
        if tag in ("", "latest"):
            p = os.path.join(self.image_tar_dir,
                             name.split(":")[0] + ".tar")
            if os.path.exists(p):
                return p
        return None
