"""Cluster artifact enumeration (reference trivy-kubernetes
pkg/k8s + pkg/trivyk8s: lists cluster resources and derives scannable
artifacts). Two sources:

- a manifests directory / file (offline, deterministic — the test path)
- a live cluster via `kubectl get ... -o json` when kubectl + kubeconfig
  are available (network-gated, mirrors the reference's client-go use)
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
from dataclasses import dataclass, field

import yaml

from trivy_tpu.log import logger

_log = logger("k8s")

# workload kinds whose pod specs carry images
WORKLOAD_KINDS = {
    "Pod", "Deployment", "StatefulSet", "DaemonSet", "ReplicaSet",
    "ReplicationController", "Job", "CronJob",
}
RBAC_KINDS = {"Role", "ClusterRole", "RoleBinding", "ClusterRoleBinding"}
# control-plane components assessed by the infra checks
INFRA_NAMES = ("kube-apiserver", "kube-controller-manager",
               "kube-scheduler", "etcd", "kubelet")


@dataclass
class KubeResource:
    kind: str = ""
    name: str = ""
    namespace: str = ""
    raw: dict = field(default_factory=dict)

    @property
    def fullname(self) -> str:
        ns = self.namespace or "default"
        return f"{ns}/{self.kind}/{self.name}"

    @property
    def images(self) -> list[str]:
        if self.kind not in WORKLOAD_KINDS:
            return []
        spec = _pod_spec(self.raw)
        out = []
        for key in ("initContainers", "containers", "ephemeralContainers"):
            for c in spec.get(key) or []:
                img = (c or {}).get("image")
                if img:
                    out.append(str(img))
        return out


def _pod_spec(doc: dict) -> dict:
    spec = doc.get("spec") or {}
    kind = doc.get("kind", "")
    if kind == "Pod":
        return spec
    if kind == "CronJob":
        return (((spec.get("jobTemplate") or {}).get("spec") or {})
                .get("template") or {}).get("spec") or {}
    return (spec.get("template") or {}).get("spec") or {}


def load_manifests(target: str) -> list[KubeResource]:
    """Parse a manifest file or directory tree into resources."""
    paths: list[str] = []
    if os.path.isdir(target):
        for root, _dirs, names in os.walk(target):
            for n in sorted(names):
                if n.endswith((".yaml", ".yml", ".json")):
                    paths.append(os.path.join(root, n))
    elif os.path.exists(target):
        paths = [target]
    else:
        raise RuntimeError(f"no such manifest file or directory: {target}")
    out: list[KubeResource] = []
    for p in paths:
        try:
            with open(p, "rb") as f:
                content = f.read()
        except OSError as e:
            _log.warn("cannot read manifest", path=p, err=str(e))
            continue
        out.extend(parse_manifest_docs(content))
    return out


def parse_manifest_docs(content: bytes) -> list[KubeResource]:
    docs: list[dict] = []
    text = content.decode("utf-8", "replace")
    if text.lstrip().startswith("{"):
        try:
            doc = json.loads(text)
            docs = doc.get("items", [doc]) if isinstance(doc, dict) else []
        except ValueError:
            return []
    else:
        try:
            for d in yaml.safe_load_all(text):
                if isinstance(d, dict):
                    docs.extend(d.get("items", [d])
                                if d.get("kind", "").endswith("List")
                                else [d])
        except yaml.YAMLError:
            return []
    out = []
    for d in docs:
        if not isinstance(d, dict) or not d.get("kind"):
            continue
        meta = d.get("metadata") or {}
        out.append(KubeResource(
            kind=str(d["kind"]), name=str(meta.get("name", "")),
            namespace=str(meta.get("namespace", "")), raw=d,
        ))
    return out


# ------------------------------------------------------------ live cluster


_KUBECTL_KINDS = (
    "pods", "deployments", "statefulsets", "daemonsets", "replicasets",
    "jobs", "cronjobs", "services", "configmaps",
    "roles", "clusterroles", "rolebindings", "clusterrolebindings",
    "networkpolicies", "ingresses",
)


def kubectl_available() -> bool:
    return shutil.which("kubectl") is not None


def load_cluster(context: str = "", namespace: str = "",
                 kinds: tuple = _KUBECTL_KINDS) -> list[KubeResource]:
    """Enumerate a live cluster: the API-server client first (kubeconfig
    or in-cluster service account, reference client-go), kubectl as a
    last-resort fallback."""
    try:
        return load_cluster_api(context, namespace, kinds)
    except Exception as e:
        _log.debug("api client unavailable, trying kubectl", err=str(e))
    if not kubectl_available():
        raise RuntimeError(
            "no kubeconfig/in-cluster credentials and no kubectl; "
            "scan a manifests directory instead")
    out: list[KubeResource] = []
    for kind in kinds:
        cmd = ["kubectl", "get", kind, "-o", "json"]
        cmd += ["--all-namespaces"] if not namespace else ["-n", namespace]
        if context:
            cmd += ["--context", context]
        try:
            proc = subprocess.run(cmd, capture_output=True, timeout=60)
        except (subprocess.TimeoutExpired, OSError) as e:
            _log.warn("kubectl failed", kind=kind, err=str(e))
            continue
        if proc.returncode != 0:
            _log.debug("kubectl get failed", kind=kind,
                       err=proc.stderr.decode("utf-8", "replace")[:200])
            continue
        out.extend(parse_manifest_docs(proc.stdout))
    return out


# kubectl plural -> API object Kind
_PLURAL_KIND = {
    "pods": "Pod", "deployments": "Deployment",
    "statefulsets": "StatefulSet", "daemonsets": "DaemonSet",
    "replicasets": "ReplicaSet", "jobs": "Job", "cronjobs": "CronJob",
    "services": "Service", "configmaps": "ConfigMap",
    "roles": "Role", "clusterroles": "ClusterRole",
    "rolebindings": "RoleBinding",
    "clusterrolebindings": "ClusterRoleBinding",
    "nodes": "Node",
}


def load_cluster_api(context: str = "", namespace: str = "",
                     kinds: tuple = _KUBECTL_KINDS) -> list[KubeResource]:
    """Enumerate a live cluster through the API server directly
    (trivy_tpu.k8s.client; no kubectl subprocess)."""
    from trivy_tpu.k8s.client import API_PATHS, KubeClient

    client = KubeClient(context=context)
    out: list[KubeResource] = []
    errors = 0
    attempted = 0
    for plural in kinds:
        kind = _PLURAL_KIND.get(plural, plural)
        if kind not in API_PATHS:
            continue
        attempted += 1
        try:
            items = client.list(kind, namespace=namespace)
        except Exception as e:
            _log.debug("list failed", kind=kind, err=str(e))
            errors += 1
            continue
        for item in items:
            meta = item.get("metadata") or {}
            out.append(KubeResource(
                kind=item.get("kind", kind),
                name=meta.get("name", ""),
                namespace=meta.get("namespace", ""),
                raw=item,
            ))
    if not out and errors == attempted and attempted:
        # every list failed (e.g. exec-based kubeconfig auth this client
        # doesn't speak): surface the failure so load_cluster can fall
        # back to kubectl, which does support it
        raise RuntimeError("all API list calls failed (unsupported auth?)")
    return out
