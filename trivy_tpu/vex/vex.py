"""VEX document parsing + report filtering (reference pkg/vex/vex.go:65
Filter; format decoders in pkg/vex/{openvex,cyclonedx,csaf}.go).

Statuses that suppress a finding: not_affected, fixed (reference
pkg/vex/vex.go NotAffected/Fixed handling). Suppressed findings move to
the result's modified-findings list rather than vanishing, mirroring
--show-suppressed."""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from trivy_tpu.log import logger
from trivy_tpu.types.report import Report, Result
from trivy_tpu.utils.purl import parse_purl

_log = logger("vex")

STATUS_NOT_AFFECTED = "not_affected"
STATUS_AFFECTED = "affected"
STATUS_FIXED = "fixed"
STATUS_UNDER_INVESTIGATION = "under_investigation"

_SUPPRESS = (STATUS_NOT_AFFECTED, STATUS_FIXED)


@dataclass
class VexStatement:
    vulnerability_id: str = ""
    vuln_aliases: list[str] = field(default_factory=list)
    status: str = ""
    justification: str = ""
    impact: str = ""           # impact_statement / detail
    # purls or bom-refs; a statement with no identifiable products never
    # suppresses (reference only suppresses on a product match — a
    # products-less statement would otherwise drop the CVE for EVERY
    # package in the report)
    products: list[str] = field(default_factory=list)

    def matches(self, vuln_id: str, aliases: list[str], purl: str,
                bom_ref: str = "") -> bool:
        finding_ids = {vuln_id, *aliases}
        statement_ids = {self.vulnerability_id, *self.vuln_aliases}
        if not (finding_ids & statement_ids):
            return False
        if not self.products:
            return False
        return any(
            _purl_match(p, purl) or (bom_ref and p == bom_ref)
            for p in self.products
        )


@dataclass
class VexDocument:
    source: str = ""
    statements: list[VexStatement] = field(default_factory=list)


def _purl_match(pattern: str, purl: str) -> bool:
    """PURL containment: pattern matches when all its set fields equal
    the target's (reference pkg/purl Match semantics)."""
    if not purl:
        return False
    if pattern == purl:
        return True
    if not pattern.startswith("pkg:"):
        return False  # bom-ref style identifier, not a purl
    try:
        a = parse_purl(pattern)
        b = parse_purl(purl)
    except Exception:
        return False
    if a.type != b.type:
        return False
    if a.namespace and a.namespace != b.namespace:
        return False
    if a.name and a.name != b.name:
        return False
    if a.version and a.version != b.version:
        return False
    for k, v in (a.qualifiers or {}).items():
        if (b.qualifiers or {}).get(k) != v:
            return False
    return True


# ------------------------------------------------------------ decoders


def _decode_openvex(doc: dict, source: str) -> VexDocument:
    out = VexDocument(source=source)
    for st in doc.get("statements") or []:
        vuln = st.get("vulnerability") or {}
        vid = vuln.get("name") or vuln.get("@id", "")
        aliases = [str(a) for a in vuln.get("aliases") or []]
        products = []
        for p in st.get("products") or []:
            pid = p.get("@id", "") if isinstance(p, dict) else str(p)
            if pid:
                products.append(pid)
            for sub in (p.get("subcomponents") or []
                        if isinstance(p, dict) else []):
                sid = sub.get("@id", "") if isinstance(sub, dict) \
                    else str(sub)
                if sid:
                    products.append(sid)
        out.statements.append(VexStatement(
            vulnerability_id=vid,
            vuln_aliases=aliases,
            status=st.get("status", ""),
            justification=st.get("justification", ""),
            impact=st.get("impact_statement", ""),
            products=products,
        ))
    return out


_CDX_STATE = {
    "not_affected": STATUS_NOT_AFFECTED,
    "exploitable": STATUS_AFFECTED,
    "resolved": STATUS_FIXED,
    "resolved_with_pedigree": STATUS_FIXED,
    "in_triage": STATUS_UNDER_INVESTIGATION,
    "false_positive": STATUS_NOT_AFFECTED,
}


def _decode_cyclonedx(doc: dict, source: str) -> VexDocument:
    out = VexDocument(source=source)
    for v in doc.get("vulnerabilities") or []:
        analysis = v.get("analysis") or {}
        status = _CDX_STATE.get(analysis.get("state", ""), "")
        products = [
            a.get("ref", "") for a in v.get("affects") or []
            if isinstance(a, dict) and a.get("ref")
        ]
        out.statements.append(VexStatement(
            vulnerability_id=v.get("id", ""),
            status=status,
            justification=analysis.get("justification", ""),
            impact=analysis.get("detail", ""),
            products=products,
        ))
    return out


def _decode_csaf(doc: dict, source: str) -> VexDocument:
    out = VexDocument(source=source)
    purl_by_product = _csaf_product_purls(doc.get("product_tree") or {})

    def expand(ids) -> list[str]:
        purls = []
        for pid in ids or []:
            purls.extend(purl_by_product.get(pid, []))
        return purls

    for v in doc.get("vulnerabilities") or []:
        vid = v.get("cve") or (v.get("ids") or [{}])[0].get("text", "")
        ps = v.get("product_status") or {}
        just = ""
        for flag in v.get("flags") or []:
            just = flag.get("label", "") or just
        for status, key in (
            (STATUS_NOT_AFFECTED, "known_not_affected"),
            (STATUS_FIXED, "fixed"),
            (STATUS_AFFECTED, "known_affected"),
            (STATUS_UNDER_INVESTIGATION, "under_investigation"),
        ):
            ids = ps.get(key)
            if ids:
                out.statements.append(VexStatement(
                    vulnerability_id=vid, status=status,
                    justification=just, products=expand(ids),
                ))
    return out


def _csaf_product_purls(tree: dict) -> dict[str, list[str]]:
    """product_id -> purls, from product_tree branches + relationships."""
    out: dict[str, list[str]] = {}

    def walk(branch):
        if isinstance(branch, dict):
            prod = branch.get("product")
            if isinstance(prod, dict):
                pid = prod.get("product_id", "")
                helper = (prod.get("product_identification_helper")
                          or {})
                purl = helper.get("purl", "")
                if pid and purl:
                    out.setdefault(pid, []).append(purl)
            for b in branch.get("branches") or []:
                walk(b)

    for b in tree.get("branches") or []:
        walk(b)
    # relationships compose products; inherit component purls
    for rel in tree.get("relationships") or []:
        full = (rel.get("full_product_name") or {}).get("product_id", "")
        ref = rel.get("product_reference", "")
        if full and ref in out:
            out.setdefault(full, []).extend(out[ref])
    return out


def load_vex(path: str) -> VexDocument:
    """Sniff the format and decode (reference pkg/vex/document.go)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if "statements" in doc and "@context" in doc:
        return _decode_openvex(doc, path)
    if doc.get("bomFormat") == "CycloneDX":
        return _decode_cyclonedx(doc, path)
    category = (doc.get("document") or {}).get("category", "")
    if category.startswith("csaf"):
        return _decode_csaf(doc, path)
    raise ValueError(f"unrecognized VEX format in {path}")


# ------------------------------------------------------------ filtering


def filter_report_vex(report: Report, vex_docs: list[VexDocument]) -> int:
    """Suppress findings asserted not_affected/fixed; returns the number
    suppressed. Suppressed entries are kept on the result as modified
    findings (rendered under ExperimentalModifiedFindings)."""
    total = 0
    for res in report.results:
        total += _filter_result(res, vex_docs)
    return total


def _filter_result(res: Result, vex_docs: list[VexDocument]) -> int:
    kept = []
    modified = getattr(res, "modified_findings", None) or []
    for v in res.vulnerabilities:
        purl = v.pkg_identifier.purl
        bom_ref = v.pkg_identifier.bom_ref
        statement = None
        for doc in vex_docs:
            for st in doc.statements:
                if st.status in _SUPPRESS and st.matches(
                    v.vulnerability_id, v.vendor_ids, purl, bom_ref
                ):
                    statement = (doc, st)
                    break
            if statement:
                break
        if statement is None:
            kept.append(v)
            continue
        doc, st = statement
        total_d = {
            "Type": "vulnerability",
            "Status": st.status,
            "Statement": st.justification or st.impact or "",
            "Source": doc.source,
            "Finding": v.to_dict(),
        }
        modified.append(total_d)
        _log.debug("vex suppressed", id=v.vulnerability_id,
                   status=st.status, source=doc.source)
    suppressed = len(res.vulnerabilities) - len(kept)
    res.vulnerabilities = kept
    res.modified_findings = modified
    return suppressed
