"""VEX document parsing + report filtering (reference pkg/vex/vex.go:65
Filter; format decoders in pkg/vex/{openvex,cyclonedx,csaf}.go).

Statuses that suppress a finding: not_affected, fixed (reference
pkg/vex/vex.go NotAffected/Fixed handling). Suppressed findings move to
the result's modified-findings list rather than vanishing, mirroring
--show-suppressed."""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from trivy_tpu.log import logger
from trivy_tpu.types.report import Report, Result
from trivy_tpu.utils.purl import parse_purl

_log = logger("vex")

STATUS_NOT_AFFECTED = "not_affected"
STATUS_AFFECTED = "affected"
STATUS_FIXED = "fixed"
STATUS_UNDER_INVESTIGATION = "under_investigation"

_SUPPRESS = (STATUS_NOT_AFFECTED, STATUS_FIXED)


@dataclass
class VexStatement:
    vulnerability_id: str = ""
    vuln_aliases: list[str] = field(default_factory=list)
    status: str = ""
    justification: str = ""
    impact: str = ""           # impact_statement / detail
    # purls or bom-refs; a statement with no identifiable products never
    # suppresses (reference only suppresses on a product match — a
    # products-less statement would otherwise drop the CVE for EVERY
    # package in the report)
    products: list[str] = field(default_factory=list)
    # product id -> subcomponent ids (OpenVEX: the statement applies to
    # vulnerabilities in these subcomponents of the product). A product
    # with no subcomponents applies to the product itself and everything
    # below it.
    subcomponents: dict[str, list[str]] = field(default_factory=dict)

    def _ids_match(self, vuln_id: str, aliases: list[str]) -> bool:
        return bool({vuln_id, *aliases} &
                    {self.vulnerability_id, *self.vuln_aliases})

    def matches(self, vuln_id: str, aliases: list[str], purl: str,
                bom_ref: str = "") -> bool:
        if not self._ids_match(vuln_id, aliases) or not self.products:
            return False
        return any(
            _purl_match(p, purl) or (bom_ref and p == bom_ref)
            for p in self.products
        )

    def matches_component(self, vuln_id: str, aliases: list[str],
                          node_purl: str, node_ref: str,
                          leaf_purl: str, leaf_ref: str) -> bool:
        """Reachability form (reference vex.go NotAffected(vuln, product,
        subComponent)): the statement's product must match the graph
        node, and when the statement carries subcomponents the vulnerable
        leaf must be one of them."""
        if not self._ids_match(vuln_id, aliases) or not self.products:
            return False
        for p in self.products:
            if not (_purl_match(p, node_purl)
                    or (node_ref and p == node_ref)):
                continue
            subs = self.subcomponents.get(p)
            if not subs:
                return True
            if any(_purl_match(s, leaf_purl)
                   or (leaf_ref and s == leaf_ref) for s in subs):
                return True
        return False


@dataclass
class VexDocument:
    source: str = ""
    statements: list[VexStatement] = field(default_factory=list)


def _purl_match(pattern: str, purl: str) -> bool:
    """PURL containment: pattern matches when all its set fields equal
    the target's (reference pkg/purl Match semantics)."""
    if not purl:
        return False
    if pattern == purl:
        return True
    if not pattern.startswith("pkg:"):
        return False  # bom-ref style identifier, not a purl
    try:
        a = parse_purl(pattern)
        b = parse_purl(purl)
    except Exception:
        return False
    if a.type != b.type:
        return False
    if a.namespace and a.namespace != b.namespace:
        return False
    if a.name and a.name != b.name:
        return False
    if a.version and a.version != b.version:
        return False
    for k, v in (a.qualifiers or {}).items():
        if (b.qualifiers or {}).get(k) != v:
            return False
    return True


# ------------------------------------------------------------ decoders


def _decode_openvex(doc: dict, source: str) -> VexDocument:
    out = VexDocument(source=source)
    for st in doc.get("statements") or []:
        vuln = st.get("vulnerability") or {}
        vid = vuln.get("name") or vuln.get("@id", "")
        aliases = [str(a) for a in vuln.get("aliases") or []]
        products = []
        subcomponents: dict[str, list[str]] = {}
        for p in st.get("products") or []:
            pid = p.get("@id", "") if isinstance(p, dict) else str(p)
            if pid:
                products.append(pid)
            subs = []
            for sub in (p.get("subcomponents") or []
                        if isinstance(p, dict) else []):
                sid = sub.get("@id", "") if isinstance(sub, dict) \
                    else str(sub)
                if sid:
                    subs.append(sid)
            if pid and subs:
                subcomponents[pid] = subs
        out.statements.append(VexStatement(
            vulnerability_id=vid,
            vuln_aliases=aliases,
            status=st.get("status", ""),
            justification=st.get("justification", ""),
            impact=st.get("impact_statement", ""),
            products=products,
            subcomponents=subcomponents,
        ))
    return out


_CDX_STATE = {
    "not_affected": STATUS_NOT_AFFECTED,
    "exploitable": STATUS_AFFECTED,
    "resolved": STATUS_FIXED,
    "resolved_with_pedigree": STATUS_FIXED,
    "in_triage": STATUS_UNDER_INVESTIGATION,
    "false_positive": STATUS_NOT_AFFECTED,
}


def _decode_cyclonedx(doc: dict, source: str) -> VexDocument:
    out = VexDocument(source=source)
    for v in doc.get("vulnerabilities") or []:
        analysis = v.get("analysis") or {}
        status = _CDX_STATE.get(analysis.get("state", ""), "")
        products = [
            a.get("ref", "") for a in v.get("affects") or []
            if isinstance(a, dict) and a.get("ref")
        ]
        out.statements.append(VexStatement(
            vulnerability_id=v.get("id", ""),
            status=status,
            justification=analysis.get("justification", ""),
            impact=analysis.get("detail", ""),
            products=products,
        ))
    return out


def _decode_csaf(doc: dict, source: str) -> VexDocument:
    out = VexDocument(source=source)
    purl_by_product = _csaf_product_purls(doc.get("product_tree") or {})

    def expand(ids) -> list[str]:
        purls = []
        for pid in ids or []:
            purls.extend(purl_by_product.get(pid, []))
        return purls

    for v in doc.get("vulnerabilities") or []:
        vid = v.get("cve") or (v.get("ids") or [{}])[0].get("text", "")
        ps = v.get("product_status") or {}
        just = ""
        for flag in v.get("flags") or []:
            just = flag.get("label", "") or just
        for status, key in (
            (STATUS_NOT_AFFECTED, "known_not_affected"),
            (STATUS_FIXED, "fixed"),
            (STATUS_AFFECTED, "known_affected"),
            (STATUS_UNDER_INVESTIGATION, "under_investigation"),
        ):
            ids = ps.get(key)
            if ids:
                out.statements.append(VexStatement(
                    vulnerability_id=vid, status=status,
                    justification=just, products=expand(ids),
                ))
    return out


def _csaf_product_purls(tree: dict) -> dict[str, list[str]]:
    """product_id -> purls, from product_tree branches + relationships."""
    out: dict[str, list[str]] = {}

    def walk(branch):
        if isinstance(branch, dict):
            prod = branch.get("product")
            if isinstance(prod, dict):
                pid = prod.get("product_id", "")
                helper = (prod.get("product_identification_helper")
                          or {})
                purl = helper.get("purl", "")
                if pid and purl:
                    out.setdefault(pid, []).append(purl)
            for b in branch.get("branches") or []:
                walk(b)

    for b in tree.get("branches") or []:
        walk(b)
    # relationships compose products; inherit component purls
    for rel in tree.get("relationships") or []:
        full = (rel.get("full_product_name") or {}).get("product_id", "")
        ref = rel.get("product_reference", "")
        if full and ref in out:
            out.setdefault(full, []).extend(out[ref])
    return out


def load_vex(path: str) -> VexDocument:
    """Sniff the format and decode (reference pkg/vex/document.go)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if "statements" in doc and "@context" in doc:
        return _decode_openvex(doc, path)
    if doc.get("bomFormat") == "CycloneDX":
        return _decode_cyclonedx(doc, path)
    category = (doc.get("document") or {}).get("category", "")
    if category.startswith("csaf"):
        return _decode_csaf(doc, path)
    raise ValueError(f"unrecognized VEX format in {path}")


# ------------------------------------------------------------ filtering


@dataclass
class _Node:
    """One component in the report's dependency graph."""

    purl: str = ""
    ref: str = ""
    parents: list[str] = field(default_factory=list)
    root: bool = False


def _component_graph(report: Report) -> dict[str, _Node]:
    """Report -> child-to-parents component graph (the reference builds
    the same shape through the SBOM encoder, vex.go:75-78): package
    `depends_on` edges point downward, so each dependency records its
    dependents as parents; packages nobody depends on hang off a root
    node carrying the artifact's identity (image purl when present)."""
    nodes: dict[str, _Node] = {}
    root_purl = ""
    md = getattr(report, "metadata", None)
    if md is not None and getattr(md, "repo_digests", None):
        # pkg:oci purl of the scanned image (reference purl.TypeOCI)
        dig = md.repo_digests[0]
        if "@" in dig:
            name, digest = dig.rsplit("@", 1)
            root_purl = (f"pkg:oci/{name.rsplit('/', 1)[-1]}@{digest}"
                         f"?repository_url={name}")
    nodes["__root__"] = _Node(purl=root_purl, ref=report.artifact_name,
                              root=True)
    for res in report.results:
        key_of: dict[str, str] = {}
        for p in res.packages:
            uid = p.identifier.uid or p.identifier.purl or \
                f"{res.target}:{p.id}"
            key_of[p.id] = uid
            nodes.setdefault(uid, _Node(
                purl=p.identifier.purl,
                ref=p.identifier.bom_ref or ""))
        has_parent: set[str] = set()
        for p in res.packages:
            uid = key_of[p.id]
            for dep in p.depends_on:
                child = key_of.get(dep)
                if child is not None:
                    nodes[child].parents.append(uid)
                    has_parent.add(child)
        for p in res.packages:
            uid = key_of[p.id]
            if uid not in has_parent and \
                    "__root__" not in nodes[uid].parents:
                nodes[uid].parents.append("__root__")
    return nodes


def filter_report_vex(report: Report, vex_sources: list) -> int:
    """Suppress findings asserted not_affected/fixed; returns the number
    suppressed. Suppressed entries are kept on the result as modified
    findings (rendered under ExperimentalModifiedFindings).

    Suppression is reachability-aware (reference vex.go reachRoot): a
    statement may target an ANCESTOR product (e.g. the container image or
    an aggregate package) with the vulnerable package as subcomponent,
    and a finding is only suppressed when every dependency path from the
    vulnerable component to the root is covered by a statement."""
    graph = _component_graph(report)
    total = 0
    for res in report.results:
        total += _filter_result(res, vex_sources, graph)
    return total


def _candidates(src, vuln, purl: str) -> list[tuple[str, VexStatement]]:
    """Statements of one source possibly relevant to (vuln, component)."""
    if hasattr(src, "candidate_statements"):
        return src.candidate_statements(purl)
    return [(src.source, st) for st in src.statements]


def _filter_result(res: Result, vex_sources: list,
                   graph: dict[str, _Node]) -> int:
    kept = []
    modified = getattr(res, "modified_findings", None) or []
    for v in res.vulnerabilities:
        leaf_purl = v.pkg_identifier.purl
        leaf_ref = v.pkg_identifier.bom_ref
        leaf_uid = v.pkg_identifier.uid or leaf_purl

        hit: list = []  # last matching (source, statement)

        def blocked(node: _Node) -> bool:
            for src in vex_sources:
                for source, st in _candidates(src, v, node.purl):
                    if st.status in _SUPPRESS and st.matches_component(
                        v.vulnerability_id, v.vendor_ids,
                        node.purl, node.ref, leaf_purl, leaf_ref,
                    ):
                        hit[:] = [source, st]
                        return True
            return False

        leaf = graph.get(leaf_uid) or _Node(purl=leaf_purl, ref=leaf_ref)

        def reaches_root(uid: str, node: _Node, seen: set) -> bool:
            if blocked(node):
                return False
            if node.root or not node.parents:
                return True
            seen.add(uid)
            for parent in node.parents:
                if parent in seen:
                    continue
                pn = graph.get(parent)
                if pn is None or reaches_root(parent, pn, seen):
                    return True
            return False

        if reaches_root(leaf_uid, leaf, set()) or not hit:
            # no path reached the root AND nothing was blocked: a
            # dependency cycle with no matching statement — keep the
            # finding (suppression requires an actual statement)
            kept.append(v)
            continue
        source, st = hit
        modified.append({
            "Type": "vulnerability",
            "Status": st.status,
            "Statement": st.justification or st.impact or "",
            "Source": source,
            "Finding": v.to_dict(),
        })
        _log.debug("vex suppressed", id=v.vulnerability_id,
                   status=st.status, source=source)
    suppressed = len(res.vulnerabilities) - len(kept)
    res.vulnerabilities = kept
    res.modified_findings = modified
    return suppressed
