"""VEX repositories (reference pkg/vex/repo + pkg/vex/repo.go
RepositorySet): named repositories configured in
`<cache>/vex/repository.yaml`, each cached under
`<cache>/vex/repositories/<name>/` with the VEX Repository Specification
layout — `vex-repository.json` manifest, `index.json` mapping
versionless package-URL ids to document locations, and the documents
themselves.

Statements are looked up lazily: a package's purl is stripped of
version/qualifiers/subpath and matched against the index of each enabled
repository in configuration order (first repository wins, reference
repo.go:109-139). Repository downloads go through the HTTP downloader
when a manifest URL is reachable; in offline environments the cached
copy is used as-is and absent repositories are skipped with a warning —
never an error (reference: errNoRepository is non-fatal).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from trivy_tpu.log import logger
from trivy_tpu.utils.purl import parse_purl

_log = logger("vex")

CONFIG_FILE = "repository.yaml"
MANIFEST_FILE = "vex-repository.json"
INDEX_FILE = "index.json"
DEFAULT_REPO_URL = "https://github.com/aquasecurity/vexhub"


def _version_sort_key(name: str):
    try:
        return (1, tuple(int(p) for p in name.split(".")))
    except ValueError:
        return (0, name)


@dataclass
class Repository:
    name: str = ""
    url: str = ""
    enabled: bool = True
    dir: str = ""

    def index(self) -> dict[str, dict] | None:
        """-> {package id: {"location": ..., "format": ...}} or None when
        the repository has never been cached. With several cached spec
        versions, the highest version's index wins (deterministic, never
        a stale directory os.walk happened to visit first)."""
        path = None
        for root, dirs, fns in os.walk(self.dir):
            # visit version dirs newest-first ("0.10" > "0.9" numerically)
            dirs.sort(key=_version_sort_key, reverse=True)
            if INDEX_FILE in fns:
                path = os.path.join(root, INDEX_FILE)
                break
        if path is None:
            return None
        try:
            with open(path, encoding="utf-8") as f:
                raw = json.load(f)
        except (OSError, ValueError) as exc:
            _log.warn("bad VEX repository index", repo=self.name,
                      err=str(exc))
            return None
        out = {}
        for p in raw.get("packages") or []:
            # the spec's JSON uses lowercase keys but Go unmarshals
            # case-insensitively, and published indexes use both
            pid = p.get("id") or p.get("ID")
            if pid:
                out[pid] = {
                    "location": p.get("location") or p.get("Location", ""),
                    "format": p.get("format") or p.get("Format", "openvex"),
                    "dir": os.path.dirname(path),
                }
        return out


def load_config(cache_dir: str) -> list[Repository]:
    """Read `<cache>/vex/repository.yaml`; a missing config yields the
    default repository entry, disabled unless cached (so zero-config
    offline scans don't warn)."""
    import yaml

    path = os.path.join(cache_dir, "vex", CONFIG_FILE)
    repos: list[Repository] = []
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            doc = yaml.safe_load(f) or {}
        for r in doc.get("repositories") or []:
            repos.append(Repository(
                name=r.get("name", ""), url=r.get("url", ""),
                enabled=bool(r.get("enabled", True))))
    else:
        repos.append(Repository(name="default", url=DEFAULT_REPO_URL))
    for r in repos:
        r.dir = os.path.join(cache_dir, "vex", "repositories", r.name)
    return [r for r in repos if r.enabled and r.name]


def _strip_purl(purl: str) -> str:
    """purl without version/qualifiers/subpath — the repository index
    key (reference repo.go:112-118)."""
    try:
        p = parse_purl(purl)
    except Exception:
        return purl
    base = f"pkg:{p.type}/"
    if p.namespace:
        base += f"{p.namespace}/"
    return base + p.name


class RepositorySet:
    """VEX source backed by the cached repositories: resolves statements
    per package purl through the repository indexes."""

    def __init__(self, cache_dir: str):
        self.repos: list[tuple[Repository, dict]] = []
        self._docs: dict[str, object] = {}
        for r in load_config(cache_dir):
            idx = r.index()
            if idx is None:
                _log.warn("VEX repository not found locally, skipping",
                          repo=r.name)
                continue
            self.repos.append((r, idx))
        if not self.repos:
            _log.warn("no available VEX repository found locally")

    def __bool__(self) -> bool:
        return bool(self.repos)

    def _load_doc(self, repo: Repository, entry: dict):
        from trivy_tpu.vex.vex import load_vex

        loc = entry["location"]
        key = f"{repo.name}:{loc}"
        if key not in self._docs:
            path = os.path.normpath(os.path.join(entry["dir"], loc))
            # documents must stay inside the repository cache dir
            # (prefix + separator: "corp-evil" must not pass as "corp")
            base = os.path.normpath(repo.dir)
            if not path.startswith(base + os.sep) and path != base:
                self._docs[key] = None
            else:
                try:
                    doc = load_vex(path)
                    doc.source = f"VEX repository: {repo.name} ({repo.url})"
                    self._docs[key] = doc
                except (OSError, ValueError) as exc:
                    _log.warn("failed to load VEX document",
                              repo=repo.name, location=loc, err=str(exc))
                    self._docs[key] = None
        return self._docs[key]

    def candidate_statements(self, purl: str) -> list[tuple[str, object]]:
        """-> [(source label, VexStatement)] for the component's purl.
        The first repository listing the package wins (precedence order,
        reference repo.go:120-139)."""
        if not purl:
            return []
        pid = _strip_purl(purl)
        for repo, idx in self.repos:
            entry = idx.get(pid)
            if entry is None:
                continue
            doc = self._load_doc(repo, entry)
            if doc is None:
                return []
            return [(doc.source, st) for st in doc.statements]
        return []
