"""OCI-attached VEX (reference pkg/vex/oci.go: openvex discovery over the
scanned image's package URL).

For a container-image report with repo digests, the registry is probed
for VEX attestations attached to the image digest:

1. the OCI 1.1 referrers API (`/v2/<repo>/referrers/<digest>`), filtered
   to OpenVEX artifact types
2. fallback: the cosign attachment tag (`sha256-<hex>.att`) used before
   referrers support

Attestation blobs may be raw OpenVEX JSON or DSSE envelopes wrapping an
in-toto statement whose predicate is the OpenVEX document; both decode
to the same VexDocument. Registry errors degrade to "no attestation" —
`--vex oci` must never fail a scan because a registry is unreachable.
"""

from __future__ import annotations

import base64
import json

from trivy_tpu.log import logger
from trivy_tpu.vex.vex import VexDocument, _decode_openvex

_log = logger("vex")

_VEX_TYPES = (
    "application/openvex+json",
    "application/vnd.openvex+json",
)
_DSSE_TYPES = (
    "application/vnd.dsse.envelope+json",
    "application/vnd.in-toto+json",
)


def _decode_attestation(raw: bytes, source: str) -> VexDocument | None:
    try:
        doc = json.loads(raw)
    except ValueError:
        return None
    # DSSE envelope -> in-toto statement -> predicate
    if isinstance(doc, dict) and "payload" in doc:
        try:
            doc = json.loads(base64.b64decode(doc["payload"]))
        except (ValueError, TypeError):
            return None
    if isinstance(doc, dict) and "predicate" in doc:
        doc = doc["predicate"]
    if isinstance(doc, dict) and "statements" in doc:
        return _decode_openvex(doc, source)
    return None


def load_oci_vex(report) -> VexDocument | None:
    """-> the image's attached VEX document, or None (absent artifact
    type / digests / registry / attestation)."""
    md = getattr(report, "metadata", None)
    if getattr(report, "artifact_type", "") != "container_image" or \
            md is None or not getattr(md, "repo_digests", None):
        _log.warn("'--vex oci' only applies to registry container images")
        return None
    ref = md.repo_digests[0]
    try:
        return _fetch_for_digest(ref)
    except Exception as exc:
        _log.warn("VEX attestation lookup failed", ref=ref, err=str(exc))
        return None


def _fetch_for_digest(repo_digest: str) -> VexDocument | None:
    from trivy_tpu.artifact.image_source import (
        RegistryClient,
        parse_reference,
    )

    name, digest = repo_digest.rsplit("@", 1)
    registry, repository, _tag, _d = parse_reference(name)
    client = RegistryClient(registry)
    source = f"VEX attestation in OCI registry ({repo_digest})"

    # OCI 1.1 referrers API
    for m in _referrers(client, repository, digest):
        if m.get("artifactType") in _VEX_TYPES or any(
            layer.get("mediaType") in _VEX_TYPES + _DSSE_TYPES
            for layer in m.get("layers", [])
        ):
            doc = _fetch_manifest_vex(client, repository,
                                      m.get("digest", ""), source)
            if doc is not None:
                return doc
    # cosign attachment tag fallback
    algo, _, hexd = digest.partition(":")
    att_tag = f"{algo}-{hexd}.att"
    try:
        manifest, _ = client.manifest(repository, att_tag)
    except Exception:
        _log.debug("no VEX attestation found", repo=repository)
        return None
    for layer in manifest.get("layers", []):
        raw = client.blob(repository, layer.get("digest", ""))
        doc = _decode_attestation(raw, source)
        if doc is not None:
            return doc
    return None


def _referrers(client, repository: str, digest: str) -> list[dict]:
    try:
        body, _headers = client._authed_get(
            f"/v2/{repository}/referrers/{digest}",
            "application/vnd.oci.image.index.v1+json",
            repository,
        )
        index = json.loads(body)
        return index.get("manifests", []) or []
    except Exception:
        return []


def _fetch_manifest_vex(client, repository: str, digest: str,
                        source: str) -> VexDocument | None:
    if not digest:
        return None
    try:
        manifest, _ = client.manifest(repository, digest)
    except Exception:
        return None
    for layer in manifest.get("layers", []):
        try:
            raw = client.blob(repository, layer.get("digest", ""))
        except Exception:
            continue
        doc = _decode_attestation(raw, source)
        if doc is not None:
            return doc
    return None
