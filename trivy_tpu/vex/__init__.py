"""VEX (Vulnerability Exploitability eXchange) suppression
(reference pkg/vex): OpenVEX, CycloneDX VEX, and CSAF documents filter
detected vulnerabilities whose status a vendor has asserted."""

from trivy_tpu.vex.vex import (  # noqa: F401
    VexDocument,
    VexStatement,
    filter_report_vex,
    load_vex,
)
