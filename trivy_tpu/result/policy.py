"""--ignore-policy: a user policy deciding which findings to drop
(reference pkg/result/filter.go applyPolicy, which evaluates an OPA Rego
policy with `package trivy; ignore { ... }` per finding).

This framework's check-engine formats stand in for Rego (the same
substitution as custom misconfig checks, iac/engine.py):

- YAML policy: ``ignore:`` is a list of condition objects in the check
  DSL, evaluated over the finding's report-JSON document; any matching
  condition drops the finding::

      ignore:
        - path: VulnerabilityID
          equals: CVE-2022-1234
        - all:
            - path: Severity
              equals: LOW
            - path: PkgName
              starts_with: internal-

- Python policy: a module defining ``ignore(finding) -> bool`` (explicit
  opt-in to code execution, like Python checks).
"""

from __future__ import annotations

from trivy_tpu.log import logger

_log = logger("policy")


class PolicyError(Exception):
    pass


class IgnorePolicy:
    def __init__(self, fn):
        self._fn = fn

    def ignored(self, finding_doc: dict) -> bool:
        try:
            return bool(self._fn(finding_doc))
        except Exception as exc:
            _log.warn("ignore policy error", err=str(exc))
            return False


def load_ignore_policy(path: str) -> IgnorePolicy:
    if path.endswith(".rego"):
        return _load_rego(path)
    if path.endswith((".yaml", ".yml")):
        return _load_yaml(path)
    if path.endswith(".py"):
        return _load_python(path)
    raise PolicyError(
        f"unsupported ignore policy {path!r} (want .rego/.yaml/.py)")


def _load_rego(path: str) -> IgnorePolicy:
    """Reference-compatible Rego ignore policy: `package trivy` with
    `ignore` rules evaluated per finding (pkg/result/filter.go
    applyPolicy; examples/ignore-policies/*.rego run unmodified)."""
    from trivy_tpu.iac.rego import Evaluator, RegoError, parse_module

    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        module = parse_module(src)
    except RegoError as exc:
        raise PolicyError(f"{path}: {exc}")
    query = "data." + ".".join(module.package) + ".ignore"

    def fn(finding: dict) -> bool:
        return Evaluator([module], input=finding).query(query) is True

    return IgnorePolicy(fn)


def _load_yaml(path: str) -> IgnorePolicy:
    import yaml

    from trivy_tpu.iac.engine import (
        CheckLoadError,
        _eval_condition,
        _validate_condition,
    )

    with open(path, encoding="utf-8") as f:
        doc = yaml.safe_load(f) or {}
    conds = doc.get("ignore")
    if not isinstance(conds, list) or not conds:
        raise PolicyError(f"{path}: 'ignore' must be a list of conditions")
    try:
        for c in conds:
            _validate_condition(c)
    except CheckLoadError as exc:
        raise PolicyError(f"{path}: {exc}")

    def fn(finding: dict) -> bool:
        return any(_eval_condition(c, finding) for c in conds)

    return IgnorePolicy(fn)


def _load_python(path: str) -> IgnorePolicy:
    import importlib.util

    spec = importlib.util.spec_from_file_location("trivy_ignore_policy",
                                                  path)
    if spec is None or spec.loader is None:
        raise PolicyError(f"cannot load {path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn = getattr(mod, "ignore", None)
    if not callable(fn):
        raise PolicyError(f"{path} defines no ignore(finding) function")
    return IgnorePolicy(fn)
