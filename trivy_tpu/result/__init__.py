from trivy_tpu.result.filter import filter_report

__all__ = ["filter_report"]
