""".trivyignore parsing (reference pkg/result/ignore.go): plain-text (one
finding ID per line, '#' comments) and YAML (per-class entries with id,
paths, purls, expired_at, statement)."""

from __future__ import annotations

import datetime
import fnmatch
import os
from dataclasses import dataclass, field


@dataclass
class IgnoreFinding:
    id: str = ""
    paths: list[str] = field(default_factory=list)
    purls: list[str] = field(default_factory=list)
    expired_at: str = ""  # ISO date
    statement: str = ""

    def expired(self, today: datetime.date) -> bool:
        if not self.expired_at:
            return False
        try:
            return datetime.date.fromisoformat(self.expired_at) < today
        except ValueError:
            return False

    def matches(self, finding_id: str, path: str, purl: str,
                today: datetime.date) -> bool:
        if self.expired(today):
            return False
        if self.id and self.id != finding_id:
            return False
        if self.paths and not any(fnmatch.fnmatch(path, p) for p in self.paths):
            return False
        if self.purls and not any(purl.startswith(p) for p in self.purls):
            return False
        return True


@dataclass
class IgnoreConfig:
    vulnerabilities: list[IgnoreFinding] = field(default_factory=list)
    misconfigurations: list[IgnoreFinding] = field(default_factory=list)
    secrets: list[IgnoreFinding] = field(default_factory=list)
    licenses: list[IgnoreFinding] = field(default_factory=list)

    @property
    def empty(self) -> bool:
        return not (self.vulnerabilities or self.misconfigurations
                    or self.secrets or self.licenses)

    def section(self, kind: str) -> list[IgnoreFinding]:
        return getattr(self, kind)

    def ignored(self, kind: str, finding_id: str, path: str = "",
                purl: str = "", today: datetime.date | None = None) -> bool:
        today = today or datetime.date.today()
        return any(
            f.matches(finding_id, path, purl, today)
            for f in self.section(kind)
        )


def load_ignore_file(path: str) -> IgnoreConfig:
    """Load .trivyignore (plain) or .trivyignore.yaml."""
    cfg = IgnoreConfig()
    if not path or not os.path.exists(path):
        return cfg
    if path.endswith((".yaml", ".yml")):
        import yaml

        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        for kind, key in [
            ("vulnerabilities", "vulnerabilities"),
            ("misconfigurations", "misconfigurations"),
            ("secrets", "secrets"),
            ("licenses", "licenses"),
        ]:
            for item in doc.get(key) or []:
                getattr(cfg, kind).append(IgnoreFinding(
                    id=item.get("id", ""),
                    paths=item.get("paths", []) or [],
                    purls=item.get("purls", []) or [],
                    expired_at=str(item.get("expired_at", "") or ""),
                    statement=item.get("statement", ""),
                ))
        return cfg
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            entry = IgnoreFinding(id=parts[0])
            # "exp:2024-01-01" suffix support
            for p in parts[1:]:
                if p.startswith("exp:"):
                    entry.expired_at = p[4:]
            # plain-file entries apply to all finding kinds
            cfg.vulnerabilities.append(entry)
            cfg.misconfigurations.append(entry)
            cfg.secrets.append(entry)
            cfg.licenses.append(entry)
    return cfg
