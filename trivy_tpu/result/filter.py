"""Post-scan result filtering (reference pkg/result/filter.go:37-61):
severity selection, ignore-status, .trivyignore entries, then stable
sorting."""

from __future__ import annotations

from trivy_tpu.result.ignore import IgnoreConfig
from trivy_tpu.types.enums import Severity, Status
from trivy_tpu.types.report import Report, Result


def filter_report(
    report: Report,
    severities: list[Severity] | None = None,
    ignore_statuses: list[str] | None = None,
    ignore_config: IgnoreConfig | None = None,
    include_non_failures: bool = False,
    ignore_unfixed: bool = False,
    ignore_policy=None,
) -> Report:
    for res in report.results:
        filter_result(
            res, severities, ignore_statuses, ignore_config,
            include_non_failures, ignore_unfixed, ignore_policy,
        )
    return report


def filter_result(
    res: Result,
    severities=None,
    ignore_statuses=None,
    ignore_config: IgnoreConfig | None = None,
    include_non_failures: bool = False,
    ignore_unfixed: bool = False,
    ignore_policy=None,
) -> None:
    sev_names = {str(s) for s in severities} if severities else None
    statuses = set(ignore_statuses or [])
    ign = ignore_config or IgnoreConfig()

    def sev_ok(s: str) -> bool:
        return sev_names is None or s in sev_names

    def policy_ok(finding) -> bool:
        # --ignore-policy (reference filter.go applyPolicy): the policy
        # sees the finding's report-JSON document
        if ignore_policy is None:
            return True
        return not ignore_policy.ignored(finding.to_dict())

    res.vulnerabilities = [
        v
        for v in res.vulnerabilities
        if sev_ok(str(v.severity))
        and (not statuses or v.status.label not in statuses)
        # --ignore-unfixed (reference pkg/result/filter.go): drop
        # findings with no fix available
        and not (ignore_unfixed and not v.fixed_version)
        and not ign.ignored(
            "vulnerabilities", v.vulnerability_id,
            path=v.pkg_path or res.target, purl=v.pkg_identifier.purl,
        )
        and policy_ok(v)
    ]
    res.vulnerabilities.sort(key=lambda v: v.sort_key())

    res.misconfigurations = [
        m
        for m in res.misconfigurations
        if (m.status == "FAIL" or include_non_failures)
        and sev_ok(m.severity)
        and not ign.ignored("misconfigurations", m.id, path=res.target)
        and policy_ok(m)
    ]
    if res.misconf_summary is not None:
        res.misconf_summary.failures = sum(
            1 for m in res.misconfigurations if m.status == "FAIL"
        )

    res.secrets = [
        s
        for s in res.secrets
        if sev_ok(s.severity)
        and not ign.ignored("secrets", s.rule_id, path=res.target)
        and policy_ok(s)
    ]
    res.licenses = [
        l
        for l in res.licenses
        if sev_ok(l.severity)
        and not ign.ignored("licenses", l.name, path=res.target)
        and policy_ok(l)
    ]
