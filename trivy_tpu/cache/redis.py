"""Redis cache backend (reference pkg/cache/redis.go): the same
ArtifactCache/LocalArtifactCache surface over a shared Redis, keys
prefixed `fanal::artifact::…` / `fanal::blob::…` exactly like the
reference so caches interoperate across scanners.

No redis client library is baked into this image, so the transport is a
minimal RESP2 implementation over a stdlib socket (optionally wrapped in
TLS with CA/client-cert options, reference redis.go:57-100).  Only the
five commands the cache needs are used: GET/SET/EXISTS/DEL/PING.
"""

from __future__ import annotations

import json
import socket
import ssl
import threading

from trivy_tpu.analysis.witness import make_lock
import urllib.parse
from dataclasses import asdict

REDIS_PREFIX = "fanal"


class RedisError(Exception):
    pass


class RespClient:
    """Minimal RESP2 client: one socket, thread-safe command execution."""

    def __init__(self, host: str, port: int, *, username: str = "",
                 password: str = "", db: int = 0, tls: bool = False,
                 ca_cert: str = "", cert: str = "", key: str = "",
                 insecure: bool = False, timeout: float = 10.0):
        sock = socket.create_connection((host, port), timeout=timeout)
        if tls:
            # No --redis-ca means "verify against system roots", never
            # "don't verify"; disabling verification requires an explicit
            # insecure opt-in (reference redis.go errors without CA+cert+key).
            ctx = ssl.create_default_context(cafile=ca_cert or None)
            if insecure:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if cert and key:
                ctx.load_cert_chain(cert, key)
            sock = ctx.wrap_socket(sock, server_hostname=host)
        self._sock = sock
        self._buf = b""
        self._lock = make_lock("cache.redis._lock")
        if password:
            args = ["AUTH", username, password] if username \
                else ["AUTH", password]
            self.execute(*args)
        if db:
            self.execute("SELECT", str(db))
        self.execute("PING")  # validate the connection (and auth) upfront

    # --------------------------------------------------------- protocol

    def _send(self, *args: str | bytes) -> None:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(b), b))
        self._sock.sendall(b"".join(out))

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise RedisError("connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise RedisError("connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n + 2:]
        return data

    def _read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RedisError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            return None if n == -1 else self._read_exact(n)
        if kind == b"*":
            n = int(rest)
            return None if n == -1 else [self._read_reply()
                                         for _ in range(n)]
        raise RedisError(f"unexpected reply type {line!r}")

    def execute(self, *args):
        with self._lock:
            self._send(*args)
            return self._read_reply()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def parse_redis_url(url: str) -> dict:
    """redis://[user:pass@]host:port[/db]"""
    u = urllib.parse.urlparse(url)
    if u.scheme not in ("redis", "rediss"):
        raise RedisError(f"unsupported redis URL scheme {u.scheme!r}")
    db = 0
    if u.path and u.path.strip("/"):
        try:
            db = int(u.path.strip("/"))
        except ValueError:
            raise RedisError(f"invalid redis db in URL: {u.path!r}")
    return {
        "host": u.hostname or "localhost",
        "port": u.port or 6379,
        "username": u.username or "",
        "password": u.password or "",
        "db": db,
        "tls": u.scheme == "rediss",
    }


class RedisCache:
    """ArtifactCache + LocalArtifactCache over Redis
    (reference pkg/cache/redis.go:102-210)."""

    def __init__(self, backend: str, *, ca_cert: str = "", cert: str = "",
                 key: str = "", tls: bool = False, ttl: int = 0,
                 insecure: bool = False, client: RespClient | None = None):
        if client is not None:
            self._client = client
        else:
            opts = parse_redis_url(backend)
            opts["tls"] = opts["tls"] or tls
            self._client = RespClient(
                opts["host"], opts["port"], username=opts["username"],
                password=opts["password"], db=opts["db"], tls=opts["tls"],
                ca_cert=ca_cert, cert=cert, key=key, insecure=insecure)
        self.ttl = ttl

    @staticmethod
    def _artifact_key(artifact_id: str) -> str:
        return f"{REDIS_PREFIX}::artifact::{artifact_id}"

    @staticmethod
    def _blob_key(blob_id: str) -> str:
        return f"{REDIS_PREFIX}::blob::{blob_id}"

    def _set(self, key: str, doc: dict) -> None:
        args = ["SET", key, json.dumps(doc, default=str)]
        if self.ttl:
            args += ["EX", str(self.ttl)]
        self._client.execute(*args)

    def _get(self, key: str) -> dict:
        raw = self._client.execute("GET", key)
        if raw is None:
            return {}
        return json.loads(raw)

    # ---------------------------------------------------- ArtifactCache

    def put_artifact(self, artifact_id: str, info) -> None:
        doc = info if isinstance(info, dict) else asdict(info)
        self._set(self._artifact_key(artifact_id), doc)

    def put_blob(self, blob_id: str, blob) -> None:
        doc = blob if isinstance(blob, dict) else asdict(blob)
        self._set(self._blob_key(blob_id), doc)

    def missing_blobs(self, artifact_id: str,
                      blob_ids: list[str]) -> tuple[bool, list[str]]:
        missing = [
            bid for bid in blob_ids
            if not self._client.execute("EXISTS", self._blob_key(bid))
        ]
        missing_artifact = not self._client.execute(
            "EXISTS", self._artifact_key(artifact_id))
        return missing_artifact, missing

    def delete_blobs(self, blob_ids: list[str]) -> None:
        if blob_ids:
            self._client.execute(
                "DEL", *[self._blob_key(b) for b in blob_ids])

    # ----------------------------------------------- LocalArtifactCache

    def get_artifact(self, artifact_id: str) -> dict:
        return self._get(self._artifact_key(artifact_id))

    def get_blob(self, blob_id: str) -> dict:
        return self._get(self._blob_key(blob_id))

    def clear(self) -> None:
        # delete only our keys, not the whole redis (redis.go:194-210)
        cursor = "0"
        while True:
            reply = self._client.execute(
                "SCAN", cursor, "MATCH", f"{REDIS_PREFIX}::*", "COUNT", "100")
            cursor = reply[0].decode() if isinstance(reply[0], bytes) \
                else str(reply[0])
            keys = reply[1] or []
            if keys:
                self._client.execute("DEL", *keys)
            if cursor == "0":
                break

    def close(self) -> None:
        self._client.close()
