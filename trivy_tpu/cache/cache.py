"""Analysis-result cache.

Interfaces mirror the reference (pkg/cache/cache.go:16-43):
- ArtifactCache (write): put_artifact / put_blob / missing_blobs
- LocalArtifactCache (read): get_artifact / get_blob
Backends: in-memory and filesystem JSON (the reference's BoltDB fs cache,
pkg/cache/fs.go, re-expressed as one JSON file per key). The cache IS the
checkpoint/resume mechanism: blob keys are content+analyzer-version hashes,
so re-scans skip unchanged layers (reference pkg/cache/key.go:19-69).
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import asdict

from trivy_tpu.types.artifact import ArtifactInfo, BlobInfo


def cache_key(
    base: str,
    analyzer_versions: dict[str, int] | None = None,
    hook_versions: dict[str, int] | None = None,
    skip_files: list[str] | None = None,
    skip_dirs: list[str] | None = None,
    patterns: list[str] | None = None,
    policy: list[str] | None = None,
) -> str:
    """Derive a cache key from a base ID + everything that can change the
    analysis result (reference pkg/cache/key.go:19-69)."""
    h = hashlib.sha256()
    payload = {
        "artifact": base,
        "analyzerVersions": analyzer_versions or {},
        "hookVersions": hook_versions or {},
        "skipFiles": skip_files or [],
        "skipDirs": skip_dirs or [],
        "patterns": patterns or [],
        "policy": policy or [],
    }
    h.update(json.dumps(payload, sort_keys=True).encode())
    return "sha256:" + h.hexdigest()


class MemoryCache:
    """reference pkg/cache/memory.go"""

    def __init__(self):
        self._artifacts: dict[str, dict] = {}
        self._blobs: dict[str, dict] = {}

    # write (ArtifactCache)
    def put_artifact(self, artifact_id: str, info: ArtifactInfo | dict) -> None:
        self._artifacts[artifact_id] = _as_dict(info)

    def put_blob(self, blob_id: str, blob: BlobInfo | dict) -> None:
        self._blobs[blob_id] = _as_dict(blob)

    def missing_blobs(self, artifact_id: str, blob_ids: list[str]):
        missing_artifact = artifact_id not in self._artifacts
        missing = [b for b in blob_ids if b not in self._blobs]
        return missing_artifact, missing

    # read (LocalArtifactCache)
    def get_artifact(self, artifact_id: str) -> dict:
        return self._artifacts.get(artifact_id, {})

    def get_blob(self, blob_id: str) -> dict:
        return self._blobs.get(blob_id, {})

    def delete_blobs(self, blob_ids: list[str]) -> None:
        for b in blob_ids:
            self._blobs.pop(b, None)

    def clear(self) -> None:
        self._artifacts.clear()
        self._blobs.clear()

    def close(self) -> None:
        pass


class FSCache(MemoryCache):
    """Filesystem-backed cache under <root>/fanal (one JSON per key),
    mirroring the role of the reference's BoltDB file cache."""

    def __init__(self, root: str):
        super().__init__()
        self.root = os.path.join(root, "fanal")
        os.makedirs(os.path.join(self.root, "artifact"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "blob"), exist_ok=True)

    def _path(self, bucket: str, key: str) -> str:
        safe = key.replace("/", "_").replace(":", "_")
        return os.path.join(self.root, bucket, safe + ".json")

    def put_artifact(self, artifact_id: str, info) -> None:
        with open(self._path("artifact", artifact_id), "w") as f:
            json.dump(_as_dict(info), f)

    def put_blob(self, blob_id: str, blob) -> None:
        with open(self._path("blob", blob_id), "w") as f:
            json.dump(_as_dict(blob), f)

    def missing_blobs(self, artifact_id: str, blob_ids: list[str]):
        missing_artifact = not os.path.exists(self._path("artifact", artifact_id))
        missing = [
            b for b in blob_ids if not os.path.exists(self._path("blob", b))
        ]
        return missing_artifact, missing

    def get_artifact(self, artifact_id: str) -> dict:
        return self._read("artifact", artifact_id)

    def get_blob(self, blob_id: str) -> dict:
        return self._read("blob", blob_id)

    def _read(self, bucket: str, key: str) -> dict:
        p = self._path(bucket, key)
        if not os.path.exists(p):
            return {}
        with open(p) as f:
            return json.load(f)

    def delete_blobs(self, blob_ids: list[str]) -> None:
        for b in blob_ids:
            p = self._path("blob", b)
            if os.path.exists(p):
                os.unlink(p)

    def clear(self) -> None:
        import shutil

        shutil.rmtree(self.root, ignore_errors=True)
        os.makedirs(os.path.join(self.root, "artifact"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "blob"), exist_ok=True)


ArtifactCache = MemoryCache  # interface alias


def _as_dict(obj) -> dict:
    if isinstance(obj, dict):
        return obj
    # dataclass blobs serialize structurally (not report-JSON): keep all
    # fields so the applier round-trips exactly
    return asdict(obj)
