"""Analysis-result cache.

Interfaces mirror the reference (pkg/cache/cache.go:16-43):
- ArtifactCache (write): put_artifact / put_blob / missing_blobs
- LocalArtifactCache (read): get_artifact / get_blob
Backends: in-memory and filesystem JSON (the reference's BoltDB fs cache,
pkg/cache/fs.go, re-expressed as one JSON file per key). The cache IS the
checkpoint/resume mechanism: blob keys are content+analyzer-version hashes,
so re-scans skip unchanged layers (reference pkg/cache/key.go:19-69).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import re
import threading

from trivy_tpu.analysis.witness import make_lock
from dataclasses import asdict

from trivy_tpu.durability import atomic
from trivy_tpu.log import logger
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.types.artifact import ArtifactInfo, BlobInfo

_log = logger("cache")


def corrupt_evictions() -> int:
    """Corrupt-entry evictions across every FSCache in the process
    (the trivy_tpu_cache_corrupt_total counter, kept as a function for
    historical callers)."""
    return int(obs_metrics.CACHE_CORRUPT.value())


def _count_corrupt_eviction() -> None:
    obs_metrics.CACHE_CORRUPT.inc()


def cache_key(
    base: str,
    analyzer_versions: dict[str, int] | None = None,
    hook_versions: dict[str, int] | None = None,
    skip_files: list[str] | None = None,
    skip_dirs: list[str] | None = None,
    patterns: list[str] | None = None,
    policy: list[str] | None = None,
) -> str:
    """Derive a cache key from a base ID + everything that can change the
    analysis result (reference pkg/cache/key.go:19-69)."""
    h = hashlib.sha256()
    payload = {
        "artifact": base,
        "analyzerVersions": analyzer_versions or {},
        "hookVersions": hook_versions or {},
        "skipFiles": skip_files or [],
        "skipDirs": skip_dirs or [],
        "patterns": patterns or [],
        "policy": policy or [],
    }
    h.update(json.dumps(payload, sort_keys=True).encode())
    return "sha256:" + h.hexdigest()


class MemoryCache:
    """reference pkg/cache/memory.go"""

    def __init__(self):
        self._artifacts: dict[str, dict] = {}
        self._blobs: dict[str, dict] = {}

    # write (ArtifactCache)
    def put_artifact(self, artifact_id: str, info: ArtifactInfo | dict) -> None:
        self._artifacts[artifact_id] = _as_dict(info)

    def put_blob(self, blob_id: str, blob: BlobInfo | dict) -> None:
        self._blobs[blob_id] = _as_dict(blob)

    def missing_blobs(self, artifact_id: str, blob_ids: list[str]):
        missing_artifact = artifact_id not in self._artifacts
        missing = [b for b in blob_ids if b not in self._blobs]
        return missing_artifact, missing

    # read (LocalArtifactCache)
    def get_artifact(self, artifact_id: str) -> dict:
        return self._artifacts.get(artifact_id, {})

    def get_blob(self, blob_id: str) -> dict:
        return self._blobs.get(blob_id, {})

    def delete_blobs(self, blob_ids: list[str]) -> None:
        for b in blob_ids:
            self._blobs.pop(b, None)

    def clear(self) -> None:
        self._artifacts.clear()
        self._blobs.clear()

    def close(self) -> None:
        pass


# filenames that need no mangling: short, and only chars every
# filesystem spells the same way
_SAFE_KEY_RX = re.compile(r"^[A-Za-z0-9._-]{1,200}$")


class FSCache(MemoryCache):
    """Filesystem-backed cache under <root>/fanal (one JSON per key),
    mirroring the role of the reference's BoltDB file cache.

    Durability contract (docs/durability.md): entries are written
    atomically (tmp+fsync+rename) with a sha256 checksum footer; a torn
    or bit-rotted entry is detected at read time, evicted, counted in
    trivy_tpu_cache_corrupt_total, and served as a cache miss — a
    corrupt cache can cost a re-scan, never a wrong or crashed one."""

    # verified docs carried from the missing_blobs integrity pass to the
    # get_* that follows in the same scan — bounds memory, saves the
    # second full read+hash+parse per entry on the hot path
    _STASH_CAP = 256

    def __init__(self, root: str):
        super().__init__()
        self.root = os.path.join(root, "fanal")
        os.makedirs(os.path.join(self.root, "artifact"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "blob"), exist_ok=True)
        atomic.sweep_stale_tmp(os.path.join(self.root, "artifact"))
        atomic.sweep_stale_tmp(os.path.join(self.root, "blob"))
        from collections import OrderedDict

        self._stash: "OrderedDict[tuple[str, str], dict]" = OrderedDict()
        self._stash_lock = make_lock("cache.cache._stash_lock")

    def _stash_put(self, bucket: str, key: str, doc: dict) -> None:
        with self._stash_lock:
            self._stash[(bucket, key)] = doc
            while len(self._stash) > self._STASH_CAP:
                self._stash.popitem(last=False)

    def _stash_pop(self, bucket: str, key: str) -> dict | None:
        with self._stash_lock:
            return self._stash.pop((bucket, key), None)

    def _path(self, bucket: str, key: str) -> str:
        """Collision-free key -> filename: safe keys keep their name,
        anything else is content-addressed by the sha256 of the FULL
        key (the old replace('/','_')/replace(':','_') mangling mapped
        'a/b' and 'a:b' to the same file)."""
        if _SAFE_KEY_RX.match(key):
            name = key
        else:
            name = "k" + hashlib.sha256(key.encode()).hexdigest()
        return os.path.join(self.root, bucket, name + ".json")

    def _legacy_path(self, bucket: str, key: str) -> str:
        """Pre-hashing scheme; still read so existing caches survive
        the upgrade (entries migrate to the new name on read)."""
        safe = key.replace("/", "_").replace(":", "_")
        return os.path.join(self.root, bucket, safe + ".json")

    def _write(self, bucket: str, key: str, doc: dict) -> None:
        self._stash_pop(bucket, key)  # never serve a superseded doc
        body = json.dumps(doc).encode()
        atomic.atomic_write(self._path(bucket, key), atomic.frame(body),
                            fault_site="cache.write")

    def put_artifact(self, artifact_id: str, info) -> None:
        self._write("artifact", artifact_id, _as_dict(info))

    def put_blob(self, blob_id: str, blob) -> None:
        self._write("blob", blob_id, _as_dict(blob))

    def _exists(self, bucket: str, key: str) -> bool:
        # integrity-verified, not a bare os.path.exists: a corrupt entry
        # must read as MISSING here so the caller re-analyzes the layer
        # now — otherwise analysis is skipped and the later get_blob
        # miss kills the very scan that discovered the corruption. The
        # verified doc is stashed so that get_blob/get_artifact does
        # not pay a second read+hash+parse for the same entry.
        doc = self._read(bucket, key)
        if not doc:
            return False
        self._stash_put(bucket, key, doc)
        return True

    def missing_blobs(self, artifact_id: str, blob_ids: list[str]):
        missing_artifact = not self._exists("artifact", artifact_id)
        missing = [b for b in blob_ids if not self._exists("blob", b)]
        return missing_artifact, missing

    def get_artifact(self, artifact_id: str) -> dict:
        doc = self._stash_pop("artifact", artifact_id)
        return doc if doc is not None else self._read("artifact", artifact_id)

    def get_blob(self, blob_id: str) -> dict:
        doc = self._stash_pop("blob", blob_id)
        return doc if doc is not None else self._read("blob", blob_id)

    def _read(self, bucket: str, key: str) -> dict:
        p = self._path(bucket, key)
        doc = self._read_file(p, key)
        if doc is not None:
            return doc
        legacy = self._legacy_path(bucket, key)
        if legacy != p:
            doc = self._read_file(legacy, key)
            if doc is not None:
                # migrate: rewrite under the collision-free name (with
                # checksum) so the shim is only paid once per entry
                self._write(bucket, key, doc)
                with contextlib.suppress(FileNotFoundError):
                    os.unlink(legacy)
                return doc
        return {}

    def _load(self, path: str, key: str) -> bytes | None:
        """Entry file -> checksum-verified body bytes; None = miss. A
        bad checksum self-heals here: evict + count + miss. (The frame
        marker contains a raw newline, which escaped JSON bodies can
        never contain — the footer split is unambiguous.)"""
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        except OSError as e:
            _log.warn("unreadable cache entry; treating as miss",
                      key=key, err=str(e))
            return None
        try:
            return atomic.unframe(raw)
        except atomic.CorruptEntry as e:
            self._evict_corrupt(path, key, e)
            return None

    def _evict_corrupt(self, path: str, key: str, err) -> None:
        _count_corrupt_eviction()
        _log.warn("corrupt cache entry evicted", key=key, err=str(err))
        with contextlib.suppress(FileNotFoundError):
            os.unlink(path)

    def _read_file(self, path: str, key: str) -> dict | None:
        """One entry file -> dict; None = miss. Corruption (bad
        checksum, truncated/invalid JSON) self-heals: evict + count +
        miss, instead of the old json.JSONDecodeError mid-scan."""
        body = self._load(path, key)
        if body is None:
            return None
        try:
            doc = json.loads(body)
            if not isinstance(doc, dict):
                raise ValueError("cache entry is not a JSON object")
            return doc
        except ValueError as e:
            self._evict_corrupt(path, key, e)
            return None

    def delete_blobs(self, blob_ids: list[str]) -> None:
        # concurrent scanners race on the same entries: suppress, don't
        # exists()-then-unlink (TOCTOU)
        for b in blob_ids:
            self._stash_pop("blob", b)
            for p in (self._path("blob", b), self._legacy_path("blob", b)):
                with contextlib.suppress(FileNotFoundError):
                    os.unlink(p)

    def clear(self) -> None:
        import shutil

        with self._stash_lock:
            self._stash.clear()
        shutil.rmtree(self.root, ignore_errors=True)
        os.makedirs(os.path.join(self.root, "artifact"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "blob"), exist_ok=True)


ArtifactCache = MemoryCache  # interface alias


def _as_dict(obj) -> dict:
    if isinstance(obj, dict):
        return obj
    # dataclass blobs serialize structurally (not report-JSON): keep all
    # fields so the applier round-trips exactly
    return asdict(obj)
