from trivy_tpu.cache.cache import (
    ArtifactCache,
    FSCache,
    MemoryCache,
    cache_key,
)

__all__ = ["ArtifactCache", "FSCache", "MemoryCache", "cache_key"]
