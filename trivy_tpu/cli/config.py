"""Layered option resolution (reference pkg/flag: CLI flag > env
TRIVY_TPU_* > config file trivy-tpu.yaml > default, realized there by
viper binding; here as argparse post-processing)."""

from __future__ import annotations

import os

from trivy_tpu.log import logger

_log = logger("config")

CONFIG_NAMES = ("trivy-tpu.yaml", "trivy.yaml")
ENV_PREFIX = "TRIVY_TPU_"


def _load_config_file(path: str | None) -> dict:
    import yaml

    candidates = [path] if path else list(CONFIG_NAMES)
    for p in candidates:
        if p and os.path.exists(p):
            with open(p, encoding="utf-8") as f:
                doc = yaml.safe_load(f) or {}
            if not isinstance(doc, dict):
                _log.warn("ignoring malformed config file", path=p)
                return {}
            _log.debug("loaded config file", path=p)
            return _flatten(doc)
    if path:
        raise FileNotFoundError(f"config file not found: {path}")
    return {}


def _flatten(doc: dict, prefix: str = "") -> dict:
    """scan: {skip-dirs: [...]} -> {"scan.skip-dirs": [...]}, and the
    leaf name alone is also addressable ("skip-dirs")."""
    out: dict = {}
    for k, v in doc.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        else:
            out[key] = v
            out.setdefault(k, v)
    return out


def _coerce(value, default):
    if isinstance(default, bool):
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if isinstance(default, int) and not isinstance(default, bool):
        return int(value)
    if isinstance(default, list):
        if isinstance(value, str):
            return [v for v in value.split(",") if v]
        return list(value)
    if isinstance(value, list):  # config list for a comma-joined flag
        return ",".join(str(v) for v in value)
    if isinstance(value, str) and value.startswith("~"):
        return os.path.expanduser(value)
    return value


def _all_defaults(parser) -> dict:
    """Defaults across the main parser AND every subparser (argparse's
    get_default only sees the top level)."""
    import argparse

    out: dict = {}
    stack = [parser]
    while stack:
        p = stack.pop()
        for a in p._actions:
            if isinstance(a, argparse._SubParsersAction):
                stack.extend(a.choices.values())
            elif a.dest and a.dest != "help":
                out.setdefault(a.dest, a.default)
    return out


def apply_layers(args, parser, argv: list[str]) -> None:
    """Overlay env + config-file values onto argparse defaults; values
    given on the command line always win. Raises ValueError on
    uncoercible env/config values (caught by main's error rendering)."""
    cfg = _load_config_file(getattr(args, "config", None))
    explicit = _explicit_dests(parser, argv)
    defaults = _all_defaults(parser)
    for dest, value in vars(args).copy().items():
        if dest in ("command", "config") or dest in explicit:
            continue
        if dest not in defaults or value != defaults[dest]:
            continue  # not a flag, or already non-default
        default = defaults[dest]
        env_key = ENV_PREFIX + dest.upper().replace("-", "_")
        flag_key = dest.replace("_", "-")
        try:
            if env_key in os.environ:
                setattr(args, dest, _coerce(os.environ[env_key], default))
            elif flag_key in cfg:
                setattr(args, dest, _coerce(cfg[flag_key], default))
        except (ValueError, TypeError) as exc:
            raise ValueError(
                f"invalid value for {flag_key!r} from environment/config: "
                f"{exc}"
            ) from exc


def _option_dests(parser) -> dict[str, str]:
    """Every option string (short and long) -> its dest, across all
    subparsers."""
    import argparse

    out: dict[str, str] = {}
    stack = [parser]
    while stack:
        p = stack.pop()
        for a in p._actions:
            if isinstance(a, argparse._SubParsersAction):
                stack.extend(a.choices.values())
                continue
            for opt in a.option_strings:
                out[opt] = a.dest
    return out


def _explicit_dests(parser, argv: list[str]) -> set[str]:
    """Dests the user actually typed, covering both --long and -x
    short spellings."""
    by_opt = _option_dests(parser)
    out = set()
    for tok in argv:
        if not tok.startswith("-") or tok == "-":
            continue
        opt = tok.split("=", 1)[0]
        if opt in by_opt:
            out.add(by_opt[opt])
        elif not opt.startswith("--") and len(opt) > 2:
            # clustered/attached short option: -ftable
            short = opt[:2]
            if short in by_opt:
                out.add(by_opt[short])
    return out


DEFAULT_CONFIG = """\
# trivy-tpu.yaml — default configuration
# CLI flags override environment (TRIVY_TPU_*), which overrides this file.
format: table
severity: ""
scanners: vuln,secret
pkg-types: os,library
exit-code: 0
parallel: 5
cache-dir: ~/.cache/trivy-tpu
"""


def generate_default_config(path: str | None = None) -> str:
    path = path or "trivy-tpu.yaml"
    if os.path.exists(path):  # reference: refuses to clobber
        raise ValueError(f"config file already exists: {path}")
    # lint: allow[atomic-write] user-requested --generate-default-config output, not program state
    with open(path, "w", encoding="utf-8") as f:
        f.write(DEFAULT_CONFIG)
    return path
