"""Scan runner: DB bootstrap -> scanner selection -> scan -> filter ->
report -> exit code (reference pkg/commands/artifact/run.go Runner)."""

from __future__ import annotations

import os
import sys

from trivy_tpu.log import logger
from trivy_tpu.types.enums import Scanner as ScannerEnum, Severity
from trivy_tpu.types.scan import ScanOptions

_log = logger()


class FatalError(Exception):
    pass


def _validate_fault_spec() -> None:
    """Fail fast on a malformed TRIVY_TPU_FAULTS before any scan work."""
    from trivy_tpu.resilience import faults

    try:
        faults.validate_env()
    except faults.FaultSpecError as e:
        raise FatalError(f"TRIVY_TPU_FAULTS: {e}")


def _severities(arg: str | None) -> list[Severity] | None:
    if not arg:
        return None
    return [Severity.parse(s) for s in arg.split(",") if s.strip()]


def _db_path(args) -> str:
    return getattr(args, "db_path", None) or os.path.join(
        args.cache_dir, "db"
    )


def _load_db(args):
    from trivy_tpu.db.store import AdvisoryDB

    path = _db_path(args)
    try:
        db = AdvisoryDB.load(path)
        _log.info("advisory DB loaded", path=path, **db.stats())
        return db
    except FileNotFoundError:
        _log.warn(
            "no advisory DB found; vulnerability results will be empty "
            "(import one with `trivy-tpu db import`)", path=path,
        )
        return AdvisoryDB()


_ENGINE_CACHE: dict = {}


def new_engine(args):
    """Fresh MatchEngine (no process cache — callers that hot-swap the
    engine, like the server, must not leave the old one pinned). The
    on-disk DB path is threaded through so a warm start with an
    unchanged DB loads the persistent compiled-tensor cache instead of
    recompiling (tensorize.cache). `--mesh` / TRIVY_TPU_MESH serves
    matching from a sharded device mesh (ops/mesh.py); a malformed
    spec fails here at startup."""
    from trivy_tpu.detector.engine import MatchEngine
    from trivy_tpu.ops import mesh as mesh_ops

    db = _load_db(args)
    db_path = _db_path(args)
    mesh_spec = getattr(args, "mesh", None)
    if mesh_spec is None:
        mesh_spec = mesh_ops.spec_from_env()
    try:
        mesh_requested = mesh_ops.parse_spec(mesh_spec) is not None
    except ValueError as exc:
        raise FatalError(f"--mesh/TRIVY_TPU_MESH: {exc}")
    if not mesh_requested:
        # no mesh in play: engine errors must not be mislabeled as
        # mesh-knob problems
        return MatchEngine(
            db, use_device=not getattr(args, "no_tpu", False),
            db_path=db_path if db.buckets else None)
    try:
        return MatchEngine(
            db, use_device=not getattr(args, "no_tpu", False),
            db_path=db_path if db.buckets else None,
            mesh_spec=mesh_spec)
    except ValueError as exc:
        # the spec parsed, so a ValueError here is a topology the
        # runtime cannot place (e.g. not enough devices)
        raise FatalError(f"--mesh/TRIVY_TPU_MESH: {exc}")


def build_engine(args):
    """MatchEngine, cached per db-path within the process."""
    key = (_db_path(args), getattr(args, "no_tpu", False))
    if key not in _ENGINE_CACHE:
        _ENGINE_CACHE[key] = new_engine(args)
    return _ENGINE_CACHE[key]


def normalize_args(args) -> None:
    """Cross-flag defaults applied once after parsing. SBOM-shaped
    output formats ARE package lists: force full package listing
    (reference flag/report_flags.go forces ListAllPkgs there)."""
    if getattr(args, "format", "") in ("cyclonedx", "spdx-json", "github"):
        args.list_all_pkgs = True


def make_scan_options(args) -> ScanOptions:
    scanners = [ScannerEnum(s) for s in args.scanners.split(",") if s]
    return ScanOptions(
        pkg_types=args.pkg_types.split(","),
        scanners=scanners,
        list_all_pkgs=args.list_all_pkgs,
        include_dev_deps=getattr(args, "include_dev_deps", False),
        sbom_sources=[s for s in
                      getattr(args, "sbom_sources", "").split(",") if s],
        rekor_url=getattr(args, "rekor_url", "https://rekor.sigstore.dev"),
    )


def run_scan(args) -> int:
    from trivy_tpu.fanal.analyzers import secret_analyzer

    normalize_args(args)
    _validate_fault_spec()

    # --no-tpu forces the host path; the default is "hybrid" (device
    # screen + concurrent host AC — the fastest measured configuration;
    # it degrades to host-only without an accelerator backend). Set per
    # invocation so an earlier --no-tpu run in the same process doesn't
    # stick.
    secret_analyzer.USE_DEVICE = (
        False if getattr(args, "no_tpu", False) else "hybrid")

    # the compiled-NFA cache follows the resolved --cache-dir like
    # every other cache (set per invocation, same pattern as
    # USE_DEVICE above)
    from trivy_tpu.secret import scanner as _secret_scanner

    _secret_scanner.set_cache_dir(getattr(args, "cache_dir", None))

    # secret-engine sizing flags reach the scanner (deep inside the
    # fanal post-analyzer) through their env knobs; explicit flags win
    # over an inherited environment
    if getattr(args, "secret_pack_mb", None) is not None:
        os.environ["TRIVY_TPU_SECRET_PACK_MB"] = \
            str(args.secret_pack_mb)
    if getattr(args, "secret_stream_chunk_mb", None) is not None:
        os.environ["TRIVY_TPU_SECRET_STREAM_CHUNK_MB"] = \
            str(args.secret_stream_chunk_mb)


    # jar sha1->GAV lookups use the java DB when it has been imported
    # (reference pkg/javadb updater singleton)
    from trivy_tpu.db import javadb

    jdb_path = javadb.default_path(args.cache_dir)
    javadb.configure(jdb_path if os.path.exists(jdb_path) else None)

    # --compliance: the spec decides which scanners run and the report
    # becomes a control-check report (reference artifact/run.go:
    # ComplianceSpec.Scanners override + compliance/report.Write)
    compliance_spec = None
    if getattr(args, "compliance", None):
        from trivy_tpu.compliance.spec import SpecError, get_compliance_spec

        try:
            compliance_spec = get_compliance_spec(args.compliance)
        except (SpecError, OSError) as e:
            raise FatalError(f"compliance spec: {e}")
        args.scanners = ",".join(compliance_spec.scanners())

    # module extensions: custom analyzers + post-scan hooks
    # (reference pkg/module manager wired into the runner)
    from trivy_tpu.module import ModuleManager
    from trivy_tpu.obs import tracing as trace

    trace_export = getattr(args, "trace_export", None)
    tracing_on = getattr(args, "trace", False) or bool(trace_export)
    if tracing_on:
        trace.enable(True)
        trace.reset()
    explicit_dir = getattr(args, "module_dir", None)
    mod_mgr = ModuleManager(
        explicit_dir or os.path.join(args.cache_dir, "modules"),
        # the shared cache dir is not consent to execute: only
        # manifest-trusted modules load from it (ADR 0001)
        require_manifest=explicit_dir is None)
    mod_mgr.load()

    from trivy_tpu.iac import engine as check_engine

    try:
        # one root span covers the whole command (scan + report), so a
        # traced run exports a single tree under a single trace id
        with trace.span("scan", command=args.command):
            # custom misconfig checks: builtin bundle + --config-check
            # paths, gated by --check-namespaces (reference pkg/iac/rego
            # + pkg/policy); skipped entirely when misconfig isn't
            # scanned
            if "misconfig" in (args.scanners or "").split(",") \
                    or args.command == "config":
                _configure_check_engine(args)
            return _run_scan_core(args, compliance_spec)
    finally:
        check_engine.reset()
        mod_mgr.unload()
        if tracing_on:
            try:
                if getattr(args, "trace", False):
                    trace.render(sys.stderr)
                if trace_export:
                    n = trace.export_chrome(trace_export)
                    _log.info("trace exported", path=trace_export, spans=n)
            except OSError as e:
                # a bad export path must not eat the finished scan's
                # exit status (and enable(False) below must still run)
                _log.error("trace export failed", path=trace_export,
                           err=str(e))
            finally:
                trace.enable(False)


def _coerce_helm_value(v: str):
    low = v.lower()
    if low in ("true", "false"):
        return low == "true"
    if low in ("null", "~", ""):
        return None
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        return v


def _helm_overrides(args) -> dict:
    """--helm-values files then --helm-set pairs -> one nested override
    dict (later sources win, mirroring helm's precedence)."""
    import yaml as _yaml

    from trivy_tpu.iac.helm import _deep_merge

    out: dict = {}
    for path in getattr(args, "helm_values", []) or []:
        try:
            with open(path, encoding="utf-8") as f:
                out = _deep_merge(out, _yaml.safe_load(f) or {})
        except (OSError, _yaml.YAMLError) as e:
            raise FatalError(f"--helm-values {path}: {e}")
    for flag in getattr(args, "helm_set", []) or []:
        # helm accepts comma-joined pairs in one flag (a=1,b=2); commas
        # inside values must be escaped as '\\,' exactly like helm
        segments = [s.replace("\x00", ",") for s in
                    flag.replace("\\,", "\x00").split(",")]
        for pair in segments:
            key, sep, val = pair.partition("=")
            if not sep or not key:
                raise FatalError(
                    f"--helm-set needs key=value, got {pair!r}")
            node = out
            parts = key.split(".")
            for part in parts[:-1]:
                node = node.setdefault(part, {})
                if not isinstance(node, dict):
                    raise FatalError(f"--helm-set {pair!r} conflicts "
                                     "with a scalar override")
            node[parts[-1]] = _coerce_helm_value(val)
    return out


def _configure_check_engine(args) -> None:
    from trivy_tpu.iac import engine as check_engine
    from trivy_tpu.iac.engine import CheckLoadError
    from trivy_tpu.policy.bundle import bundle_check_paths

    # user-supplied paths may contain Python checks (explicit opt-in to
    # code execution); a downloaded bundle is data-only — its .py files
    # are refused at load time (reference Rego bundles are sandboxed by
    # the OPA interpreter; we get the same property by construction)
    user_paths = list(getattr(args, "config_check", []) or [])
    bundle_paths = bundle_check_paths(
        args.cache_dir,
        repository=getattr(args, "checks_bundle_repository", ""),
        skip_update=getattr(args, "skip_check_update", False))
    try:
        check_engine.configure(
            check_paths=user_paths,
            bundle_paths=bundle_paths,
            namespaces=getattr(args, "check_namespaces", []),
            data_paths=getattr(args, "config_data", []),
            include_deprecated=getattr(
                args, "include_deprecated_checks", False))
    except (CheckLoadError, OSError) as e:
        raise FatalError(f"loading checks: {e}")


def _parse_duration(spec: str | None) -> float:
    """Go-style duration ("5m", "300s", "1h30m", "500ms") or bare
    seconds -> seconds (reference --timeout, default 5m). Trailing
    garbage is an error, not silently dropped."""
    import re as _re

    import math

    if not spec:
        return 300.0
    try:
        v = float(spec)
        if not math.isfinite(v) or v <= 0:
            raise FatalError(f"invalid --timeout {spec!r}")
        return v
    except ValueError:
        pass
    unit_rx = r"(\d+(?:\.\d+)?)(ms|h|m|s)"
    if not _re.fullmatch(f"(?:{unit_rx})+", spec):
        raise FatalError(f"invalid --timeout {spec!r}")
    total = 0.0
    for n, unit in _re.findall(unit_rx, spec):
        total += float(n) * {"h": 3600.0, "m": 60.0, "s": 1.0,
                             "ms": 0.001}[unit]
    if total <= 0:
        raise FatalError(f"invalid --timeout {spec!r}")
    return total


def _scan_with_timeout(scanner, options, timeout_s: float,
                       budget_s: float | None = None):
    """Per-scan deadline (reference artifact/run.go:338 ctx timeout).
    The scan runs in a worker thread; on deadline the CLI fails with the
    reference's DeadlineExceeded advice (the worker, being a daemon
    thread, cannot outlive the process). `budget_s` (--scan-timeout)
    additionally arms the cooperative deadline budget that propagates
    through the scan spine and to the server via X-Trivy-Deadline —
    the scope is entered inside the worker because it is thread-local."""
    import threading

    from trivy_tpu.obs import tracing

    box: dict = {}
    # the worker thread starts from an empty contextvars context:
    # adopt the submitting thread's span/scan id so a fleet lane's scan
    # spans stay attached to the lane's span instead of orphaning
    from trivy_tpu.monitor import capture as mon_capture

    trace_ctx = tracing.capture()
    # the monitor's scan capture (a contextvar, like the trace context)
    # must follow the scan onto the worker thread or a --monitor-index
    # scan records an empty inventory
    mon_ctx = mon_capture.current()

    def work():
        try:
            with tracing.adopt(trace_ctx), mon_capture.adopt(mon_ctx):
                if budget_s:
                    from trivy_tpu.resilience.retry import (
                        Deadline,
                        deadline_scope,
                    )

                    with deadline_scope(Deadline.after(budget_s)):
                        box["report"] = scanner.scan_artifact(options)
                else:
                    box["report"] = scanner.scan_artifact(options)
        except BaseException as exc:  # lint: allow[bare-except] re-raised on the main thread after join
            box["error"] = exc

    t = threading.Thread(target=work, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise FatalError(
            f"scan deadline exceeded ({timeout_s:.0f}s); increase "
            "--timeout (e.g. --timeout 10m)")
    if "error" in box:
        raise box["error"]
    return box["report"]


def open_monitor_index(args):
    """The durable monitor index for a --monitor-index scan, or None
    (no flag, monitor disabled, or client mode — a remote scan's detect
    phase runs server-side, so the server owns the index there)."""
    path = getattr(args, "monitor_index", None)
    if not path:
        return None
    from trivy_tpu import monitor as monitor_mod

    if not monitor_mod.enabled():
        return None
    if getattr(args, "server", None):
        _log.warn("--monitor-index is ignored in client mode; run the "
                  "server with --monitor-index instead")
        return None
    from trivy_tpu.monitor.index import MonitorIndex

    return MonitorIndex.open_or_reset(path)


def _build_cache(args):
    """Cache backend selection shared by single-target and fleet scans."""
    from trivy_tpu.cache.cache import FSCache

    backend = getattr(args, "cache_backend", "fs") or "fs"
    if backend.startswith(("redis://", "rediss://")):
        from trivy_tpu.cache.redis import RedisCache, RedisError

        try:
            return RedisCache(
                backend, ca_cert=getattr(args, "redis_ca", ""),
                cert=getattr(args, "redis_cert", ""),
                key=getattr(args, "redis_key", ""),
                tls=getattr(args, "redis_tls", False),
                insecure=getattr(args, "redis_insecure", False))
        except (OSError, RedisError) as e:
            raise FatalError(f"redis cache backend: {e}")
    if backend == "memory":
        from trivy_tpu.cache.cache import MemoryCache

        return MemoryCache()
    if backend == "fs":
        return FSCache(args.cache_dir)
    raise FatalError(
        f"unknown cache backend {backend!r} (fs, memory, redis://...)")


def _scan_target(args, cache):
    """Build the scanner for args.target and run it under the timeout /
    budget flags -> raw (unfiltered) Report."""
    from trivy_tpu.resilience.retry import DeadlineExceeded
    from trivy_tpu.scanner.scan import Scanner

    artifact, driver = _select_scanner(args, cache)
    scanner = Scanner(driver, artifact)
    budget_spec = getattr(args, "scan_timeout", None)
    budget_s = _parse_duration(budget_spec) if budget_spec else None
    try:
        return _scan_with_timeout(
            scanner, make_scan_options(args),
            _parse_duration(getattr(args, "timeout", None)),
            budget_s=budget_s)
    except DeadlineExceeded as e:
        raise FatalError(
            f"scan deadline exceeded: {e} (increase --scan-timeout, or "
            "add --fallback in client mode to degrade to a local scan)")


def _run_scan_core(args, compliance_spec) -> int:
    from trivy_tpu.report.writer import write_report

    if getattr(args, "resume", None) or getattr(args, "targets", None):
        # fleet mode: many artifacts, one journal, one merged report
        from trivy_tpu.cli.fleet import run_fleet

        if compliance_spec is not None:
            raise FatalError("--compliance is not supported with fleet "
                             "scans (--targets/--resume)")
        return run_fleet(args)

    cache = _build_cache(args)
    mon_index = open_monitor_index(args)
    if mon_index is None:
        report = _scan_target(args, cache)
    else:
        from trivy_tpu.monitor.capture import capture_scan
        from trivy_tpu.tensorize import cache as compile_cache

        try:
            with capture_scan() as cap:
                report = _scan_target(args, cache)
            mon_index.update(
                getattr(args, "input", None) or args.target,
                cap.packages, cap.findings,
                db_digest=compile_cache.db_digest(_db_path(args)))
        finally:
            mon_index.close()
    severities = _postprocess_report(args, report)

    if compliance_spec is not None:
        from trivy_tpu.compliance.report import (
            build_compliance_report,
            write_compliance_report,
        )

        comp = build_compliance_report(report.results, compliance_spec)
        # lint: allow[atomic-write] user-requested report stream (--output), partial file is visible to the user
        out = open(args.output, "w") if args.output else None
        try:
            write_compliance_report(
                comp, fmt="json" if args.format == "json" else "table",
                report=getattr(args, "report", "summary"), output=out)
        finally:
            if out:
                out.close()
    else:
        from trivy_tpu import obs

        with obs.phase("report"):
            write_report(report, fmt=args.format, output=args.output,
                         template=args.template, severities=severities,
                         dependency_tree=getattr(args, "dependency_tree",
                                                 False))
    return _exit_code(args, report)


def _exit_code(args, report) -> int:
    # exit-code policy (reference pkg/commands/operation/operation.go:118):
    # FINDINGS drive the exit code; retained package lists do not
    if args.exit_code:
        for res in report.results:
            if (res.vulnerabilities or res.misconfigurations
                    or res.secrets or res.licenses):
                return args.exit_code
    if args.exit_on_eol and report.metadata.os and report.metadata.os.eosl:
        return args.exit_on_eol
    return 0


def _postprocess_report(args, report):
    """Result shaping between scan and render: VEX suppression,
    severity/status/ignore filtering, package stripping. Shared by the
    single-target path and each fleet artifact. Returns the parsed
    severity list (the table renderer wants it again)."""
    from trivy_tpu.result.filter import filter_report
    from trivy_tpu.result.ignore import load_ignore_file

    # VEX suppression runs before severity/ignore filtering
    # (reference pkg/result/filter.go:37 -> pkg/vex/vex.go:65).
    # Sources: a document path, "repo" (cached VEX repositories), or
    # "oci" (attestation attached to the scanned image).
    vex_specs = getattr(args, "vex", None) or []
    if vex_specs:
        from trivy_tpu.vex import filter_report_vex, load_vex

        sources = []
        for spec in vex_specs:
            if spec == "repo":
                from trivy_tpu.vex.repo import RepositorySet

                rs = RepositorySet(args.cache_dir)
                if rs:
                    sources.append(rs)
            elif spec == "oci":
                from trivy_tpu.vex.oci import load_oci_vex

                doc = load_oci_vex(report)
                if doc is not None:
                    sources.append(doc)
            else:
                sources.append(load_vex(spec))
        n = filter_report_vex(report, sources) if sources else 0
        if n:
            _log.info("vex suppressed findings", count=n)
    if not getattr(args, "show_suppressed", False):
        for res in report.results:
            res.modified_findings = []

    severities = _severities(args.severity)
    ignore_cfg = load_ignore_file(args.ignorefile)
    statuses = (args.ignore_status or "").split(",") if args.ignore_status else None
    ignore_policy = None
    if getattr(args, "ignore_policy", None):
        from trivy_tpu.result.policy import load_ignore_policy

        try:
            ignore_policy = load_ignore_policy(args.ignore_policy)
        except Exception as e:
            # .py policies can raise anything at import time
            # (SyntaxError, ImportError, ...); all of it is user input
            raise FatalError(f"ignore policy: {e}")
    filter_report(report, severities=severities, ignore_statuses=statuses,
                  ignore_config=ignore_cfg,
                  ignore_unfixed=getattr(args, "ignore_unfixed", False),
                  ignore_policy=ignore_policy)

    # packages travel with results internally (VEX reachability, the
    # dependency tree); they render under --list-all-pkgs, the
    # dependency tree, and SBOM-shaped formats (which ARE package lists
    # — the reference forces list-all-pkgs for them)
    keep_pkgs = (getattr(args, "list_all_pkgs", False)
                 or getattr(args, "dependency_tree", False))
    if not keep_pkgs:
        for res in report.results:
            res.packages = []
    return severities


def _select_scanner(args, cache):
    """reference pkg/commands/artifact/scanner.go: artifact kind x
    standalone/client -> (artifact, driver)."""
    if getattr(args, "server", None):
        from trivy_tpu.rpc.client import RemoteCache, RemoteDriver

        driver = RemoteDriver(args.server, token=args.token)
        # analysis runs client-side but blobs land in the SERVER's cache
        # (reference pkg/commands/artifact/scanner.go remote scanners)
        cache = RemoteCache(args.server, token=args.token)
        if getattr(args, "fallback", False):
            # --fallback: blobs mirror into a local cache and the scan
            # degrades to a locally-built engine when the breaker opens
            # or the deadline budget runs out (docs/resilience.md)
            from trivy_tpu.cache.cache import MemoryCache
            from trivy_tpu.resilience.breaker import CircuitBreaker
            from trivy_tpu.resilience.fallback import (
                FallbackCache,
                FallbackDriver,
            )

            breaker = CircuitBreaker(failure_threshold=3, recovery_s=30.0,
                                     name="rpc")
            cache = FallbackCache(cache, MemoryCache(), breaker=breaker)
            local_cache = cache

            def _local_driver():
                from trivy_tpu.scanner.local import LocalDriver

                return LocalDriver(build_engine(args), local_cache)

            driver = FallbackDriver(driver, _local_driver, breaker=breaker)
    else:
        from trivy_tpu.scanner.local import LocalDriver

        driver = LocalDriver(build_engine(args), cache)

    # analyzers whose scanner class was not requested are disabled
    # (reference pkg/commands/artifact/run.go disabledAnalyzers)
    scanners = set((args.scanners or "").split(","))
    disabled: set[str] = set()
    if "misconfig" not in scanners and args.command != "config":
        disabled.add("config")
    if "secret" not in scanners:
        disabled.add("secret")
    if "license" not in scanners:
        disabled.add("license-file")
    else:
        from trivy_tpu.fanal.analyzers.license_file import LicenseFileAnalyzer

        LicenseFileAnalyzer.full = bool(getattr(args, "license_full", False))

    # per-target analyzer gating (reference artifact/run.go:178-215):
    # fs scans read lockfiles, not installed-package stores; rootfs the
    # inverse; repository additionally skips OS analyzers
    from trivy_tpu.fanal.analyzer import (
        TYPE_INDIVIDUAL_PKGS,
        TYPE_LOCKFILES,
        TYPE_OSES,
    )

    cmd = args.command
    if cmd in ("filesystem", "fs"):
        disabled |= TYPE_INDIVIDUAL_PKGS | {"sbom"}
    elif cmd == "rootfs":
        disabled |= TYPE_LOCKFILES
    elif cmd in ("repository", "repo"):
        disabled |= TYPE_INDIVIDUAL_PKGS | TYPE_OSES | {"sbom"}
    if cmd == "sbom":
        from trivy_tpu.artifact.sbom import SBOMArtifact

        return SBOMArtifact(args.target, cache), driver
    if cmd in ("filesystem", "fs", "rootfs", "config"):
        from trivy_tpu.artifact.local_fs import FSArtifact

        return FSArtifact(
            args.target, cache,
            skip_files=args.skip_files, skip_dirs=args.skip_dirs,
            as_rootfs=(cmd == "rootfs"),
            misconfig_only=(cmd == "config"),
            parallel=args.parallel,
            disabled_analyzers=disabled,
            secret_config=getattr(args, "secret_config", None),
            file_patterns=getattr(args, "file_patterns", []),
            helm_overrides=_helm_overrides(args),
        ), driver
    if cmd in ("repository", "repo"):
        from trivy_tpu.artifact.repo import RepoArtifact

        return RepoArtifact(
            args.target, cache,
            skip_files=args.skip_files, skip_dirs=args.skip_dirs,
            parallel=args.parallel,
            disabled_analyzers=disabled,
            secret_config=getattr(args, "secret_config", None),
            branch=getattr(args, "branch", ""),
            tag=getattr(args, "tag", ""),
            commit=getattr(args, "commit", ""),
            helm_overrides=_helm_overrides(args),
        ), driver
    if cmd == "image":
        from trivy_tpu.artifact.image import ImageArtifact

        target = getattr(args, "input", None) or args.target
        if target is None:
            raise FatalError("image target or --input required")
        sources = tuple(
            s.strip() for s in
            getattr(args, "image_src", "containerd,docker,podman,remote").split(",")
            if s.strip())
        return ImageArtifact(
            target, cache, from_tar=bool(getattr(args, "input", None)),
            parallel=args.parallel,
            disabled_analyzers=disabled,
            secret_config=getattr(args, "secret_config", None),
            file_patterns=getattr(args, "file_patterns", []),
            image_sources=sources,
            insecure=getattr(args, "insecure", False),
            username=getattr(args, "username", ""),
            password=getattr(args, "password", ""),
            helm_overrides=_helm_overrides(args),
        ), driver
    if cmd == "vm":
        from trivy_tpu.artifact.vm import VMArtifact

        return VMArtifact(
            args.target, cache,
            parallel=args.parallel,
            disabled_analyzers=disabled,
            secret_config=getattr(args, "secret_config", None),
            file_patterns=getattr(args, "file_patterns", []),
            helm_overrides=_helm_overrides(args),
        ), driver
    raise FatalError(f"unsupported scan command {cmd!r}")


def run_k8s(args) -> int:
    """`kubernetes` subcommand (reference pkg/k8s/commands/run.go:26)."""
    from trivy_tpu.k8s.report import write_cluster_report
    from trivy_tpu.k8s.scanner import ClusterScanner

    scanners = {s.strip() for s in (args.scanners or "").split(",")
                if s.strip()}
    valid = {"vuln", "misconfig", "rbac", "infra"}
    if unknown := scanners - valid:
        raise FatalError(
            f"unknown k8s scanners: {', '.join(sorted(unknown))} "
            f"(valid: {', '.join(sorted(valid))})")

    compliance_spec = None
    if getattr(args, "compliance", None):
        from trivy_tpu.compliance.spec import SpecError, get_compliance_spec

        try:
            compliance_spec = get_compliance_spec(args.compliance)
        except (SpecError, OSError) as e:
            raise FatalError(f"compliance spec: {e}")
        scanners = set(compliance_spec.scanners()) & valid or {"misconfig"}
        # KCV controls are produced by the infra/node assessment and the
        # RBAC-range KSV ids (KSV041-053, the rbac.py rule set) by the
        # RBAC assessment, not by the per-resource misconfig scan
        spec_ids = {c.id for ctrl in compliance_spec.spec.controls
                    for c in ctrl.checks}
        if any(i.startswith("AVD-KCV-") for i in spec_ids):
            scanners.add("infra")
        rbac_ids = {f"AVD-KSV-{n:04d}" for n in range(41, 54)}
        if spec_ids & rbac_ids:
            scanners.add("rbac")

    engine = None
    if "vuln" in scanners:
        engine = build_engine(args)
    scanner = ClusterScanner(
        scanners=scanners, workers=args.parallel,
        image_tar_dir=getattr(args, "image_tar_dir", None), engine=engine,
        disable_node_collector=getattr(args, "disable_node_collector",
                                       False),
        node_collector_namespace=getattr(args, "node_collector_namespace",
                                         None),
        node_collector_image=getattr(args, "node_collector_imageref",
                                     None),
    )
    try:
        report = scanner.scan(args.target, context=args.context,
                              namespace=args.namespace)
    except RuntimeError as e:
        print(str(e), file=sys.stderr)
        return 1
    if compliance_spec is not None:
        from trivy_tpu.compliance.report import (
            build_compliance_report,
            write_compliance_report,
        )
        from trivy_tpu.types.report import (
            DetectedMisconfiguration,
            Result,
        )

        results: list[Result] = []
        for rr in report.resources:
            if rr.misconfigurations:
                results.append(Result(
                    target=rr.resource.fullname, result_class="config",
                    type="kubernetes",
                    misconfigurations=rr.misconfigurations))
            for img, rep in rr.image_reports:
                results.extend(rep.results)
        # infra/node (KCV) and RBAC (KSV) assessments map onto the CIS
        # control-plane/node controls (reference k8s compliance includes
        # node-collector output)
        for f in list(report.infra) + list(report.rbac):
            num = "".join(ch for ch in f.id if ch.isdigit())
            prefix = "KCV" if f.id.startswith("KCV") else "KSV"
            results.append(Result(
                target=f.resource, result_class="config",
                type="kubernetes",
                misconfigurations=[DetectedMisconfiguration(
                    type="kubernetes", id=f.id,
                    avd_id=f"AVD-{prefix}-{int(num or 0):04d}",
                    title=f.title, message=f.message,
                    severity=f.severity, status="FAIL")]))
        comp = build_compliance_report(results, compliance_spec)
        # lint: allow[atomic-write] user-requested report stream (--output), partial file is visible to the user
        out = open(args.output, "w") if args.output else None
        try:
            write_compliance_report(
                comp, fmt="json" if args.format == "json" else "table",
                report=args.report, output=out)
        finally:
            if out:
                out.close()
        return 0
    fmt = "json" if args.format == "json" else args.report
    write_cluster_report(report, fmt=fmt, output=args.output)
    return 0


def run_convert(args) -> int:
    import json

    from trivy_tpu.report.writer import write_report
    from trivy_tpu.result.filter import filter_report
    from trivy_tpu.types.report import Report

    with open(args.report) as f:
        doc = json.load(f)
    report = _report_from_json(doc)
    severities = _severities(args.severity)
    if severities:
        filter_report(report, severities=severities)
    write_report(report, fmt=args.format, output=args.output,
                 template=args.template, severities=severities)
    return 0


def _report_from_json(doc: dict):
    """Rebuild a Report (subset) from its JSON rendering for `convert`."""
    from trivy_tpu.types import report as R
    from trivy_tpu.types.artifact import OS, Layer, Package, PkgIdentifier
    from trivy_tpu.types.enums import Status

    rep = R.Report(
        schema_version=doc.get("SchemaVersion", 2),
        created_at=doc.get("CreatedAt", ""),
        artifact_name=doc.get("ArtifactName", ""),
        artifact_type=doc.get("ArtifactType", ""),
    )
    md = doc.get("Metadata") or {}
    rep.metadata = R.Metadata(
        size=md.get("Size", 0),
        os=OS(family=md.get("OS", {}).get("Family", ""),
              name=md.get("OS", {}).get("Name", ""),
              eosl=md.get("OS", {}).get("EOSL", False),
              extended=md.get("OS", {}).get("Extended", False))
        if md.get("OS") else None,
        image_id=md.get("ImageID", ""),
        diff_ids=md.get("DiffIDs", []) or [],
        repo_tags=md.get("RepoTags", []) or [],
        repo_digests=md.get("RepoDigests", []) or [],
        degraded=md.get("Degraded", ""),
    )
    for rdoc in doc.get("Results") or []:
        res = R.Result(
            target=rdoc.get("Target", ""),
            result_class=rdoc.get("Class", ""),
            type=rdoc.get("Type", ""),
        )
        for v in rdoc.get("Vulnerabilities") or []:
            ident = v.get("PkgIdentifier") or {}
            res.vulnerabilities.append(R.DetectedVulnerability(
                vulnerability_id=v.get("VulnerabilityID", ""),
                vendor_ids=v.get("VendorIDs", []) or [],
                pkg_id=v.get("PkgID", ""),
                pkg_name=v.get("PkgName", ""),
                pkg_path=v.get("PkgPath", ""),
                pkg_identifier=PkgIdentifier(
                    purl=ident.get("PURL", ""), uid=ident.get("UID", "")
                ),
                installed_version=v.get("InstalledVersion", ""),
                fixed_version=v.get("FixedVersion", ""),
                status=Status.parse(v.get("Status", "unknown")),
                severity_source=v.get("SeveritySource", ""),
                primary_url=v.get("PrimaryURL", ""),
                layer=Layer(
                    digest=(v.get("Layer") or {}).get("Digest", ""),
                    diff_id=(v.get("Layer") or {}).get("DiffID", ""),
                ),
                data_source=R.DataSource(
                    id=(v.get("DataSource") or {}).get("ID", ""),
                    base_id=(v.get("DataSource") or {}).get("BaseID", ""),
                    name=(v.get("DataSource") or {}).get("Name", ""),
                    url=(v.get("DataSource") or {}).get("URL", ""),
                ) if v.get("DataSource") else None,
                info=R.VulnerabilityInfo(
                    title=v.get("Title", ""),
                    description=v.get("Description", ""),
                    severity=v.get("Severity", "UNKNOWN"),
                    cwe_ids=v.get("CweIDs", []) or [],
                    cvss=v.get("CVSS", {}) or {},
                    references=v.get("References", []) or [],
                    published_date=v.get("PublishedDate", ""),
                    last_modified_date=v.get("LastModifiedDate", ""),
                    vendor_severity=v.get("VendorSeverity", {}) or {},
                ),
            ))
        for p in rdoc.get("Packages") or []:
            ident = p.get("Identifier") or {}
            res.packages.append(Package(
                id=p.get("ID", ""), name=p.get("Name", ""),
                version=p.get("Version", ""),
                release=p.get("Release", ""),
                epoch=p.get("Epoch", 0) or 0,
                arch=p.get("Arch", ""),
                src_name=p.get("SrcName", ""),
                src_version=p.get("SrcVersion", ""),
                src_release=p.get("SrcRelease", ""),
                licenses=p.get("Licenses", []) or [],
                relationship=p.get("Relationship", ""),
                depends_on=p.get("DependsOn", []) or [],
                file_path=p.get("FilePath", ""),
                identifier=PkgIdentifier(
                    purl=ident.get("PURL", ""), uid=ident.get("UID", "")),
                layer=Layer(
                    digest=(p.get("Layer") or {}).get("Digest", ""),
                    diff_id=(p.get("Layer") or {}).get("DiffID", ""),
                ),
            ))
        for s in rdoc.get("Secrets") or []:
            res.secrets.append(R.DetectedSecret(
                rule_id=s.get("RuleID", ""), category=s.get("Category", ""),
                severity=s.get("Severity", "UNKNOWN"),
                title=s.get("Title", ""), start_line=s.get("StartLine", 0),
                end_line=s.get("EndLine", 0), match=s.get("Match", ""),
            ))
        for m in rdoc.get("Misconfigurations") or []:
            res.misconfigurations.append(R.DetectedMisconfiguration(
                type=m.get("Type", ""), id=m.get("ID", ""),
                avd_id=m.get("AVDID", ""), title=m.get("Title", ""),
                description=m.get("Description", ""),
                message=m.get("Message", ""), namespace=m.get("Namespace", ""),
                resolution=m.get("Resolution", ""),
                severity=m.get("Severity", "UNKNOWN"),
                primary_url=m.get("PrimaryURL", ""),
                references=m.get("References", []) or [],
                status=m.get("Status", ""),
            ))
        if rdoc.get("MisconfSummary"):
            res.misconf_summary = R.MisconfSummary(
                successes=rdoc["MisconfSummary"].get("Successes", 0),
                failures=rdoc["MisconfSummary"].get("Failures", 0),
            )
        rep.results.append(res)
    return rep


def run_server(args) -> int:
    from trivy_tpu.cache.cache import FSCache
    from trivy_tpu.rpc.server import serve

    _validate_fault_spec()
    engine = new_engine(args)
    host, _, port = args.listen.partition(":")
    serve(engine, host=host or "localhost", port=int(port or 4954),
          token=args.token, cache=FSCache(args.cache_dir),
          db_path=_db_path(args),
          drain_timeout=_parse_duration(
              getattr(args, "drain_timeout", None) or "30s"),
          sched_window_ms=getattr(args, "sched_window_ms", None),
          sched_max_rows=getattr(args, "sched_max_rows", None),
          monitor_index=getattr(args, "monitor_index", None))
    return 0


def run_watch(args) -> int:
    """`trivy-tpu watch` (docs/monitoring.md): poll for advisory-DB
    generation changes and re-score the indexed fleet incrementally,
    emitting introduced/resolved finding events as JSON lines — or
    tail a running server's /monitor/events ring with --server."""
    import sys

    from trivy_tpu.monitor import watch as watch_mod

    _validate_fault_spec()
    interval = _parse_duration(getattr(args, "interval", None) or "60s")
    out = sys.stdout
    if getattr(args, "output", None):
        # lint: allow[atomic-write] user-requested event stream (--output): append-only JSONL the user tails
        out = open(args.output, "a", encoding="utf-8")
    try:
        if getattr(args, "server", None):
            return watch_mod.watch_remote(
                args.server, out, token=getattr(args, "token", None),
                interval_s=min(interval, 10.0),
                once=getattr(args, "once", False))
        from trivy_tpu import monitor as monitor_mod

        if not monitor_mod.enabled():
            raise FatalError(
                "TRIVY_TPU_MONITOR=0 disables the monitor subsystem")
        db_path = _db_path(args)
        index_path = getattr(args, "index", None) or os.path.join(
            args.cache_dir, "monitor-index.jsonl")
        index = watch_mod.open_index(
            index_path, journal_path=getattr(args, "journal", None))
        try:
            return watch_mod.watch_local(
                db_path, index, lambda: new_engine(args), out,
                interval_s=interval, once=getattr(args, "once", False),
                verify=True if getattr(args, "verify", False) else None)
        finally:
            index.close()
    except KeyboardInterrupt:
        return 0
    finally:
        if out is not sys.stdout:
            out.close()


def run_fleet_admin(args) -> int:
    """`trivy-tpu fleet status|rollout|metrics|profile|events|serve`
    (docs/fleet.md): replica-set health, the coordinated advisory-DB
    rollout controller, and the fleet observability control plane
    (metrics/attribution federation, stitched traces, the durable ops
    event log)."""
    import json as _json
    import sys

    from trivy_tpu.fleet import rollout as rollout_mod
    from trivy_tpu.fleet.endpoints import split_urls

    _validate_fault_spec()
    cmd = getattr(args, "fleet_command", None)
    if cmd is None:
        raise FatalError("fleet: choose a subcommand (status, rollout, "
                         "metrics, profile, events, serve, control)")
    token = getattr(args, "token", None)
    if cmd == "events":
        return _run_fleet_events(args)
    endpoints = split_urls(getattr(args, "endpoints", "") or "")
    if not endpoints:
        raise FatalError("fleet: no endpoints given")
    if cmd == "status":
        status = rollout_mod.fleet_status(endpoints, token=token)
        print(_json.dumps(status, indent=2, sort_keys=True))
        return 0 if all(s.get("ready") for s in status) else 1
    if cmd == "metrics":
        from trivy_tpu.fleet import telemetry

        fed = telemetry.federate_endpoints(endpoints, token=token)
        body = fed.render().decode()
        if getattr(args, "output", None):
            # lint: allow[atomic-write] user-requested exposition dump (--output), not program state
            with open(args.output, "w", encoding="utf-8") as f:
                f.write(body)
        else:
            print(body, end="")
        errors = getattr(fed, "errors", {})
        for idx, err in sorted(errors.items()):
            print(f"# scrape failed: replica {idx}: {err}",
                  file=sys.stderr)
        return 0 if not errors else 1
    if cmd == "profile":
        return _render_fleet_profile(endpoints, token,
                                     as_json=getattr(args, "json", False),
                                     flight=getattr(args, "flight", None))
    if cmd == "serve":
        return _run_fleet_serve(args, endpoints, token)
    if cmd == "control":
        return _run_fleet_control(args, endpoints, token)
    if cmd != "rollout":
        raise FatalError(f"fleet: unknown subcommand {cmd!r}")
    if getattr(args, "journal", None):
        from trivy_tpu.fleet import slo as slo_mod

        slo_mod.install_journal(args.journal)
    probes = None
    if getattr(args, "probes", None):
        probes = rollout_mod.load_probes(args.probes)
    try:
        report = rollout_mod.run_rollout(
            _db_path(args), endpoints, token=token, probes=probes,
            rescore=not getattr(args, "no_rescore", False),
            canary=getattr(args, "canary", None),
            on_event=lambda ev: print(
                _json.dumps(ev, sort_keys=True), file=sys.stderr))
    except rollout_mod.RolloutError as e:
        raise FatalError(f"fleet rollout: {e}")
    doc = report.doc()
    out = _json.dumps(doc, indent=2, sort_keys=True)
    if getattr(args, "output", None):
        # lint: allow[atomic-write] user-requested report stream (--output), partial file is visible to the user
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(out + "\n")
    print(out)
    return 0 if report.outcome in ("completed", "noop") else 1


def _run_fleet_events(args) -> int:
    """`trivy-tpu fleet events --journal PATH [--follow]`: replay the
    durable ops event journal (torn tail tolerated) as JSON lines;
    --follow tails the file incrementally and survives compaction /
    rotation (the tail reopens on inode change or truncation and
    resumes from the sealed replay point — the seq cursor)."""
    import json as _json
    import time as _time

    from trivy_tpu.durability.appendlog import AppendLogError
    from trivy_tpu.fleet.slo import JournalTail, OpsEventLog

    follow = getattr(args, "follow", False)
    since = getattr(args, "since", 0) or 0
    if not follow:
        # One-shot replay: the line-bounded journal reader already
        # tolerates torn tails and corrupt records.
        try:
            events = OpsEventLog.read(args.journal)
        except (AppendLogError, OSError) as e:
            raise FatalError(f"fleet events: {e}")
        out = sys.stdout
        if getattr(args, "output", None):
            # lint: allow[atomic-write] user-requested event stream (--output): append-only JSONL the user tails
            out = open(args.output, "a", encoding="utf-8")
        try:
            for ev in events:
                if int(ev.get("seq", 0)) > since:
                    out.write(_json.dumps(ev, sort_keys=True) + "\n")
            out.flush()
            return 0
        finally:
            if out is not sys.stdout:
                out.close()
    out = sys.stdout
    if getattr(args, "output", None):
        # lint: allow[atomic-write] user-requested event stream (--output): append-only JSONL the user tails
        out = open(args.output, "a", encoding="utf-8")
    tail = JournalTail(args.journal, since=since)
    try:
        while True:
            for ev in tail.poll():
                out.write(_json.dumps(ev, sort_keys=True) + "\n")
            out.flush()
            _time.sleep(1.0)
    except KeyboardInterrupt:
        return 0
    finally:
        tail.close()
        if out is not sys.stdout:
            out.close()


def _run_fleet_control(args, endpoints: list, token: str | None) -> int:
    """`trivy-tpu fleet control`: the blocking self-driving loop —
    observe the fleet, decide against policy, journal, act
    (docs/fleet.md "Self-driving fleet")."""
    from trivy_tpu.fleet import controller as ctrl_mod
    from trivy_tpu.fleet import slo as slo_mod

    if getattr(args, "journal", None):
        past = slo_mod.install_journal(args.journal)
        _log.info("ops event journal installed", path=args.journal,
                  replayed=len(past))
    interval = _parse_duration(getattr(args, "interval", None) or "5s")
    policy = ctrl_mod.ControllerPolicy(
        min_replicas=getattr(args, "min_replicas", None),
        max_replicas=getattr(args, "max_replicas", None))
    actuator = ctrl_mod.HttpFleetActuator(
        endpoints, token=token,
        spawn_cmd=getattr(args, "spawn_cmd", None),
        load_cmd=getattr(args, "load_cmd", None))
    ctl = ctrl_mod.FleetController(
        actuator, policy=policy,
        journal_path=getattr(args, "actions", None),
        dry_run=getattr(args, "dry_run", False))
    try:
        ctl.run(interval_s=interval,
                max_ticks=getattr(args, "ticks", None),
                on_tick=lambda report: print(
                    ctrl_mod.render_report(report), flush=True))
        return 0
    except KeyboardInterrupt:
        return 0
    finally:
        ctl.close()
        if getattr(args, "journal", None):
            slo_mod.uninstall_journal()


def _run_fleet_serve(args, endpoints: list, token: str | None) -> int:
    """`trivy-tpu fleet serve`: the blocking control-plane process —
    federation endpoint + monitor loop (docs/fleet.md)."""
    import time

    from trivy_tpu.fleet import slo as slo_mod
    from trivy_tpu.fleet import telemetry

    if getattr(args, "journal", None):
        past = slo_mod.install_journal(args.journal)
        _log.info("ops event journal installed", path=args.journal,
                  replayed=len(past))
    host, _sep, port = (getattr(args, "listen", None)
                        or "localhost:4955").rpartition(":")
    try:
        port_n = int(port)
    except ValueError:
        raise FatalError(f"fleet serve: bad --listen {args.listen!r}")
    interval = _parse_duration(getattr(args, "interval", None) or "5s")
    monitor = telemetry.FleetMonitor(endpoints, token=token)
    srv = telemetry.FederationServer(
        endpoints, host=host or "localhost", port=port_n,
        token=getattr(args, "token", None),
        upstream_token=getattr(args, "upstream_token", None) or token,
        monitor=monitor, monitor_interval_s=interval)
    srv.start()
    print(f"federation endpoint: {srv.address} "
          f"({len(endpoints)} replica(s))")
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        return 0
    finally:
        srv.shutdown()
        if getattr(args, "journal", None):
            slo_mod.uninstall_journal()


def _render_fleet_profile(endpoints: list, token: str | None,
                          as_json: bool, flight: str | None) -> int:
    """Shared by `trivy-tpu fleet profile` and the multi-endpoint form
    of `trivy-tpu profile`: per-replica attribution sections plus the
    federated fleet verdict; --flight stitches every replica's flight
    recorder into ONE Chrome trace."""
    import json as _json

    from trivy_tpu.fleet import telemetry

    profiles = []
    errors = []
    for ep in endpoints:
        try:
            profiles.append((ep.rstrip("/"),
                             telemetry.fetch_profile(ep, token=token)))
        except telemetry.FederationError as e:
            errors.append(str(e))
    if not profiles:
        raise FatalError("profile fetch failed: "
                         + "; ".join(errors or ["no endpoints"]))
    doc = telemetry.federate_profiles(profiles)
    if flight:
        fdoc = telemetry.stitch_endpoints(endpoints, token=token)
        # lint: allow[atomic-write] user-requested trace-export artifact, not program state
        with open(flight, "w", encoding="utf-8") as f:
            _json.dump(fdoc, f, indent=1)
            f.write("\n")
        st = fdoc.get("stitch", {})
        print(f"stitched flight trace written: {flight} "
              f"({st.get('replicas', 0)} replica(s), "
              f"{st.get('traces', 0)} trace(s), "
              f"{st.get('fragments', 0)} fragment(s), "
              f"{st.get('cancelled_spans', 0)} cancelled span(s), "
              f"{st.get('orphan_roots', 0)} orphan root(s))")
    if as_json:
        print(_json.dumps(doc, indent=2, sort_keys=True))
        return 0 if not errors else 1
    for label, rep in doc["replicas"].items():
        print(f"-- replica {label} "
              f"(scans {rep.get('scans', 0)}, "
              f"verdict: {rep.get('verdict', '?')})")
    fleet = doc["fleet"]
    print(f"-- fleet ({len(doc['replicas'])} replica(s), "
          f"scans {fleet['scans']}, wall {fleet['wall_s']:.3f}s)")
    print(f"{'lane':<16} {'busy s':>10} {'critical s':>11} {'share':>7}")
    for lane, row in fleet["lanes"].items():
        print(f"{lane:<16} {row['busy_s']:>10.3f} "
              f"{row['crit_s']:>11.3f} {row['crit_share']:>7.1%}")
    print(f"{'other':<16} {'':>10} {fleet['other_s']:>11.3f}")
    print(f"fleet verdict: {fleet['verdict']}")
    for err in errors:
        print(f"scrape failed: {err}", file=sys.stderr)
    return 0 if not errors else 1


def run_profile(args) -> int:
    """`trivy-tpu profile URL`: render a live server's bottleneck
    attribution (docs/observability.md "Attribution & profiling") —
    per-lane busy/critical seconds, the roofline "bound by X" verdict,
    recent per-scan records, and the slow-scan flight recorder.

    A comma-separated URL names a replica set: every replica's profile
    renders as its own section plus the federated fleet merge, and
    --flight stitches every replica's flight recorder into ONE Chrome
    trace (docs/observability.md "Fleet observability")."""
    import json as _json
    import urllib.error
    import urllib.request

    from trivy_tpu.fleet.endpoints import split_urls

    endpoints = [u if u.startswith("http") else "http://" + u
                 for u in split_urls(args.server)]
    if len(endpoints) > 1:
        return _render_fleet_profile(
            endpoints, getattr(args, "token", None),
            as_json=getattr(args, "json", False),
            flight=getattr(args, "flight", None))

    base = args.server.rstrip("/")
    if not base.startswith("http"):
        base = "http://" + base

    def get(path: str) -> dict:
        req = urllib.request.Request(base + path)
        if getattr(args, "token", None):
            req.add_header("Trivy-Token", args.token)
        with urllib.request.urlopen(req, timeout=10) as r:
            return _json.loads(r.read().decode())

    try:
        doc = get("/debug/profile")
        if getattr(args, "flight", None):
            fdoc = get("/debug/flight")
            # lint: allow[atomic-write] user-requested trace-export artifact, not program state
            with open(args.flight, "w", encoding="utf-8") as f:
                _json.dump(fdoc, f, indent=1)
                f.write("\n")
            print(f"flight ring written: {args.flight} "
                  f"({len(fdoc.get('traceEvents', []))} events, "
                  f"{fdoc.get('flightRecorder', {}).get('traces', 0)} "
                  "traces)")
    except urllib.error.URLError as e:
        raise FatalError(f"profile fetch failed: {e}")
    if getattr(args, "json", False):
        print(_json.dumps(doc, indent=2, sort_keys=True))
        return 0
    if not doc.get("enabled", False) and not doc.get("roots"):
        print("attribution disabled on this server "
              "(TRIVY_TPU_ATTRIB=0) or no scans observed yet")
        return 0
    print(f"scans observed: {doc.get('scans', 0)}  "
          f"(roots: {doc.get('roots', 0)}, "
          f"wall {doc.get('wall_s', 0.0):.3f}s)")
    print(f"{'lane':<16} {'busy s':>10} {'critical s':>11} {'share':>7}")
    for lane, row in (doc.get("lanes") or {}).items():
        print(f"{lane:<16} {row.get('busy_s', 0.0):>10.3f} "
              f"{row.get('crit_s', 0.0):>11.3f} "
              f"{row.get('crit_share', 0.0):>7.1%}")
    print(f"{'other':<16} {'':>10} "
          f"{doc.get('other_s', 0.0):>11.3f}")
    print(f"verdict: {doc.get('verdict', '?')}")
    flight = doc.get("flight") or {}
    slowest = flight.get("slowest") or []
    if slowest:
        print(f"flight recorder (slowest {len(slowest)} of "
              f"ring {flight.get('n')}):")
        for r in slowest:
            print(f"  {r.get('wall_s', 0.0):>9.3f}s  "
                  f"{r.get('name', ''):<14} "
                  f"dominant={r.get('dominant', '')} "
                  f"trace={r.get('trace_id', '')}")
    return 0


def _render_usage_table(tenants: dict, top: int | None) -> None:
    """Per-tenant cost-vector table, ordered by lane-seconds (the
    field closest to 'who is spending the fleet')."""
    rows = []
    for tenant, rec in tenants.items():
        f = rec.get("fields") or {}
        lane_s = sum((rec.get("lanes") or {}).values())
        rows.append((tenant, f, lane_s))
    rows.sort(key=lambda r: (-r[2], r[0]))
    if top is not None:
        rows = rows[:max(top, 0)]
    print(f"{'tenant':<20} {'scans':>7} {'sheds':>6} {'queries':>9} "
          f"{'rows':>10} {'MB in':>8} {'MB out':>8} {'lane s':>9}")
    for tenant, f, lane_s in rows:
        print(f"{tenant:<20} {f.get('scans', 0.0):>7.0f} "
              f"{f.get('sheds', 0.0):>6.0f} "
              f"{f.get('queries', 0.0):>9.0f} "
              f"{f.get('rows_matched', 0.0):>10.0f} "
              f"{f.get('wire_bytes_in', 0.0) / 1e6:>8.3f} "
              f"{f.get('wire_bytes_out', 0.0) / 1e6:>8.3f} "
              f"{lane_s:>9.3f}")


def run_usage(args) -> int:
    """`trivy-tpu usage URL[,URL2]`: render per-tenant usage metering
    (docs/observability.md "Usage metering") — one cost-vector row per
    tenant hash, fleet totals, and the lane-second conservation check.
    A comma-separated URL federates the replica set (tenant vectors
    summed — hashes are replica-independent); `--journal PATH` renders
    the last durable snapshot from a usage journal instead."""
    import json as _json
    import urllib.error
    import urllib.request

    top = getattr(args, "top", None)
    journal = getattr(args, "journal", None)
    if journal:
        from trivy_tpu.obs import usage as usage_mod

        doc = usage_mod.replay_journal(journal)
        if getattr(args, "json", False):
            print(_json.dumps(doc, indent=2, sort_keys=True))
            return 0
        print(f"usage journal: {journal}")
        _render_usage_table(doc.get("tenants") or {}, top)
        return 0
    if not getattr(args, "server", None):
        raise FatalError("usage: provide a server URL or --journal PATH")

    from trivy_tpu.fleet.endpoints import split_urls

    endpoints = [u if u.startswith("http") else "http://" + u
                 for u in split_urls(args.server)]
    token = getattr(args, "token", None) \
        or os.environ.get("TRIVY_TPU_PROFILE_TOKEN")
    if len(endpoints) > 1:
        from trivy_tpu.fleet import telemetry as _telemetry

        doc = _telemetry.federate_usage_endpoints(endpoints, token=token)
        if getattr(args, "json", False):
            print(_json.dumps(doc, indent=2, sort_keys=True))
            return 0 if not doc.get("errors") else 1
        fleet = doc.get("fleet") or {}
        print(f"fleet usage ({len(endpoints)} replicas, "
              f"{len(fleet.get('tenants') or {})} tenants)")
        _render_usage_table(fleet.get("tenants") or {}, top)
        cons = fleet.get("conservation") or {}
        print(f"conservation: tenant lane-seconds "
              f"{cons.get('tenant_lane_s', 0.0):.3f} vs attribution "
              f"{cons.get('attrib_lane_s', 0.0):.3f} — "
              f"{'OK' if cons.get('ok') else 'VIOLATION'}")
        for ep, err in (doc.get("errors") or {}).items():
            print(f"usage fetch failed: {ep}: {err}", file=sys.stderr)
        return 0 if not doc.get("errors") and cons.get("ok", True) else 1

    base = endpoints[0].rstrip("/")
    req = urllib.request.Request(base + "/debug/usage")
    if token:
        req.add_header("Trivy-Token", token)
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            doc = _json.loads(r.read().decode())
    except urllib.error.URLError as e:
        raise FatalError(f"usage fetch failed: {e}")
    if getattr(args, "json", False):
        print(_json.dumps(doc, indent=2, sort_keys=True))
        return 0
    if not doc.get("enabled", True) and not doc.get("tenants"):
        print("usage metering disabled on this server "
              "(TRIVY_TPU_USAGE=0) or no scans observed yet")
        return 0
    print(f"usage ({len(doc.get('tenants') or {})} tenants, "
          f"top-N {doc.get('top_n', 0)})")
    _render_usage_table(doc.get("tenants") or {}, top)
    cons = doc.get("conservation") or {}
    print(f"conservation: tenant lane-seconds "
          f"{cons.get('tenant_lane_s', 0.0):.3f} vs attribution "
          f"{cons.get('attrib_lane_s', 0.0):.3f} — "
          f"{'OK' if cons.get('ok') else 'VIOLATION'}")
    return 0 if cons.get("ok", True) else 1


def run_db(args) -> int:
    from trivy_tpu.db.store import AdvisoryDB

    if args.db_command == "import":
        if os.path.isdir(args.source):
            db = AdvisoryDB.load(args.source)
        else:
            from trivy_tpu.db.trivydb import try_load

            # a real trivy-db boltdb artifact imports directly
            db = try_load(args.source) or _import_json(args.source)
        path = getattr(args, "db_path", None) or os.path.join(args.cache_dir, "db")
        db.save(path)
        # an explicit import is the new truth: drop the last-good link
        # a previous `db download` left, or every reader would resolve
        # through it and silently keep serving the old generation
        from trivy_tpu.db import generations as _gens

        lg = _gens.last_good_path(path)
        if os.path.islink(lg):
            os.unlink(lg)
            _log.info("imported DB supersedes downloaded generation; "
                      "last-good link removed", path=path)
        _log.info("imported advisory DB", path=path, **db.stats())
        return 0
    if args.db_command == "stats":
        path = getattr(args, "db_path", None) or os.path.join(args.cache_dir, "db")
        db = AdvisoryDB.load(path)
        import json as _json

        print(_json.dumps(db.stats(), indent=2))
        return 0
    if args.db_command == "download":
        from trivy_tpu.db.oci import DB_MEDIA_TYPE, OCIError, install_artifact

        dest = getattr(args, "db_path", None) or os.path.join(
            args.cache_dir, "db")
        try:
            # crash-safe generation install: verified blob, staged
            # extraction, atomic last-good promotion (docs/durability.md)
            gen = install_artifact(
                args.db_repository, dest, media_type=DB_MEDIA_TYPE,
                insecure=getattr(args, "insecure", False))
        except OCIError as e:
            raise FatalError(str(e))
        _log.info("advisory DB downloaded", path=dest, generation=gen)
        return 0
    if args.db_command == "import-java":
        import gzip
        import json as _json

        from trivy_tpu.db import javadb

        jdb = javadb.JavaDB.create(javadb.default_path(args.cache_dir))
        opener = gzip.open if args.source.endswith(".gz") else open
        with opener(args.source, "rb") as f:
            entries = (_json.loads(line) for line in f if line.strip())
            n = jdb.import_entries(entries)
        jdb.write_metadata()
        jdb.close()
        _log.info("imported java DB", entries=n)
        return 0
    raise FatalError("usage: trivy-tpu db {import,import-java,stats}")


def _import_json(path: str):
    """Import a flat JSON advisory dump: {"buckets": {...}, "vulnerability":
    {...}} (same shape the store persists)."""
    import gzip
    import json

    from trivy_tpu.db.model import Advisory, VulnerabilityMeta
    from trivy_tpu.db.store import AdvisoryDB

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        blob = json.loads(f.read())
    db = AdvisoryDB()
    for bucket, pkgs in blob.get("buckets", {}).items():
        for name, advs in pkgs.items():
            for a in advs:
                db.put_advisory(bucket, name, Advisory.from_json(a))
    for vid, m in blob.get("vulnerability", {}).items():
        db.put_meta(VulnerabilityMeta.from_json(vid, m))
    return db


def run_clean(args) -> int:
    """`clean` (reference pkg/commands/clean): selective cache removal."""
    import shutil

    if args.all:
        shutil.rmtree(args.cache_dir, ignore_errors=True)
        _log.info("removed cache", path=args.cache_dir)
        return 0
    selected = False
    if getattr(args, "vuln_db", False):
        shutil.rmtree(os.path.join(args.cache_dir, "db"), ignore_errors=True)
        _log.info("removed advisory DB")
        selected = True
    if getattr(args, "java_db", False):
        shutil.rmtree(os.path.join(args.cache_dir, "javadb"),
                      ignore_errors=True)
        _log.info("removed java DB")
        selected = True
    if getattr(args, "scan_cache", False) or not selected:
        shutil.rmtree(os.path.join(args.cache_dir, "fanal"),
                      ignore_errors=True)
        _log.info("removed scan cache")
    return 0


def run_registry(args) -> int:
    """`registry login|logout` (reference pkg/commands/auth): credentials
    are stored docker-config style so the registry client
    (artifact.image_source._docker_config_auth) picks them up."""
    import base64
    import json as _json

    sub = getattr(args, "registry_command", None)
    cfg_dir = os.environ.get("DOCKER_CONFIG",
                             os.path.expanduser("~/.docker"))
    cfg_path = os.path.join(cfg_dir, "config.json")
    try:
        with open(cfg_path, "rb") as f:
            cfg = _json.load(f)
    except (OSError, ValueError):
        cfg = {}
    auths = cfg.setdefault("auths", {})

    if sub == "login":
        password = args.password
        if password is None or getattr(args, "password_stdin", False):
            password = sys.stdin.readline().rstrip("\n")
        if not password:
            raise FatalError("no password provided (use --password or pipe "
                             "it to stdin with --password-stdin)")
        raw = f"{args.username}:{password}".encode()
        auths[args.server] = {"auth": base64.b64encode(raw).decode()}
        os.makedirs(cfg_dir, exist_ok=True)
        fd = os.open(cfg_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            _json.dump(cfg, f, indent=2)
        _log.info("logged in", registry=args.server)
        return 0
    if sub == "logout":
        if auths.pop(args.server, None) is None:
            _log.warn("not logged in", registry=args.server)
            return 0
        # same 0600 idiom as login: credentials must never be group-readable
        fd = os.open(cfg_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            _json.dump(cfg, f, indent=2)
        _log.info("logged out", registry=args.server)
        return 0
    raise FatalError("usage: registry {login|logout} <server>")


def run_plugin(args) -> int:
    """`plugin install|uninstall|list|info|run` (reference pkg/plugin)."""
    from trivy_tpu.plugin import PluginError, PluginManager

    mgr = PluginManager(args.cache_dir)
    sub = getattr(args, "plugin_command", None)
    try:
        if sub == "install":
            p = mgr.install(args.source)
            print(f"installed {p.name} {p.version}".rstrip())
            return 0
        if sub == "uninstall":
            if not mgr.uninstall(args.name):
                raise FatalError(f"plugin {args.name!r} is not installed")
            return 0
        if sub == "list":
            for p in mgr.list():
                print(f"{p.name}\t{p.version}\t{p.summary}")
            return 0
        if sub == "info":
            p = mgr.get(args.name)
            if p is None:
                raise FatalError(f"plugin {args.name!r} is not installed")
            print(f"name: {p.name}\nversion: {p.version}\n"
                  f"summary: {p.summary}\ndescription: {p.description}")
            return 0
        if sub == "run":
            return mgr.run(args.name, list(args.plugin_args))
    except PluginError as e:
        raise FatalError(str(e))
    raise FatalError("usage: plugin {install|uninstall|list|info|run}")


def run_module(args) -> int:
    """`module install|uninstall|list` (reference pkg/module manager):
    modules are .py files under <cache>/modules loaded at scan time."""
    import shutil

    mod_dir = os.path.join(args.cache_dir, "modules")
    sub = getattr(args, "module_command", None)
    if sub == "install":
        if not args.source.endswith(".py") or not os.path.exists(args.source):
            raise FatalError(f"module source must be an existing .py file: "
                             f"{args.source}")
        os.makedirs(mod_dir, exist_ok=True)
        dest = os.path.join(mod_dir, os.path.basename(args.source))
        shutil.copyfile(args.source, dest)
        from trivy_tpu.module.manager import ModuleManager

        ModuleManager.record_trust(mod_dir, os.path.basename(dest))
        _log.info("installed module", path=dest)
        return 0
    if sub == "uninstall":
        name = args.name if args.name.endswith(".py") else args.name + ".py"
        path = os.path.join(mod_dir, name)
        if not os.path.exists(path):
            raise FatalError(f"module {args.name!r} is not installed")
        os.unlink(path)
        from trivy_tpu.module.manager import ModuleManager

        ModuleManager.revoke_trust(mod_dir, name)
        return 0
    if sub == "list":
        if os.path.isdir(mod_dir):
            for f in sorted(os.listdir(mod_dir)):
                if f.endswith(".py"):
                    print(f)
        return 0
    raise FatalError("usage: module {install|uninstall|list}")


def run_chaos(args) -> int:
    """`chaos run|replay` (docs/resilience.md "Chaos campaigns"):
    seeded multi-fault schedules against live mini-system scenarios,
    five invariant oracles per episode, machine-checked (site, action)
    coverage, auto-shrinking repros."""
    import json as _json
    import sys

    # the mesh/dcn scenarios need multiple host devices on CPU-only
    # boxes; the flag only takes effect before the first jax import
    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    from trivy_tpu.chaos import campaign

    cmd = getattr(args, "chaos_command", None)
    budget = getattr(args, "budget", None)
    budget_s = budget if budget is not None else \
        campaign.default_budget_s()
    strict = bool(getattr(args, "strict", False))

    if cmd == "replay":
        try:
            res = campaign.replay(args.spec, args.scenario,
                                  budget_s=budget_s, strict=strict)
        except campaign.ChaosError as e:
            raise FatalError(f"chaos replay: {e}")
        print(_json.dumps(res.to_dict(), indent=2, sort_keys=True))
        if res.ok:
            _log.info("replay held all invariants", spec=args.spec,
                      scenario=args.scenario)
            return 0
        _log.error("replay reproduced the failure", spec=args.spec,
                   failures=res.failures)
        return 1

    if cmd == "run":
        seed = getattr(args, "seed", None)
        seed = seed if seed is not None else campaign.default_seed()
        episodes = getattr(args, "episodes", None)
        episodes = episodes if episodes is not None else \
            campaign.default_episodes()
        names = None
        if getattr(args, "scenarios", None):
            names = [s.strip() for s in args.scenarios.split(",")
                     if s.strip()]
            unknown = [n for n in names
                       if n not in campaign.SCENARIOS]
            if unknown:
                raise FatalError(
                    f"chaos: unknown scenario(s) {unknown!r}; "
                    f"known: {sorted(campaign.SCENARIOS)}")
        try:
            rep = campaign.run_campaign(
                seed=seed, n_episodes=episodes, scenario_names=names,
                budget_s=budget_s, strict=strict,
                log=lambda m: _log.info(m))
        except campaign.ChaosError as e:
            raise FatalError(f"chaos run: {e}")
        out = getattr(args, "report_json", None)
        if out:
            from trivy_tpu.durability.atomic import atomic_write

            body = _json.dumps(rep.to_dict(), indent=2,
                               sort_keys=True).encode()
            atomic_write(out, body, fault_site="report.write")
        for repro in rep.repros:
            print(f"repro [{repro.scenario}] {repro.env_line()}",
                  file=sys.stderr)
        print(f"chaos: {len(rep.results)} episodes, "
              f"{len(rep.failures)} failing, "
              f"coverage {rep.coverage:.3f}"
              + (f", excluded {sorted(rep.excluded)}"
                 if rep.excluded else ""))
        return 0 if rep.ok else 1

    raise FatalError("usage: chaos {run|replay}")
