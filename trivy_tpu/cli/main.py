"""trivy-tpu CLI (reference cmd/trivy + pkg/commands/app.go re-expressed
with argparse; same subcommand surface, TPU engine underneath)."""

from __future__ import annotations

import argparse
import os
import sys

import trivy_tpu
from trivy_tpu import log


def _add_global_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--debug", action="store_true", help="debug logging")
    p.add_argument("--quiet", "-q", action="store_true", help="suppress logs")
    p.add_argument("--log-format", default="text",
                   choices=("text", "json"),
                   help="log line format; json emits one object per "
                        "line with trace_id/span_id/scan_id correlation "
                        "fields (fleet runs, log pipelines)")
    p.add_argument("--config", "-c", default=None,
                   help="config file (default trivy-tpu.yaml if present)")
    p.add_argument("--generate-default-config", action="store_true",
                   help="write trivy-tpu.yaml with defaults and exit")
    p.add_argument(
        "--cache-dir",
        default=os.environ.get(
            "TRIVY_TPU_CACHE_DIR",
            os.path.join(os.path.expanduser("~"), ".cache", "trivy-tpu"),
        ),
        help="cache directory",
    )


def _add_scan_flags(p: argparse.ArgumentParser) -> None:
    from trivy_tpu.report.writer import FORMATS

    p.add_argument("--format", "-f", default="table",
                   help=f"output format ({','.join(FORMATS)})")
    p.add_argument("--output", "-o", default=None, help="output file")
    p.add_argument("--template", "-t", default=None, help="go-style template path/string")
    p.add_argument("--severity", "-s", default=None,
                   help="comma-separated severities (UNKNOWN,LOW,MEDIUM,HIGH,CRITICAL)")
    p.add_argument("--scanners", default="vuln,secret",
                   help="comma-separated scanners (vuln,misconfig,secret,license)")
    p.add_argument("--secret-config", default="trivy-secret.yaml",
                   help="custom secret rule config path (reference "
                        "--secret-config)")
    p.add_argument("--pkg-types", default="os,library",
                   help="comma-separated package types (os,library)")
    p.add_argument("--db-path", default=None,
                   help="advisory DB directory (default <cache>/db)")
    p.add_argument("--skip-db-update", action="store_true")
    p.add_argument("--offline-scan", action="store_true")
    p.add_argument("--list-all-pkgs", action="store_true")
    p.add_argument("--include-dev-deps", action="store_true",
                   help="include development dependencies (supported "
                        "lockfiles only)")
    p.add_argument("--ignorefile", default=".trivyignore")
    p.add_argument("--ignore-policy", default=None,
                   help="finding ignore policy: .yaml condition DSL or "
                        ".py with ignore(finding) (reference's Rego "
                        "--ignore-policy)")
    p.add_argument("--ignore-unfixed", action="store_true",
                   help="hide vulnerabilities with no fixed version")
    p.add_argument("--dependency-tree", action="store_true",
                   help="show a reversed dependency origin tree for "
                        "vulnerable packages (table format)")
    p.add_argument("--file-patterns", action="append", default=[],
                   help="analyzer file pattern (type:regex); repeatable")
    p.add_argument("--ignore-status", default=None,
                   help="comma-separated statuses to ignore")
    p.add_argument("--exit-code", type=int, default=0)
    p.add_argument("--exit-on-eol", type=int, default=0)
    p.add_argument("--no-tpu", action="store_true",
                   help="run matching on host instead of the TPU kernel")
    p.add_argument("--mesh", default=None, metavar="DPxDB",
                   help="serve matching from a sharded device mesh: "
                        "'DPxDB' (e.g. 2x4: 2 data-parallel groups x 4 "
                        "advisory shards), 'HOSTSxDPxDB' (e.g. 2x1x4: "
                        "cross-host distributed MeshDB over "
                        "TRIVY_TPU_DCN workers, dp x db per host), "
                        "'auto' (topology from DB size, device count "
                        "and per-host HBM budget), or 'off' "
                        "single-chip (default; env TRIVY_TPU_MESH)")
    p.add_argument("--secret-pack-mb", type=float, default=None,
                   metavar="MB",
                   help="packed super-buffer MiB per device secret "
                        "anchor-screen dispatch (dispatch "
                        "amortization; default per-bank measured "
                        "value; env TRIVY_TPU_SECRET_PACK_MB)")
    p.add_argument("--secret-stream-chunk-mb", type=float, default=None,
                   metavar="MB",
                   help="streaming secret-scan chunk MiB for files "
                        "over 10 MiB (byte-identical to whole-file; "
                        "default 4; env "
                        "TRIVY_TPU_SECRET_STREAM_CHUNK_MB)")
    p.add_argument("--timeout", default="5m",
                   help="per-scan deadline (e.g. 300s, 5m, 1h; "
                        "reference --timeout default 5m)")
    p.add_argument("--scan-timeout", default=None,
                   help="per-scan deadline BUDGET propagated through the "
                        "scan spine and to the server via the "
                        "X-Trivy-Deadline header; the server sheds work "
                        "it cannot finish in time (503 + Retry-After). "
                        "Go-style duration; unset = no budget")
    p.add_argument("--fallback", action="store_true",
                   help="with --server: degrade to a local scan when the "
                        "remote is unavailable (circuit breaker) or the "
                        "deadline budget runs out; degraded reports "
                        "carry Metadata.Degraded")
    p.add_argument("--parallel", type=int, default=5,
                   help="number of parallel analysis workers")
    p.add_argument("--targets", default=None, metavar="FILE",
                   help="fleet mode: file of extra targets (one per "
                        "line, # comments) scanned alongside the "
                        "positional target; emits one merged JSON "
                        "report (docs/durability.md)")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="fleet mode: write an append-only scan journal "
                        "(fsynced per-artifact checkpoints) enabling "
                        "--resume after a crash")
    p.add_argument("--resume", default=None, metavar="JOURNAL",
                   help="resume an interrupted fleet scan from its "
                        "journal: completed artifacts are skipped, "
                        "in-flight ones re-run; the merged report is "
                        "byte-identical to an uninterrupted run")
    p.add_argument("--fleet-parallel", type=int, default=1,
                   help="fleet mode: artifacts scanned concurrently")
    p.add_argument("--monitor-index", default=None, metavar="PATH",
                   help="record each scanned artifact's package "
                        "inventory + finding baseline into the durable "
                        "monitor index at PATH, enabling `trivy-tpu "
                        "watch` advisory-delta re-scoring "
                        "(docs/monitoring.md)")
    p.add_argument("--server", default=None,
                   help="scan server URL (client mode); a comma-"
                        "separated list names a replica set served "
                        "through the fleet smart client (client-side "
                        "load balancing, failover, hedged requests — "
                        "docs/fleet.md)")
    p.add_argument("--token", default=None, help="server auth token")
    p.add_argument("--cache-backend", default="fs",
                   help="cache backend: fs, memory, or redis://host:port")
    p.add_argument("--redis-ca", default="", help="redis CA cert path")
    p.add_argument("--redis-cert", default="", help="redis client cert path")
    p.add_argument("--redis-key", default="", help="redis client key path")
    p.add_argument("--redis-tls", action="store_true",
                   help="enable TLS for the redis cache backend")
    p.add_argument("--redis-insecure", action="store_true",
                   help="skip certificate verification for the redis "
                        "cache backend (NOT recommended)")
    p.add_argument("--skip-files", action="append", default=[])
    p.add_argument("--skip-dirs", action="append", default=[])
    p.add_argument("--sbom-sources", default="",
                   help="comma-separated SBOM discovery sources for "
                        "unpackaged binaries (rekor)")
    p.add_argument("--rekor-url", default="https://rekor.sigstore.dev",
                   help="rekor server URL for --sbom-sources rekor")
    p.add_argument("--trace", action="store_true",
                   help="print a stage-timing trace after the scan "
                        "(set TRIVY_TPU_JAX_TRACE_DIR for a device "
                        "profile)")
    p.add_argument("--trace-export", default=None, metavar="FILE",
                   help="write the collected spans as Chrome "
                        "trace-event JSON (open in Perfetto / "
                        "chrome://tracing); implies span collection "
                        "even without --trace")
    p.add_argument("--module-dir", default=None,
                   help="directory of scan-module extensions "
                        "(default <cache>/modules)")
    p.add_argument("--vex", action="append", default=[],
                   help="VEX source: a document path (OpenVEX / CycloneDX "
                        "VEX / CSAF), 'repo' (cached VEX repositories), "
                        "or 'oci' (image-attached attestation); "
                        "repeatable")
    p.add_argument("--show-suppressed", action="store_true",
                   help="include VEX-suppressed findings in the report")
    p.add_argument("--license-full", action="store_true",
                   help="also classify license headers in source files "
                        "(license scanner)")
    p.add_argument("--compliance", default=None,
                   help="compliance report to generate (builtin name like "
                        "docker-cis-1.6.0 or @path/to/spec.yaml)")
    p.add_argument("--report", default="summary",
                   choices=("all", "summary"),
                   help="compliance report detail (all, summary)")
    _add_check_flags(p)


def _add_check_flags(p) -> None:
    """Misconfig check-engine flags (reference pkg/flag/rego_flags.go)."""
    p.add_argument("--config-check", action="append", default=[],
                   dest="config_check",
                   help="path to a custom check file (.py/.yaml) or a "
                        "directory of them; repeatable")
    p.add_argument("--check-namespaces", action="append", default=[],
                   dest="check_namespaces",
                   help="enable custom-check namespaces (e.g. 'user'); "
                        "repeatable")
    p.add_argument("--config-data", action="append", default=[],
                   dest="config_data",
                   help="path to YAML/JSON data made available to custom "
                        "checks; repeatable")
    p.add_argument("--include-deprecated-checks", action="store_true",
                   help="also run checks marked deprecated")
    p.add_argument("--helm-set", action="append", default=[],
                   dest="helm_set",
                   help="helm value override path.to.key=value; "
                        "repeatable")
    p.add_argument("--helm-values", action="append", default=[],
                   dest="helm_values",
                   help="helm values file overriding chart defaults; "
                        "repeatable")
    p.add_argument("--checks-bundle-repository", default="",
                   help="OCI repository for the check bundle "
                        "(overrides the builtin bundle source)")
    p.add_argument("--skip-check-update", action="store_true",
                   help="do not refresh the downloaded check bundle")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        allow_abbrev=False,
        prog="trivy-tpu",
        description="TPU-native security scanner (artifact -> vulnerabilities, "
        "secrets, misconfigurations, licenses)",
    )
    _add_global_flags(parser)
    sub = parser.add_subparsers(dest="command")

    for name, help_text, with_target in [
        ("image", "scan a container image (tar archive or registry ref)", True),
        ("filesystem", "scan a local filesystem directory", True),
        ("fs", "alias of filesystem", True),
        ("rootfs", "scan a root filesystem", True),
        ("repository", "scan a git repository", True),
        ("repo", "alias of repository", True),
        ("sbom", "scan an SBOM file (CycloneDX/SPDX json)", True),
        ("vm", "scan a VM image", True),
    ]:
        p = sub.add_parser(name, help=help_text, allow_abbrev=False)
        _add_global_flags(p)
        _add_scan_flags(p)
        if name in ("repository", "repo"):
            p.add_argument("--branch", default="",
                           help="git branch to check out")
            p.add_argument("--tag", default="", help="git tag to check out")
            p.add_argument("--commit", default="",
                           help="git commit to check out")
        if name == "image":
            p.add_argument("--input", default=None,
                           help="image tar archive path")
            p.add_argument("--image-src", default="containerd,docker,podman,remote",
                           help="comma-separated image sources tried in "
                                "order (containerd,docker,podman,remote)")
            p.add_argument("--insecure", action="store_true",
                           help="allow plain-HTTP / unverified registries")
            p.add_argument("--username", default=os.environ.get(
                "TRIVY_TPU_USERNAME", ""), help="registry username")
            p.add_argument("--password", default=os.environ.get(
                "TRIVY_TPU_PASSWORD", ""), help="registry password")
            p.add_argument("target", nargs="?", default=None)
        else:
            p.add_argument("target")

    p = sub.add_parser("kubernetes", help="scan a kubernetes cluster or "
                       "manifests directory", allow_abbrev=False,
                       aliases=["k8s"])
    _add_global_flags(p)
    p.add_argument("--report", default="summary",
                   choices=["summary", "all"],
                   help="report detail level")
    p.add_argument("--format", "-f", default="table",
                   choices=["table", "json"],
                   help="output format")
    p.add_argument("--output", "-o", default=None)
    p.add_argument("--scanners", default="misconfig,rbac,infra",
                   help="comma-separated (vuln,misconfig,rbac,infra)")
    p.add_argument("--context", default="", help="kubeconfig context")
    p.add_argument("--namespace", "-n", default="",
                   help="restrict to one namespace")
    p.add_argument("--image-tar-dir", default=None,
                   help="directory of image tars for offline vuln scans")
    p.add_argument("--compliance", default=None,
                   help="compliance report (k8s-nsa-1.0, "
                        "k8s-pss-baseline-0.1, k8s-pss-restricted-0.1, "
                        "or @path)")
    p.add_argument("--db-path", default=None)
    p.add_argument("--no-tpu", action="store_true")
    p.add_argument("--parallel", type=int, default=5)
    p.add_argument("--disable-node-collector", action="store_true",
                   help="skip the per-node collector Job on live "
                        "cluster scans")
    p.add_argument("--node-collector-namespace", default=None,
                   help="namespace for node-collector Jobs "
                        "(default trivy-temp)")
    p.add_argument("--node-collector-imageref", default=None,
                   help="node-collector image to run")
    p.add_argument("target", nargs="?", default="cluster",
                   help="'cluster' (live) or a manifests dir/file")

    p = sub.add_parser("convert", help="convert a saved JSON report", allow_abbrev=False)
    _add_global_flags(p)
    p.add_argument("--format", "-f", default="table")
    p.add_argument("--output", "-o", default=None)
    p.add_argument("--template", "-t", default=None)
    p.add_argument("--severity", "-s", default=None)
    p.add_argument("report")

    p = sub.add_parser("server", help="run the scan server", allow_abbrev=False)
    _add_global_flags(p)
    p.add_argument("--listen", default="localhost:4954")
    p.add_argument("--token", default=None)
    p.add_argument("--db-path", default=None)
    p.add_argument("--no-tpu", action="store_true")
    p.add_argument("--mesh", default=None, metavar="DPxDB",
                   help="serve matching from a sharded device mesh: "
                        "'DPxDB', 'HOSTSxDPxDB' (cross-host over "
                        "TRIVY_TPU_DCN workers), 'auto', or 'off' "
                        "(default; env TRIVY_TPU_MESH)")
    p.add_argument("--drain-timeout", default="30s",
                   help="graceful-drain budget on SIGTERM: /readyz goes "
                        "503 immediately, in-flight scans get this long "
                        "to finish, the rest are shed with Retry-After "
                        "(go-style duration)")
    from trivy_tpu.sched.scheduler import (
        DEFAULT_MAX_ROWS,
        DEFAULT_WINDOW_MS,
    )

    p.add_argument("--sched-window-ms", type=float,
                   default=DEFAULT_WINDOW_MS,
                   help="match-scheduler coalesce window: max "
                        "milliseconds a scan's detect batch waits to "
                        "share a device micro-batch with concurrent "
                        "requests (TRIVY_TPU_SCHED=0 disables the "
                        "scheduler entirely — exact per-request path)")
    p.add_argument("--sched-max-rows", type=int,
                   default=DEFAULT_MAX_ROWS,
                   help="match-scheduler target micro-batch size in "
                        "package-query rows; larger requests are "
                        "chunk-interleaved across batches so small "
                        "scans are never starved")
    p.add_argument("--monitor-index", default=None, metavar="PATH",
                   help="continuous monitoring: record completed scans "
                        "in the durable monitor index at PATH and "
                        "re-score the fleet incrementally after every "
                        "advisory-DB hot swap, emitting introduced/"
                        "resolved finding events at /monitor/events "
                        "(docs/monitoring.md)")

    p = sub.add_parser(
        "watch", help="continuous monitoring: re-score indexed "
        "artifacts when the advisory DB changes, emitting introduced/"
        "resolved findings as JSON lines (docs/monitoring.md)",
        allow_abbrev=False)
    _add_global_flags(p)
    p.add_argument("--db-path", default=None,
                   help="advisory DB directory to watch "
                        "(default <cache>/db)")
    p.add_argument("--index", default=None, metavar="PATH",
                   help="monitor index path (default "
                        "<cache>/monitor-index.jsonl; create it by "
                        "scanning with --monitor-index)")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="fleet scan journal to rebuild the index from "
                        "when it is missing or corrupt")
    p.add_argument("--interval", default="60s",
                   help="poll interval between DB generation checks "
                        "(go-style duration)")
    p.add_argument("--once", action="store_true",
                   help="process at most one pending DB change, then "
                        "exit (scripting/CI)")
    p.add_argument("--server", default=None,
                   help="tail a running server's /monitor/events ring "
                        "instead of watching a local DB root")
    p.add_argument("--token", default=None, help="server auth token")
    p.add_argument("--output", "-o", default=None,
                   help="write events here instead of stdout")
    p.add_argument("--verify", action="store_true",
                   help="cross-check every re-score against a "
                        "from-scratch full re-match (double work)")
    p.add_argument("--no-tpu", action="store_true",
                   help="run re-matching on host instead of the TPU "
                        "kernel")
    p.add_argument("--mesh", default=None, metavar="DPxDB",
                   help="re-match on a sharded device mesh ('DPxDB', "
                        "'HOSTSxDPxDB', 'auto', or 'off'; env "
                        "TRIVY_TPU_MESH)")

    p = sub.add_parser(
        "fleet", help="fleet administration: replica status and the "
        "coordinated advisory-DB rollout (canary, zero-diff probe "
        "set, staged roll, automatic rollback — docs/fleet.md)",
        allow_abbrev=False)
    _add_global_flags(p)
    flsub = p.add_subparsers(dest="fleet_command")
    pfs = flsub.add_parser(
        "status", help="JSON /readyz of every replica (ready state, "
        "serving generation, mesh/secret-probe notes)",
        allow_abbrev=False)
    _add_global_flags(pfs)
    pfs.add_argument("endpoints",
                     help="comma-separated replica URLs")
    pfs.add_argument("--token", default=None, help="server auth token")
    pfr = flsub.add_parser(
        "rollout", help="staged fleet-wide advisory-DB hot swap: "
        "canary first, probe set replayed for zero diff, then roll, "
        "rollback on regression; the delta re-score triggers on "
        "exactly one replica", allow_abbrev=False)
    _add_global_flags(pfr)
    pfr.add_argument("endpoints",
                     help="comma-separated replica URLs")
    pfr.add_argument("--db-path", required=True,
                     help="shared advisory-DB root (the staged+promoted "
                          "generation under it is the rollout target)")
    pfr.add_argument("--token", default=None, help="server auth token")
    pfr.add_argument("--probes", default=None, metavar="FILE",
                     help="probe set: JSON (array or lines) of "
                          "captured scan requests replayed against the "
                          "canary vs the serving fleet; any byte diff "
                          "rolls back")
    pfr.add_argument("--canary", default=None, metavar="URL",
                     help="replica to roll first (default: the first "
                          "endpoint still behind)")
    pfr.add_argument("--no-rescore", action="store_true",
                     help="skip triggering the advisory-delta "
                          "re-score after the roll")
    pfr.add_argument("--output", "-o", default=None,
                     help="write the rollout report JSON here")
    pfr.add_argument("--journal", default=None, metavar="PATH",
                     help="durable fleet ops event journal: rollout "
                          "stages and DB swaps append (fsynced) here "
                          "(docs/fleet.md 'Event catalog')")
    pfm = flsub.add_parser(
        "metrics", help="federated fleet exposition: scrape every "
        "replica's /metrics (OpenMetrics, exemplars preserved) and "
        "merge — counters summed, histogram buckets merged, every "
        "series re-emitted with a replica label (docs/fleet.md)",
        allow_abbrev=False)
    _add_global_flags(pfm)
    pfm.add_argument("endpoints", help="comma-separated replica URLs")
    pfm.add_argument("--token", default=None, help="server auth token")
    pfm.add_argument("--output", "-o", default=None,
                     help="write the federated exposition here "
                          "instead of stdout")
    pfp = flsub.add_parser(
        "profile", help="federated bottleneck attribution: every "
        "replica's /debug/profile merged into one fleet roofline "
        "verdict with per-replica sections (docs/observability.md "
        "'Fleet observability')", allow_abbrev=False)
    _add_global_flags(pfp)
    pfp.add_argument("endpoints", help="comma-separated replica URLs")
    pfp.add_argument("--token", default=None, help="server auth token")
    pfp.add_argument("--json", action="store_true",
                     help="print the raw federated document")
    pfp.add_argument("--flight", default=None, metavar="FILE",
                     help="also stitch every replica's flight "
                          "recorder into ONE Chrome trace at FILE "
                          "(per-replica process rows; hedge losers "
                          "marked cancelled)")
    pfe = flsub.add_parser(
        "events", help="fleet ops event log: read (or follow) the "
        "durable event journal — breaker trips, failovers, hedge "
        "outcomes, rollout stages, DB swaps, replica skew, SLO burn "
        "alerts (docs/fleet.md 'Event catalog')", allow_abbrev=False)
    _add_global_flags(pfe)
    pfe.add_argument("--journal", required=True, metavar="PATH",
                     help="event journal path (torn-tail-tolerant "
                          "replay)")
    pfe.add_argument("--follow", action="store_true",
                     help="keep tailing the journal for new events")
    pfe.add_argument("--since", type=int, default=0, metavar="SEQ",
                     help="only events with a sequence number > SEQ")
    pfe.add_argument("--output", "-o", default=None,
                     help="write events here instead of stdout")
    pfv = flsub.add_parser(
        "serve", help="run the fleet observability control plane: a "
        "token-gated federation endpoint (/metrics /profile /flight "
        "/events) plus the monitor loop — health probes, replica-skew "
        "detection, SLO burn-rate alerts journaled durably "
        "(docs/fleet.md)", allow_abbrev=False)
    _add_global_flags(pfv)
    pfv.add_argument("endpoints", help="comma-separated replica URLs")
    pfv.add_argument("--listen", default="localhost:4955",
                     help="host:port for the federation endpoint")
    pfv.add_argument("--token", default=None,
                     help="token gating the federation endpoint "
                          "(also used upstream unless --upstream-token)")
    pfv.add_argument("--upstream-token", default=None,
                     help="auth token for scraping the replicas")
    pfv.add_argument("--journal", default=None, metavar="PATH",
                     help="durable ops event journal path")
    pfv.add_argument("--interval", default="5s",
                     help="monitor tick period (go-style duration)")
    pfc = flsub.add_parser(
        "control", help="run the self-driving fleet controller: an "
        "SLO-driven remediation/autoscaling loop — scale against "
        "offered load under a cost floor, drain-and-replace unhealthy "
        "replicas, re-resolve degraded mesh topology, tune the hedge "
        "budget; every decision journaled and replayed idempotently "
        "(docs/fleet.md 'Self-driving fleet')", allow_abbrev=False)
    _add_global_flags(pfc)
    pfc.add_argument("endpoints", help="comma-separated replica URLs")
    pfc.add_argument("--token", default=None, help="server auth token")
    pfc.add_argument("--actions", default=None, metavar="PATH",
                     help="controller action journal (intent/applied "
                          "records; replayed idempotently across "
                          "controller crashes). Default: observe-only "
                          "decisions are still emitted but not "
                          "durably journaled")
    pfc.add_argument("--journal", default=None, metavar="PATH",
                     help="durable fleet ops event journal every "
                          "controller_action event appends to")
    pfc.add_argument("--interval", default="5s",
                     help="control-loop tick period (go-style "
                          "duration)")
    pfc.add_argument("--ticks", type=int, default=None, metavar="N",
                     help="stop after N control passes (default: run "
                          "until interrupted)")
    pfc.add_argument("--dry-run", action="store_true",
                     help="journal and emit every decision without "
                          "acting on the fleet (the rehearsal "
                          "contract: nothing changes but the journal)")
    pfc.add_argument("--spawn-cmd", default=None, metavar="CMD",
                     help="shell command that starts one replica and "
                          "prints its URL on the last stdout line "
                          "(how scale_up/drain_replace reach your "
                          "process supervisor); without it the "
                          "controller cannot add replicas")
    pfc.add_argument("--load-cmd", default=None, metavar="CMD",
                     help="shell command printing the fleet's offered "
                          "load (a number) on its last stdout line; "
                          "default: sum of the in-flight scan counts "
                          "replicas report on /readyz. With neither "
                          "signal the controller never scales on "
                          "load")
    pfc.add_argument("--min-replicas", type=int, default=None,
                     help="autoscaler cost floor (default "
                          "TRIVY_TPU_CONTROLLER_MIN_REPLICAS or 1)")
    pfc.add_argument("--max-replicas", type=int, default=None,
                     help="autoscaler ceiling (default "
                          "TRIVY_TPU_CONTROLLER_MAX_REPLICAS or 4)")

    p = sub.add_parser(
        "profile", help="fetch a live server's bottleneck attribution "
        "(/debug/profile): per-resource-lane occupancy, critical-path "
        "shares, the roofline verdict, and the slow-scan flight "
        "recorder; a comma-separated URL federates a replica set "
        "(docs/observability.md)", allow_abbrev=False)
    _add_global_flags(p)
    p.add_argument("server", help="scan server URL (e.g. "
                                  "http://localhost:4954); a comma-"
                                  "separated list federates the whole "
                                  "replica set (per-replica sections + "
                                  "the fleet merge)")
    p.add_argument("--token", default=None,
                   help="server auth token (or the dedicated "
                        "TRIVY_TPU_PROFILE_TOKEN)")
    p.add_argument("--json", action="store_true",
                   help="print the raw /debug/profile document")
    p.add_argument("--flight", default=None, metavar="FILE",
                   help="also fetch /debug/flight (the N slowest scan "
                        "traces) as Chrome trace-event JSON to FILE; "
                        "with a replica set, every recorder is pulled "
                        "and stitched into ONE trace (per-replica "
                        "process rows, hedge losers marked cancelled)")

    p = sub.add_parser(
        "usage", help="fetch a live server's per-tenant usage metering "
        "(/debug/usage): cost vectors per tenant hash, fleet totals, "
        "and the lane-second conservation check; a comma-separated URL "
        "federates a replica set (docs/observability.md 'Usage "
        "metering')", allow_abbrev=False)
    _add_global_flags(p)
    p.add_argument("server", nargs="?", default=None,
                   help="scan server URL (e.g. http://localhost:4954); "
                        "a comma-separated list federates the whole "
                        "replica set; omit with --journal to read a "
                        "usage journal file instead")
    p.add_argument("--token", default=None,
                   help="server auth token (or the dedicated "
                        "TRIVY_TPU_PROFILE_TOKEN)")
    p.add_argument("--json", action="store_true",
                   help="print the raw usage document")
    p.add_argument("--top", type=int, default=None, metavar="K",
                   help="show only the K tenants with the most "
                        "lane-seconds (default: all)")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="render the last durable snapshot from a usage "
                        "journal (TRIVY_TPU_USAGE_JOURNAL) instead of "
                        "querying a live server")

    p = sub.add_parser("db", help="advisory DB operations", allow_abbrev=False)
    _add_global_flags(p)
    dbsub = p.add_subparsers(dest="db_command")
    pi = dbsub.add_parser("import", help="import advisories from a JSON dump", allow_abbrev=False)
    _add_global_flags(pi)
    pi.add_argument("source")
    pi.add_argument("--db-path", default=None)
    ps = dbsub.add_parser("stats", help="show DB statistics", allow_abbrev=False)
    _add_global_flags(ps)
    ps.add_argument("--db-path", default=None)
    pd = dbsub.add_parser(
        "download",
        help="download the advisory DB as an OCI artifact",
        allow_abbrev=False)
    _add_global_flags(pd)
    pd.add_argument("--db-repository",
                    default="ghcr.io/aquasecurity/trivy-db:2")
    pd.add_argument("--insecure", action="store_true")
    pj = dbsub.add_parser(
        "import-java",
        help="import a java sha1->GAV dump (JSONL: "
             '{"groupId","artifactId","version","sha1"} per line)',
        allow_abbrev=False)
    _add_global_flags(pj)
    pj.add_argument("source")

    p = sub.add_parser("plugin", help="manage plugins", allow_abbrev=False)
    _add_global_flags(p)
    plsub = p.add_subparsers(dest="plugin_command")
    pp = plsub.add_parser("install", help="install a plugin from a local "
                          "dir, zip, or URL", allow_abbrev=False)
    _add_global_flags(pp)
    pp.add_argument("source")
    pp = plsub.add_parser("uninstall", help="remove an installed plugin",
                          allow_abbrev=False)
    _add_global_flags(pp)
    pp.add_argument("name")
    pp = plsub.add_parser("list", help="list installed plugins",
                          allow_abbrev=False)
    _add_global_flags(pp)
    pp = plsub.add_parser("info", help="show plugin details",
                          allow_abbrev=False)
    _add_global_flags(pp)
    pp.add_argument("name")
    pp = plsub.add_parser("run", help="run a plugin", allow_abbrev=False)
    _add_global_flags(pp)
    pp.add_argument("name")
    pp.add_argument("plugin_args", nargs=argparse.REMAINDER)

    p = sub.add_parser("module", help="manage scan modules",
                       allow_abbrev=False)
    _add_global_flags(p)
    mosub = p.add_subparsers(dest="module_command")
    mm = mosub.add_parser("install", help="install a module (.py file)",
                          allow_abbrev=False)
    _add_global_flags(mm)
    mm.add_argument("source")
    mm = mosub.add_parser("uninstall", help="remove a module",
                          allow_abbrev=False)
    _add_global_flags(mm)
    mm.add_argument("name")
    mm = mosub.add_parser("list", help="list installed modules",
                          allow_abbrev=False)
    _add_global_flags(mm)

    p = sub.add_parser("registry", help="registry authentication",
                       allow_abbrev=False)
    _add_global_flags(p)
    regsub = p.add_subparsers(dest="registry_command")
    pl = regsub.add_parser("login", help="log in to a registry",
                           allow_abbrev=False)
    _add_global_flags(pl)
    pl.add_argument("--username", "-u", required=True)
    pl.add_argument("--password", default=None,
                    help="password (omit to read from stdin)")
    pl.add_argument("--password-stdin", action="store_true")
    pl.add_argument("server")
    po = regsub.add_parser("logout", help="log out of a registry",
                           allow_abbrev=False)
    _add_global_flags(po)
    po.add_argument("server")

    p = sub.add_parser("clean", help="clean caches", allow_abbrev=False)
    _add_global_flags(p)
    p.add_argument("--all", "-a", action="store_true",
                   help="remove everything under the cache dir")
    p.add_argument("--scan-cache", action="store_true",
                   help="remove cached scan blobs")
    p.add_argument("--vuln-db", action="store_true",
                   help="remove the advisory DB")
    p.add_argument("--java-db", action="store_true",
                   help="remove the java GAV DB")

    p = sub.add_parser("config", help="scan config files for misconfigurations", allow_abbrev=False)
    _add_global_flags(p)
    _add_scan_flags(p)
    p.add_argument("target")

    p = sub.add_parser(
        "chaos", help="deterministic chaos campaigns over the fault "
        'matrix (docs/resilience.md "Chaos campaigns")',
        allow_abbrev=False)
    _add_global_flags(p)
    chsub = p.add_subparsers(dest="chaos_command")
    pcr = chsub.add_parser(
        "run", help="run a seeded multi-fault campaign with invariant "
        "oracles and 100% (site, action) coverage", allow_abbrev=False)
    _add_global_flags(pcr)
    pcr.add_argument("--seed", type=int, default=None,
                     help="campaign seed (default TRIVY_TPU_CHAOS_SEED)")
    pcr.add_argument("--episodes", type=int, default=None,
                     help="seeded episodes before the coverage sweep "
                     "(default TRIVY_TPU_CHAOS_EPISODES)")
    pcr.add_argument("--scenarios", default=None,
                     help="comma-separated scenario names (default: all)")
    pcr.add_argument("--budget", type=float, default=None,
                     help="per-episode watchdog budget in seconds "
                     "(default TRIVY_TPU_CHAOS_BUDGET_S)")
    pcr.add_argument("--strict", action="store_true",
                     help="degraded stamps do not excuse output "
                     "divergence")
    pcr.add_argument("--json", dest="report_json", default=None,
                     metavar="PATH",
                     help="write the campaign report as JSON")
    pcp = chsub.add_parser(
        "replay", help="replay one TRIVY_TPU_FAULTS spec (a shrunk "
        "repro) against a scenario", allow_abbrev=False)
    _add_global_flags(pcp)
    pcp.add_argument("spec", help="TRIVY_TPU_FAULTS spec string")
    pcp.add_argument("--scenario", required=True,
                     help="scenario name (chaos.SCENARIOS)")
    pcp.add_argument("--budget", type=float, default=None,
                     help="watchdog budget in seconds")
    pcp.add_argument("--strict", action="store_true",
                     help="degraded stamps do not excuse output "
                     "divergence")

    sub.add_parser("version", help="print version", allow_abbrev=False)

    # `lint` shares the analysis package's flag definitions — one
    # definition, so global flags may precede the subcommand and the
    # CLI accepts exactly what `python -m trivy_tpu.analysis.lint` does
    from trivy_tpu.analysis.lint import add_arguments as _lint_args

    p = sub.add_parser(
        "lint", help="run the project invariant linter "
        "(docs/static-analysis.md)", allow_abbrev=False)
    _lint_args(p)
    return parser


def main(argv: list[str] | None = None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    parser = build_parser()

    # `trivy-tpu <plugin-name> args…` runs an installed plugin
    # (reference pkg/plugin/plugin.go:101 + cmd/trivy plugin-mode)
    known = {"image", "filesystem", "fs", "rootfs", "repository", "repo",
             "sbom", "vm", "kubernetes", "k8s", "convert", "server", "db",
             "clean", "config", "version", "registry", "plugin", "module",
             "lint", "watch", "profile", "usage", "fleet", "chaos"}
    if argv and not argv[0].startswith("-") and argv[0] not in known:
        from trivy_tpu.plugin import PluginManager

        cache_dir = os.environ.get(
            "TRIVY_TPU_CACHE_DIR",
            os.path.join(os.path.expanduser("~"), ".cache", "trivy-tpu"))
        mgr = PluginManager(cache_dir)
        if mgr.get(argv[0]) is not None:
            return mgr.run(argv[0], argv[1:])

    args = parser.parse_args(argv)

    if getattr(args, "command", None) == "lint":
        from trivy_tpu.analysis.lint import run_from_args

        return run_from_args(args)

    if getattr(args, "generate_default_config", False):
        from trivy_tpu.cli.config import generate_default_config

        try:
            path = generate_default_config(getattr(args, "config", None))
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 1
        print(f"written: {path}")
        return 0

    # layered resolution: CLI > TRIVY_TPU_* env > config file > default
    from trivy_tpu.cli.config import apply_layers

    try:
        apply_layers(args, parser, argv)
    except (ValueError, FileNotFoundError) as e:
        print(str(e), file=sys.stderr)
        return 1
    log.init(debug=getattr(args, "debug", False),
             quiet=getattr(args, "quiet", False),
             fmt=getattr(args, "log_format", "text"))

    if args.command in (None, "version"):
        if args.command is None:
            parser.print_help()
            return 0
        print(f"Version: {trivy_tpu.__version__}")
        return 0

    from trivy_tpu.cli import run

    try:
        if args.command in ("image", "filesystem", "fs", "rootfs",
                            "repository", "repo", "sbom", "vm", "config"):
            return run.run_scan(args)
        if args.command in ("kubernetes", "k8s"):
            return run.run_k8s(args)
        if args.command == "convert":
            return run.run_convert(args)
        if args.command == "server":
            return run.run_server(args)
        if args.command == "watch":
            return run.run_watch(args)
        if args.command == "profile":
            return run.run_profile(args)
        if args.command == "usage":
            return run.run_usage(args)
        if args.command == "fleet":
            return run.run_fleet_admin(args)
        if args.command == "db":
            return run.run_db(args)
        if args.command == "clean":
            return run.run_clean(args)
        if args.command == "registry":
            return run.run_registry(args)
        if args.command == "plugin":
            return run.run_plugin(args)
        if args.command == "module":
            return run.run_module(args)
        if args.command == "chaos":
            return run.run_chaos(args)
    except run.FatalError as e:
        log.logger().error(str(e))
        return 1
    except FileNotFoundError as e:
        log.logger().error(f"file not found: {e.filename or e}")
        return 1
    except (ValueError, OSError) as e:
        log.logger().error(str(e))
        return 1
    except Exception as e:  # scan-level failures render as one error line
        if getattr(args, "debug", False):
            raise
        log.logger().error(f"{type(e).__name__}: {e}")
        return 1
    parser.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
