"""Journaled, resumable fleet scans (docs/durability.md).

One invocation scans many artifacts of the same kind:

    trivy-tpu image --targets refs.txt --journal fleet.jsonl \
        --format json --output fleet.json

Every artifact's lifecycle (pending → running → done/failed, with the
finished report embedded and digest-sealed) is checkpointed to the
journal before the run proceeds, so after a SIGKILL:

    trivy-tpu image --targets refs.txt --resume fleet.jsonl \
        --format json --output fleet.json

skips completed artifacts, re-runs in-flight/pending ones, and writes a
merged report byte-identical to an uninterrupted run (timestamps under
the fake-clock contract of utils/clock).
"""

from __future__ import annotations

import copy
import json
import os
import sys

from trivy_tpu.cli.run import (
    FatalError,
    _build_cache,
    _postprocess_report,
    _scan_target,
    open_monitor_index,
)
from trivy_tpu.durability import ScanJournal, atomic_write, options_fingerprint
from trivy_tpu.durability.journal import JournalError
from trivy_tpu.fanal import pipeline as analysis_pipeline
from trivy_tpu.log import logger
from trivy_tpu.obs import metrics as obs_metrics
from trivy_tpu.obs import tracing
from trivy_tpu.resilience import faults
from trivy_tpu.utils import clock
from trivy_tpu.utils import uuid as uuid_util
from trivy_tpu.utils.pipeline import PipelineError, run_pipeline

_log = logger("fleet")

FAULT_SITE = "fleet.scan"  # kill rules here crash between artifacts


def _read_targets_file(path: str) -> list[str]:
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError as e:
        raise FatalError(f"--targets {path}: {e}")
    return [ln.strip() for ln in lines
            if ln.strip() and not ln.lstrip().startswith("#")]


def _given_targets(args) -> list[str]:
    """Positional target (if any) + --targets file lines, deduped in
    order — the fleet is the union, so the usual single-target CLI
    shape still works with a file of extras."""
    out: list[str] = []
    positional = getattr(args, "input", None) or getattr(args, "target", None)
    if positional:
        out.append(positional)
    tf = getattr(args, "targets", None)
    if tf:
        out.extend(_read_targets_file(tf))
    seen: set[str] = set()
    return [t for t in out if not (t in seen or seen.add(t))]


def run_fleet(args) -> int:
    if getattr(args, "format", "json") != "json":
        # before any journal is created: a refused run must not leave a
        # half-born journal blocking the corrected invocation
        raise FatalError("fleet scans emit a merged JSON report; "
                         "use --format json")
    fingerprint = options_fingerprint(args.command, args)
    resume_path = getattr(args, "resume", None)
    journal = None
    if resume_path:
        try:
            journal = ScanJournal.resume(resume_path)
        except JournalError as e:
            raise FatalError(str(e))
        if journal.command != args.command:
            raise FatalError(
                f"journal {resume_path} was written by "
                f"`trivy-tpu {journal.command}`, not `{args.command}`")
        if journal.fingerprint != fingerprint:
            raise FatalError(
                f"journal {resume_path} was written with different scan "
                "options; resuming would skew the merged report "
                "(re-run with the original flags, or start a fresh "
                "journal)")
        targets = journal.targets
        given = _given_targets(args)
        unknown = [t for t in given if t not in targets]
        if unknown:
            raise FatalError(
                f"targets not in journal {resume_path}: "
                f"{', '.join(unknown)} (a resume cannot grow the fleet)")
    else:
        targets = _given_targets(args)
        if not targets:
            raise FatalError("fleet scan needs at least one target "
                             "(positional and/or --targets FILE)")
        jpath = getattr(args, "journal", None)
        if jpath:
            try:
                journal = ScanJournal.create(
                    jpath, args.command, targets, fingerprint)
            except JournalError as e:
                raise FatalError(str(e))

    # ONE cache handle for every lane: layer analyses from concurrent
    # workers land in (and dedupe through) the same backend, and the
    # in-process singleflight registry sees one cache identity, so a
    # base layer shared across --fleet-parallel lanes is analyzed once
    cache = _build_cache(args)
    # --monitor-index: every completed artifact records its package
    # inventory + finding baseline into the shared durable index, so a
    # later `trivy-tpu watch` / DB promote re-scores this fleet
    # incrementally (docs/monitoring.md). Lanes share one handle —
    # updates serialize on the index lock.
    mon_index = open_monitor_index(args)
    mon_digest = None
    if mon_index is not None:
        from trivy_tpu.cli.run import _db_path
        from trivy_tpu.tensorize import cache as compile_cache

        # one digest for the whole fleet: the generation every lane's
        # baseline is matched against (stamped per index record)
        mon_digest = compile_cache.db_digest(_db_path(args))
        if journal is not None and resume_path:
            # artifacts already completed in the resumed journal are
            # skipped by the scan loop, so they would silently miss the
            # index: backfill from the embedded reports (null baseline,
            # like a rebuild — first re-score adopts silently) unless a
            # pre-crash record already covers them
            from trivy_tpu.monitor.index import packages_from_report

            for t, doc in journal.done.items():
                if mon_index.packages_of(t):
                    continue
                pkgs = packages_from_report(doc)
                if pkgs:
                    mon_index.update(t, pkgs, None)
    lane = {t: i + 1 for i, t in enumerate(targets)}  # stable fleet index
    reports: dict[str, dict] = dict(journal.done) if journal else {}
    todo = [t for t in targets if t not in reports]
    if journal and len(reports):
        _log.info("resuming fleet scan", done=len(reports), todo=len(todo),
                  layers_journaled=len(journal.layers))
    # snapshot the process-wide analysis counters so the summary line
    # reports THIS fleet's layers analyzed vs deduped
    analysis_base = (obs_metrics.LAYERS_ANALYZED.value(),
                     obs_metrics.LAYER_DEDUPE_HITS.value(),
                     obs_metrics.LAYER_DEDUPE_INFLIGHT_WAITS.value())

    def scan_one(target: str) -> None:
        # deterministic crash point for the kill-and-resume matrix
        faults.check_kill(FAULT_SITE)
        if os.environ.get("TRIVY_TPU_DETERMINISTIC_UUID") == "1":
            # per-artifact uuid lane, keyed by the stable fleet index:
            # a resumed run replays the exact ids of an uninterrupted
            # one (meaningful for sequential fleets; concurrent workers
            # share the counter after the jump)
            uuid_util.set_lane(lane[target])
        a = copy.copy(args)
        a.target = target
        if args.command == "image":
            # a fleet line that names an existing file is a tar archive,
            # anything else a registry reference
            a.input = target if os.path.exists(target) else None
        # each lane gets its own span (attached to the fleet root via
        # the pipeline's context adoption) and its own scan id, which
        # the artifact's log lines and inner spans inherit
        with tracing.scan_scope(force=True), \
                tracing.span("fleet.artifact", target=target,
                             lane=lane[target]):
            try:
                if mon_index is None:
                    report = _scan_target(a, cache)
                else:
                    from trivy_tpu.monitor.capture import capture_scan

                    with capture_scan() as cap:
                        report = _scan_target(a, cache)
                    mon_index.update(target, cap.packages, cap.findings,
                                     db_digest=mon_digest)
                _postprocess_report(a, report)
            except Exception as e:
                if journal:
                    journal.mark_failed(target, f"{type(e).__name__}: {e}")
                raise
        doc = report.to_dict()
        if journal:
            journal.mark_done(target, doc)  # fsynced before we move on
        reports[target] = doc

    on_start = None
    if journal:
        def on_start(_i, target):
            journal.mark_running(target)

    workers = max(1, int(getattr(args, "fleet_parallel", 1) or 1))
    try:
        # fleet-wide layer journal: every lane records completed layer
        # analyses, and a resumed crawl replays them as dedupe hints
        with tracing.span("fleet", artifacts=len(todo), workers=workers), \
                analysis_pipeline.journal_scope(
                    on_layer=journal.mark_layer if journal else None,
                    precompleted=set(journal.layers) if journal else None):
            run_pipeline(todo, scan_one, workers=workers,
                         on_start=on_start)
    except PipelineError as e:
        hint = (f"; completed work is journaled — re-run with "
                f"--resume {journal.path} to retry" if journal else "")
        raise FatalError(f"fleet scan: {e}{hint}")
    finally:
        if journal:
            journal.close()
        if mon_index is not None:
            mon_index.close()
        analyzed = obs_metrics.LAYERS_ANALYZED.value() - analysis_base[0]
        deduped = obs_metrics.LAYER_DEDUPE_HITS.value() - analysis_base[1]
        waits = obs_metrics.LAYER_DEDUPE_INFLIGHT_WAITS.value() \
            - analysis_base[2]
        if analyzed or deduped:
            _log.info("fleet analysis summary",
                      layers_analyzed=int(analyzed),
                      layers_deduped=int(deduped),
                      inflight_waits=int(waits),
                      dedupe_ratio=round(
                          deduped / max(analyzed + deduped, 1), 3))

    _write_fleet_report(args, targets, reports)
    # same exit-code policy as single-target scans (cli/run.py
    # _exit_code): findings first, then end-of-life OS
    if args.exit_code and any(_has_findings(reports[t]) for t in targets):
        return args.exit_code
    if getattr(args, "exit_on_eol", 0) and \
            any(_is_eosl(reports[t]) for t in targets):
        return args.exit_on_eol
    return 0


def _has_findings(doc: dict) -> bool:
    return any(
        r.get("Vulnerabilities") or r.get("Misconfigurations")
        or r.get("Secrets") or r.get("Licenses")
        for r in doc.get("Results") or [])


def _is_eosl(doc: dict) -> bool:
    return bool(((doc.get("Metadata") or {}).get("OS") or {}).get("EOSL"))


def _write_fleet_report(args, targets: list[str],
                        reports: dict[str, dict]) -> None:
    """Merged report, per-target documents in fleet order — the order
    and the embedded reports are journal-stable, so an interrupted +
    resumed fleet renders the same bytes as an uninterrupted one."""
    merged = {
        "SchemaVersion": 2,
        "CreatedAt": clock.now_rfc3339(),
        "ArtifactType": "fleet",
        "Targets": len(targets),
        "Reports": [reports[t] for t in targets],
    }
    data = json.dumps(merged, indent=2) + "\n"
    if getattr(args, "output", None):
        atomic_write(args.output, data.encode(), fault_site="report.write")
    else:
        sys.stdout.write(data)
