"""Central registry of every ``TRIVY_TPU_*`` environment knob.

One source of truth: the ``env-knob`` lint rule fails when code reads a
``TRIVY_TPU_*`` variable that is not declared here (or declares one
nothing reads), and ``docs/knobs.md`` is GENERATED from this table —
the rule also fails when that file is stale.  Regenerate with::

    python -m trivy_tpu.analysis.lint --write-knobs-doc

Exception by design: ``cli/config.py`` maps *every* CLI flag onto
``TRIVY_TPU_<FLAG>`` dynamically; that wildcard family is documented
below rather than enumerated.
"""

from __future__ import annotations

from dataclasses import dataclass

DOC_PATH = "docs/knobs.md"


@dataclass(frozen=True)
class Knob:
    name: str
    default: str       # rendered verbatim; "" shows as (unset)
    subsystem: str
    kill_switch: bool  # "set to 0 restores the pre-feature path"
    doc: str


KNOBS: tuple[Knob, ...] = (
    # --- resilience / fault injection
    Knob("TRIVY_TPU_FAULTS", "", "resilience", False,
         "Deterministic fault-injection plan (site:action@selector "
         "grammar, docs/resilience.md); validated at startup."),
    Knob("TRIVY_TPU_FAULT_SEED", "0", "resilience", False,
         "Default RNG seed for `@pF` probability selectors when the "
         "fault spec carries no `seed=` token — makes probabilistic "
         "specs replayable (chaos repros paste both knobs)."),
    # --- chaos campaign engine (docs/resilience.md "Chaos campaigns")
    Knob("TRIVY_TPU_CHAOS_SEED", "0", "chaos", False,
         "Campaign seed for `trivy-tpu chaos run`: derives every "
         "episode's fault schedule, so a campaign replays exactly."),
    Knob("TRIVY_TPU_CHAOS_EPISODES", "50", "chaos", False,
         "Episode count for `trivy-tpu chaos run` when --episodes is "
         "not given."),
    Knob("TRIVY_TPU_CHAOS_BUDGET_S", "30", "chaos", False,
         "Per-episode liveness watchdog budget (seconds): an episode "
         "that does not finish inside it is a liveness violation."),
    # --- scheduler (continuous batching)
    Knob("TRIVY_TPU_SCHED", "1", "sched", True,
         "Cross-request match scheduler; 0 restores the exact "
         "per-request detect path."),
    Knob("TRIVY_TPU_QOS", "1", "sched", True,
         "Per-tenant weighted fair-share on the coalesce queue "
         "(deficit round-robin over chunk interleaving); 0 restores "
         "the tenant-blind oldest-deadline-first compose."),
    Knob("TRIVY_TPU_QOS_TENANT_QUEUE", "", "sched", False,
         "Per-tenant queue-depth cap on the match scheduler; a "
         "tenant over its cap is shed (503 + Retry-After) while "
         "other tenants keep enqueueing. Unset/0 = the global "
         "--sched-max-queue only."),
    Knob("TRIVY_TPU_QOS_WEIGHTS", "", "sched", False,
         "Comma list of tenant=weight fair-share weights for the "
         "QoS compose, e.g. 'abc123=3,*=1' ('*' sets the default "
         "weight). Unset = every tenant weight 1."),
    # --- serving mesh
    Knob("TRIVY_TPU_MESH", "", "ops", False,
         "Serving-mesh topology: 'DPxDB' (e.g. 2x4), 'auto' (sized "
         "from DB rows + device count), unset/off = single-chip "
         "(same as --mesh)."),
    Knob("TRIVY_TPU_MESH_SHARD_RETRIES", "1", "ops", False,
         "Failed mesh shard dispatches retried before that shard's "
         "advisory slice degrades to the host oracle."),
    Knob("TRIVY_TPU_MESH_HBM_GB", "8.0", "ops", False,
         "Per-device HBM budget (GB) the 'auto' mesh topology sizes "
         "advisory shards against (per host on the distributed "
         "MeshDB)."),
    Knob("TRIVY_TPU_DCN", "", "ops", False,
         "Cross-host distributed-MeshDB workers: 'spawn' launches as "
         "many local worker subprocesses as the spec needs ('spawn:N' "
         "pins the count, validated against the spec), "
         "'host:port,...' connects pre-started workers (python -m "
         "trivy_tpu.ops.dcn --worker [--bind ADDR]); unset = "
         "single-host serving only."),
    Knob("TRIVY_TPU_DCN_TIMEOUT_S", "60", "ops", False,
         "Per-request DCN worker timeout (seconds) before the "
         "coordinator retries and then degrades that host's advisory "
         "slice to the bit-identical host mask."),
    # --- detector pipeline
    Knob("TRIVY_TPU_PIPELINE", "1", "detector", True,
         "Double-buffered host/device match executor; 0 runs the "
         "serial stage loop."),
    Knob("TRIVY_TPU_PIPELINE_WORKERS", "(auto)", "detector", False,
         "Crunch-lane thread count override for the pipelined "
         "executor; malformed values warn and fall back."),
    # --- artifact analysis pipeline
    Knob("TRIVY_TPU_ANALYSIS_PIPELINE", "1", "fanal", True,
         "Pipelined layer fetch/analyze with cross-image dedupe; 0 "
         "restores the serial layer loop byte-identically."),
    Knob("TRIVY_TPU_ANALYSIS_PREFETCH", "2", "fanal", False,
         "Layer-prefetch depth: compressed layers allowed in flight "
         "ahead of the analyzing thread."),
    Knob("TRIVY_TPU_ANALYSIS_WORKERS", "5", "fanal", False,
         "Walk-lane count for the multi-lane layer executor; "
         "overrides --parallel, clamped to [1, 32]; malformed values "
         "warn and fall back."),
    Knob("TRIVY_TPU_NATIVE_SPLIT", "1", "fanal", True,
         "Native streaming gunzip+tar splitter on the layer walk; 0 "
         "restores the pure-Python tarfile walk (also the automatic "
         "fallback when no toolchain is present)."),
    Knob("TRIVY_TPU_VECTOR_ANALYZERS", "1", "fanal", True,
         "Vectorized hot analyzers (packed-trigram license "
         "classification, numpy yarn.lock tokenization); 0 restores "
         "the scalar engines, which stay byte-identical either way."),
    # --- compiled-DB cache
    Knob("TRIVY_TPU_COMPILE_CACHE", "1", "tensorize", True,
         "Persistent compiled-DB tensor cache; 0 recompiles from the "
         "advisory DB on every start."),
    # --- continuous monitoring (advisory-delta re-scoring)
    Knob("TRIVY_TPU_MONITOR", "1", "monitor", True,
         "Advisory-delta monitor subsystem; 0 stops scans recording "
         "index state and promotes triggering re-scores."),
    Knob("TRIVY_TPU_DELTA_FULL_THRESHOLD", "0.5", "monitor", False,
         "Touched-key fraction above which a delta re-score degrades "
         "to re-matching every indexed artifact."),
    Knob("TRIVY_TPU_DELTA_VERIFY", "", "monitor", False,
         "1 makes every delta re-score cross-check itself against a "
         "from-scratch full re-match (double work; CI paranoia)."),
    Knob("TRIVY_TPU_DELTA_BUDGET_S", "", "monitor", False,
         "Wall-time budget (seconds) for one delta re-score; on "
         "expiry the sweep sheds and the index state is not advanced."),
    # --- secret engine
    Knob("TRIVY_TPU_SECRET_PROBE", "1", "secret", True,
         "Hybrid-mode device-vs-host timing probe; 0 skips the probe "
         "and uses the host AC path."),
    Knob("TRIVY_TPU_SECRET_DEVICE_SHARE", "(scanner default)", "secret",
         False,
         "Byte fraction the hybrid secret split hands the device "
         "anchor screen."),
    Knob("TRIVY_TPU_SECRET_PACK_MB", "(per-bank default)", "secret",
         False,
         "Packed super-buffer MiB per device anchor-screen dispatch "
         "(the secret engine's dispatch-amortization lever; same as "
         "--secret-pack-mb)."),
    Knob("TRIVY_TPU_SECRET_STREAM_CHUNK_MB", "4", "secret", False,
         "Streaming secret-scan chunk MiB for files over 10 MiB "
         "(floor 64 KiB; same as --secret-stream-chunk-mb)."),
    # --- fleet serving tier
    Knob("TRIVY_TPU_FLEET", "1", "fleet", True,
         "Fleet smart-client + cache-tier features; 0 pins multi-URL "
         "clients to their first endpoint through the exact "
         "single-server path and keeps the in-process layer gate on "
         "redis caches."),
    Knob("TRIVY_TPU_FLEET_HEDGE_MS", "75", "fleet", False,
         "Hedge delay: milliseconds a scan may sit unanswered on its "
         "primary replica before the same request is raced on a "
         "second one (first response wins, zero diff); 0 disables "
         "hedging."),
    Knob("TRIVY_TPU_FLEET_HEDGE_BUDGET", "0.1", "fleet", False,
         "Max fraction of requests allowed to hedge (bounds the "
         "duplicate-work cost of a uniformly slow fleet)."),
    Knob("TRIVY_TPU_FLEET_HEALTH_INTERVAL_S", "5", "fleet", False,
         "Period of the smart client's background /readyz (JSON) "
         "health prober over the endpoint set."),
    Knob("TRIVY_TPU_FLEET_EVENTS", "1", "fleet", True,
         "Fleet ops event bus (docs/fleet.md 'Event catalog'): "
         "failovers, hedge outcomes, breaker/health transitions, "
         "rollout stages, replica skew, SLO burn alerts — ringed, "
         "counted, and journaled when a journal is installed; 0 "
         "restores the pre-feature path (no emission at all)."),
    Knob("TRIVY_TPU_FLEET_EVENTS_JOURNAL", "", "fleet", False,
         "Path of a durable fleet ops event journal THIS process "
         "installs lazily on its first emit — the way a scan client "
         "makes its failover/hedge/breaker events durable (the event "
         "bus is process-local; use one path per process)."),
    Knob("TRIVY_TPU_FLEET_SLO_TARGET", "0.999", "fleet", False,
         "Fleet availability SLO target the burn-rate engine "
         "evaluates multi-window alerts against (burn = error rate / "
         "(1 - target))."),
    Knob("TRIVY_TPU_FLEET_SLO_LATENCY_MS", "", "fleet", False,
         "Latency SLI threshold in milliseconds: a successful request "
         "slower than this counts against the SLO budget (unset = "
         "availability-only SLO)."),
    # --- fleet controller (docs/fleet.md "Self-driving fleet")
    Knob("TRIVY_TPU_CONTROLLER", "1", "fleet", True,
         "Fleet controller kill switch: 0 makes every tick observe "
         "and decide nothing — exactly the pre-controller fleet."),
    Knob("TRIVY_TPU_CONTROLLER_MIN_REPLICAS", "1", "fleet", False,
         "Autoscaler cost floor: the controller never drains the "
         "fleet below this many replicas, however calm the load."),
    Knob("TRIVY_TPU_CONTROLLER_MAX_REPLICAS", "4", "fleet", False,
         "Autoscaler ceiling: the controller never spawns past this "
         "many replicas, however hot the load."),
    Knob("TRIVY_TPU_CONTROLLER_SCALE_UP_LOAD", "4", "fleet", False,
         "Offered load per ready replica above which the controller "
         "spawns one replica (subject to the ceiling and cooldown)."),
    Knob("TRIVY_TPU_CONTROLLER_SCALE_DOWN_LOAD", "1", "fleet", False,
         "Offered load per ready replica below which a tick counts "
         "as calm toward the scale-down hysteresis window."),
    Knob("TRIVY_TPU_CONTROLLER_HOLDS", "3", "fleet", False,
         "Scale-down hysteresis: consecutive calm ticks required "
         "before one replica is drained (any non-calm tick resets "
         "the streak — one quiet minute never shrinks the fleet)."),
    Knob("TRIVY_TPU_CONTROLLER_COOLDOWN_S", "30", "fleet", False,
         "Per-action-kind cooldown seconds between controller "
         "actions (damps oscillation: scale/drain/re-resolve each "
         "rate-limited independently)."),
    Knob("TRIVY_TPU_CONTROLLER_UNHEALTHY_TICKS", "3", "fleet", False,
         "Consecutive failed-probe ticks before a replica is "
         "drained, retired, and replaced (drain_replace)."),
    Knob("TRIVY_TPU_CONTROLLER_DEGRADED_TICKS", "3", "fleet", False,
         "Consecutive ticks a replica must report degraded mesh "
         "hosts before the controller tells it to re-resolve its "
         "topology over the survivors (mesh_reresolve)."),
    Knob("TRIVY_TPU_CONTROLLER_HEDGE_SKEW", "4", "fleet", False,
         "p99/p50 probe-latency skew at which the controller raises "
         "the hedge budget; below half this, the budget returns to "
         "the configured baseline (hedge_tune)."),
    # --- RPC
    Knob("TRIVY_TPU_RPC_GZIP_MIN", "8192", "rpc", False,
         "Minimum body size in bytes before the negotiated gzip wire "
         "framing compresses a request/response."),
    Knob("TRIVY_TPU_WIRE", "1", "rpc", True,
         "Binary columnar RPC wire (application/x-trivy-columnar). 0 "
         "at either end disables the negotiation: the client stops "
         "offering, the server stops advertising and 400s columnar "
         "bodies WITHOUT the capability header so clients unlearn "
         "and resend JSON (docs/performance.md)."),
    # --- observability
    Knob("TRIVY_TPU_TRACE", "", "obs", False,
         "Enable span collection without the --trace flag (1/true)."),
    Knob("TRIVY_TPU_SLOW_SPAN_MS", "", "obs", False,
         "Log any span exceeding this many milliseconds, even with "
         "tracing off."),
    Knob("TRIVY_TPU_JAX_TRACE_DIR", "", "obs", False,
         "Directory for JAX profiler dumps alongside --trace-export."),
    Knob("TRIVY_TPU_ATTRIB", "", "obs", True,
         "Span-to-resource-lane bottleneck attribution "
         "(docs/observability.md): unset = on while a scan server "
         "runs, 1 forces it on for one-shot CLI scans, 0 disables "
         "the aggregator entirely (pre-feature span fast path)."),
    Knob("TRIVY_TPU_FLIGHT_RECORDER_N", "8", "obs", False,
         "Slow-scan flight recorder ring size: the N slowest scan "
         "traces kept live for /debug/flight Chrome-JSON export "
         "(0 disables the recorder)."),
    Knob("TRIVY_TPU_PROFILE_TOKEN", "", "obs", False,
         "Dedicated auth token for the server's /debug/profile and "
         "/debug/flight endpoints (grants profiling access without "
         "the scan/cache token; the scan token always works too)."),
    Knob("TRIVY_TPU_USAGE", "", "obs", True,
         "Per-tenant usage metering (docs/observability.md 'Usage "
         "metering'): unset/1 = on (the server opens a cost-vector "
         "scope per request), 0 disables scope creation entirely — "
         "every accrual call short-circuits on one contextvar read."),
    Knob("TRIVY_TPU_USAGE_TOP_N", "64", "obs", False,
         "Distinct tenants tracked by the usage registry and the "
         "trivy_tpu_tenant_* metrics before new arrivals collapse "
         "into the 'other' bucket (cardinality bound)."),
    Knob("TRIVY_TPU_USAGE_JOURNAL", "", "obs", False,
         "Path of the per-interval usage journal (durability/"
         "appendlog format: torn-tail-tolerant replay, compaction); "
         "empty disables journaling."),
    Knob("TRIVY_TPU_USAGE_INTERVAL_S", "60", "obs", False,
         "Seconds between cumulative usage-journal snapshots (the "
         "journal also syncs once at server shutdown)."),
    # --- analysis (this package)
    Knob("TRIVY_TPU_LOCK_WITNESS", "", "analysis", False,
         "1 wraps the project's named locks in the lock-order witness "
         "(cycle detection at test teardown); off = raw primitives."),
    # --- CLI / environment plumbing
    Knob("TRIVY_TPU_CACHE_DIR", "~/.cache/trivy-tpu", "cli", False,
         "Scan/artifact cache directory (same as --cache-dir)."),
    Knob("TRIVY_TPU_USERNAME", "", "cli", False,
         "Default registry username (same as --username)."),
    Knob("TRIVY_TPU_PASSWORD", "", "cli", False,
         "Default registry password (same as --password)."),
    # --- utils
    Knob("TRIVY_TPU_DETERMINISTIC_UUID", "", "utils", False,
         "1 makes scan/lane UUIDs a deterministic sequence so fleet "
         "goldens byte-match."),
    Knob("TRIVY_TPU_FAKE_TIME", "", "utils", False,
         "Fixed ISO timestamp for the report clock (golden tests)."),
    # --- modules / native
    Knob("TRIVY_TPU_TRUST_STORE", "", "module", False,
         "Override path for the scan-module trust manifest."),
    Knob("TRIVY_TPU_NATIVE_DIR", "~/.cache/trivy-tpu/native", "native",
         False,
         "Build/cache directory for the native AC helper library."),
    # --- bench harness (bench.py only)
    Knob("TRIVY_TPU_DEVICE_WAIT", "900", "bench", False,
         "Total seconds bench.py spends acquiring the device before "
         "falling back to CPU."),
    Knob("TRIVY_TPU_MICRO_WAIT", "600", "bench", False,
         "Per-attempt device-acquire budget for the bench supervisor."),
    Knob("TRIVY_TPU_FORCE_CPU", "", "bench", False,
         "1 pins the bench child to the CPU backend."),
    Knob("TRIVY_TPU_BENCH_ADVISORIES", "500000", "bench", False,
         "Synthetic advisory-DB size for the bench run."),
    Knob("TRIVY_TPU_BENCH_QUERIES", "240000", "bench", False,
         "Synthetic package-query count for the bench crawl."),
    Knob("TRIVY_TPU_BENCH_NO_PROBE", "", "bench", False,
         "1 skips the subprocess device probe."),
    Knob("TRIVY_TPU_BENCH_RUN_TIMEOUT", "1500", "bench", False,
         "Seconds before the supervisor kills a wedged bench child."),
    Knob("TRIVY_TPU_BENCH_CHILD", "", "bench", False,
         "Internal: set by the supervisor on the re-exec'd child."),
    Knob("TRIVY_TPU_BENCH_DEVICE_STATUS", "unknown", "bench", False,
         "Internal: device probe verdict handed to the child."),
    Knob("TRIVY_TPU_BENCH_PHASE_JSON", "", "bench", False,
         "Internal: --phase-json path surviving the supervised "
         "re-exec."),
    Knob("TRIVY_TPU_BENCH_SCHED_CLIENTS", "8", "bench", False,
         "Concurrent keep-alive clients in the serving bench."),
    Knob("TRIVY_TPU_BENCH_SECRET_CLIENTS", "6", "bench", False,
         "Concurrent scans in the scheduler-batched secret bench "
         "rung."),
    Knob("TRIVY_TPU_BENCH_SCHED_SCANS", "6", "bench", False,
         "Scans per client in the serving bench."),
    Knob("TRIVY_TPU_BENCH_ANALYSIS_IMAGES", "10", "bench", False,
         "Synthetic-registry image count in the analysis bench."),
    Knob("TRIVY_TPU_BENCH_MESH_PODS", "10000", "bench", False,
         "Synthetic pod count for the mesh-serving bench crawl "
         "(BASELINE config #5 shape)."),
    Knob("TRIVY_TPU_BENCH_MESH_CHILD", "", "bench", False,
         "Internal: set on the CPU-mesh subprocess the mesh bench "
         "spawns (8 virtual devices)."),
    Knob("TRIVY_TPU_BENCH_DELTA_KEYS", "50000", "bench", False,
         "Advisory (space, name) key count for the delta-rescore "
         "bench's synthetic DB generations."),
    Knob("TRIVY_TPU_BENCH_DELTA_ARTIFACTS", "200", "bench", False,
         "Journaled-artifact count for the delta-rescore bench's "
         "synthetic fleet."),
    Knob("TRIVY_TPU_BENCH_CAPSTONE_IMAGES", "6", "bench", False,
         "Synthetic-registry image count for the capstone "
         "end-to-end bench (BASELINE configs #4/#5 as one system)."),
    Knob("TRIVY_TPU_BENCH_CAPSTONE_CLIENTS", "4", "bench", False,
         "Concurrent fleet clients crawling the capstone bench's "
         "live server."),
    Knob("TRIVY_TPU_BENCH_CAPSTONE_PODS", "240", "bench", False,
         "Pod-scan count for the capstone bench's cluster "
         "(config #5) phase; pods round-robin over the registry "
         "images so artifact-level dedupe engages."),
    Knob("TRIVY_TPU_BENCH_CAPSTONE_CHILD", "", "bench", False,
         "Internal: set on the 8-virtual-device subprocess the "
         "capstone bench spawns."),
    Knob("TRIVY_TPU_BENCH_DCN_ADVISORIES", "320000", "bench", False,
         "Synthetic advisory-DB size for the cross-host serving bench "
         "(the TRIVY_TPU_SCALE_FULL 2M shape, scaled for CI)."),
    Knob("TRIVY_TPU_BENCH_DCN_QUERIES", "40000", "bench", False,
         "Synthetic package-query count for the cross-host serving "
         "bench crawl."),
    Knob("TRIVY_TPU_BENCH_DCN_CHILD", "", "bench", False,
         "Internal: set on the 4-virtual-device coordinator "
         "subprocess the DCN bench spawns."),
    Knob("TRIVY_TPU_BENCH_FLEET_REPLICAS", "3", "bench", False,
         "Replica-set size for the fleet-serving bench."),
    Knob("TRIVY_TPU_BENCH_FLEET_CLIENTS", "6", "bench", False,
         "Concurrent smart clients in the fleet-serving bench."),
    Knob("TRIVY_TPU_BENCH_FLEET_SCANS", "8", "bench", False,
         "Scans per client in the fleet-serving bench."),
    Knob("TRIVY_TPU_BENCH_WIRE_CLIENTS", "6", "bench", False,
         "Concurrent keep-alive clients in the columnar-wire bench."),
    Knob("TRIVY_TPU_BENCH_WIRE_SCANS", "8", "bench", False,
         "Scans per client in the columnar-wire bench."),
)



def generate_knobs_md(knob_list=None) -> str:
    """The exact content of docs/knobs.md (byte-compared by the
    ``env-knob`` lint rule; regenerate via --write-knobs-doc).
    ``knob_list`` lets the linter render from the LINTED tree's
    extracted table so ``--root worktree`` staleness is judged against
    the worktree's own registry; default is this module's KNOBS."""
    knob_list = KNOBS if knob_list is None else knob_list
    lines = [
        "# `TRIVY_TPU_*` environment knobs",
        "",
        "<!-- GENERATED from trivy_tpu/analysis/knobs.py — do not edit",
        "     by hand.  Regenerate with:",
        "         python -m trivy_tpu.analysis.lint --write-knobs-doc",
        "     The env-knob lint rule fails when this file is stale. -->",
        "",
        "Every environment variable the scanner reads, from one",
        "registry (`trivy_tpu/analysis/knobs.py`).  *Kill-switch — yes*",
        "means setting the knob to `0` restores the exact pre-feature",
        "code path (the zero-diff escape hatch for each perf layer).",
        "",
        "| Name | Default | Subsystem | Kill-switch | What it does |",
        "|---|---|---|---|---|",
    ]
    for k in sorted(knob_list, key=lambda k: (k.subsystem, k.name)):
        default = f"`{k.default}`" if k.default else "(unset)"
        lines.append(
            f"| `{k.name}` | {default} | {k.subsystem} | "
            f"{'yes' if k.kill_switch else 'no'} | {k.doc} |")
    lines += [
        "",
        "Additionally, **every CLI flag** is settable as",
        "`TRIVY_TPU_<FLAG>` (upper-cased, `-` → `_`): explicit",
        "command-line values win, then the environment, then the",
        "config file (`trivy_tpu/cli/config.py`).  That wildcard",
        "family is intentionally not enumerated here.",
        "",
        "See [docs/performance.md](performance.md) for what the",
        "perf-layer kill-switches disable, and",
        "[docs/static-analysis.md](static-analysis.md) for the lint",
        "rule that keeps this table honest.",
        "",
    ]
    return "\n".join(lines)
