"""Static companion to the lock witness: ``with <lock>`` nesting → graph.

The runtime witness (``analysis.witness``) only sees interleavings the
tests actually drive.  This pass reads every ``with`` statement in the
tree and records the lock-nesting pairs the *code* can produce, using
the same naming convention the witness uses
(``<module-under-trivy_tpu>.<attr>``), so the two graphs union into one
order check: an edge witnessed at runtime in one direction and written
statically in the other is a lock inversion even if no test ever
interleaved it.

Heuristics (documented limitations, not bugs):

- a ``with`` item counts as a lock when it is a bare attribute or name
  whose identifier contains ``lock``, ``cond`` or ``mutex`` (the
  project convention) — ``with self._cond:``, ``with _CONN_POOL_LOCK:``;
- nesting is tracked lexically within one function body; cross-function
  nesting (helper called under a held lock that takes another lock) is
  the runtime witness's job;
- ``with registry.locked():`` — a *call* — is invisible here; the
  runtime witness covers the metrics registry;
- the name is keyed on the *use-site* module (no type inference), so a
  lock reached through another object's attribute (``with
  self.cdb._intern_lock:`` in detector/engine.py) gets a
  ``detector.engine.*`` alias while the runtime witness names it by its
  creation site (``tensorize.compile._intern_lock``) — an inversion
  split across the two aliases is only caught when the runtime witness
  observes both arms itself.
"""

from __future__ import annotations

import ast
import os
import re

LOCK_ID_RX = re.compile(r"lock|cond|mutex", re.IGNORECASE)


def lock_name(item: ast.expr, module: str) -> str | None:
    """The witness-convention name for a with-item, or None if the
    expression does not look like a named lock."""
    if isinstance(item, ast.Attribute) and LOCK_ID_RX.search(item.attr):
        return f"{module}.{item.attr}"
    if isinstance(item, ast.Name) and LOCK_ID_RX.search(item.id):
        return f"{module}.{item.id}"
    return None


def module_name(relpath: str) -> str:
    """``trivy_tpu/sched/scheduler.py`` -> ``sched.scheduler`` (the
    witness naming root).  Files outside trivy_tpu/ keep their stem."""
    p = relpath.replace(os.sep, "/")
    if p.startswith("trivy_tpu/"):
        p = p[len("trivy_tpu/"):]
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class _Extractor(ast.NodeVisitor):
    """Collects (outer, inner, line) nesting triples per function."""

    def __init__(self, module: str):
        self.module = module
        self.stack: list[str] = []
        self.edges: list[tuple[str, str, int]] = []
        self.names: set[str] = set()

    # a fresh lexical scope gets a fresh nesting stack
    def _scoped(self, node) -> None:
        saved, self.stack = self.stack, []
        self.generic_visit(node)
        self.stack = saved

    visit_FunctionDef = visit_AsyncFunctionDef = visit_Lambda = _scoped

    def visit_With(self, node: ast.With) -> None:
        taken: list[str] = []
        for item in node.items:
            name = lock_name(item.context_expr, self.module)
            if name is None:
                continue
            self.names.add(name)
            for held in self.stack + taken:
                if held != name:
                    self.edges.append((held, name, node.lineno))
            taken.append(name)
        self.stack.extend(taken)
        for stmt in node.body:
            self.visit(stmt)
        del self.stack[len(self.stack) - len(taken):]


def extract(relpath: str, tree: ast.AST) -> _Extractor:
    ex = _Extractor(module_name(relpath))
    ex.visit(tree)
    return ex


def static_graph(files) -> tuple[dict[str, set[str]],
                                 dict[tuple[str, str], tuple[str, int]]]:
    """Build the whole-tree static nesting graph.

    ``files`` yields ``(relpath, ast_tree)``.  Returns ``(edges,
    where)`` with ``where[(a, b)] = (relpath, line)`` of the first
    occurrence, for diagnostics."""
    edges: dict[str, set[str]] = {}
    where: dict[tuple[str, str], tuple[str, int]] = {}
    for relpath, tree in files:
        ex = extract(relpath, tree)
        for a, b, line in ex.edges:
            edges.setdefault(a, set()).add(b)
            where.setdefault((a, b), (relpath, line))
    return edges, where


def union(*graphs: dict[str, set[str]]) -> dict[str, set[str]]:
    """Union adjacency-set graphs (runtime witness + static pass)."""
    out: dict[str, set[str]] = {}
    for g in graphs:
        for a, bs in g.items():
            out.setdefault(a, set()).update(bs)
    return out
