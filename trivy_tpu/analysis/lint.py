"""Project invariant linter entry point.

Run as a module or via the CLI subcommand::

    python -m trivy_tpu.analysis.lint [--json] [--baseline FILE]
        [--root DIR] [--rule ID ...] [--list-rules] [--write-knobs-doc]
    trivy-tpu lint [same flags]

Exit codes: 0 clean, 1 findings, 2 usage/internal error.  The tier-1
enforcement test (tests/test_analysis.py) and bench.py's exit-code
path both call :func:`run_lint`, so a lint regression fails
verification, not just this command.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# rules/knobs (the AST machinery) import lazily inside the functions
# that need them: cli/main.py imports this module on EVERY invocation
# just to register the `lint` subcommand's flags

DEFAULT_BASELINE = ".lint-baseline.json"


def repo_root() -> str:
    """The tree this package was loaded from (…/trivy_tpu/analysis/..)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def is_project_tree(root: str) -> bool:
    """True when `root` is a source checkout, not an installed package.

    Several rules check repo-level artifacts (docs/, bench.py, the
    baseline) that wheels do not ship; linting site-packages would
    report phantom doc-missing / knob-unread findings on a healthy
    install, so the CLI refuses with a clear message instead."""
    return any(os.path.exists(os.path.join(root, marker))
               for marker in ("pyproject.toml", "README.md"))


def run_lint(root: str | None = None, rule_ids=None,
             baseline_path: str | None = None):
    """-> (findings, suppressed).  `baseline_path=None` uses the
    default baseline file when present; "" disables baselines."""
    from trivy_tpu.analysis import rules

    root = root or repo_root()
    if baseline_path is None:
        cand = os.path.join(root, DEFAULT_BASELINE)
        baseline_path = cand if os.path.exists(cand) else ""
    baseline = rules.load_baseline(baseline_path) if baseline_path else []
    project = rules.Project(root)
    return rules.run(project, rule_ids=rule_ids, baseline=baseline)


def add_arguments(ap) -> None:
    """Register the lint flags on ``ap`` — shared between this module's
    own parser and the ``trivy-tpu lint`` subcommand (one definition,
    so the CLI accepts exactly what ``python -m`` accepts)."""
    ap.add_argument("--root", default=None,
                    help="tree to lint (default: the installed repo)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help=f"baseline file (default: {DEFAULT_BASELINE} "
                         "at the root if present; '' disables)")
    ap.add_argument("--rule", action="append", default=None, metavar="ID",
                    help="run only this rule (repeatable)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--write-knobs-doc", action="store_true",
                    help="regenerate docs/knobs.md from analysis.knobs "
                         "and exit")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="trivy-tpu lint",
        description="project invariant linter (docs/static-analysis.md)")
    add_arguments(ap)
    return run_from_args(ap.parse_args(argv))


def run_from_args(args) -> int:
    """The post-parse half of :func:`main` — the ``trivy-tpu lint``
    subcommand dispatches here with the namespace the main CLI parsed."""
    from trivy_tpu.analysis import knobs, rules

    if args.list_rules:
        for rid, cls in sorted(rules.RULES.items()):
            print(f"{rid}: {cls.summary}")
        return 0

    root = args.root or repo_root()
    if not is_project_tree(root):
        print(f"lint: {root} does not look like a trivy-tpu source "
              "checkout (no pyproject.toml or README.md) — the linter "
              "validates repo-level invariants (docs/, bench.py) that "
              "installed packages do not ship; pass --root "
              "PATH-TO-CHECKOUT", file=sys.stderr)
        return 2
    if args.write_knobs_doc:
        # render from the TARGET tree's extracted table, matching what
        # the env-knob staleness check will compare against
        declared = rules.Project(root).declared_knobs
        path = os.path.join(root, knobs.DOC_PATH)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # plain write: docs are derived artifacts, regenerated at will
        with open(path, "w", encoding="utf-8") as f:  # lint: allow[atomic-write] generated doc, rewritten idempotently from the registry
            f.write(knobs.generate_knobs_md(declared))
        print(f"wrote {path}")
        return 0

    if args.rule:
        unknown = set(args.rule) - set(rules.RULES)
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
    try:
        findings, suppressed = run_lint(
            root=root, rule_ids=set(args.rule) if args.rule else None,
            baseline_path=args.baseline)
    except (OSError, ValueError, SyntaxError) as exc:
        print(f"lint failed: {exc}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({
            "findings": [f.as_dict() for f in findings],
            "suppressed": [
                {**f.as_dict(), "via": via} for f, via in suppressed],
            "rules": sorted(rules.RULES),
            "clean": not findings,
        }, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        print(f"{len(findings)} finding(s), {len(suppressed)} "
              "suppressed" + ("" if findings else " — clean"))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
