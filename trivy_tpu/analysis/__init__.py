"""Project invariant linter + concurrency witness (docs/static-analysis.md).

Six PRs of threaded serving work (scheduler micro-batches, pipeline
lanes, layer singleflight, fleet lanes, TTL server gates) rest on
conventions no tool enforced: durable writes go through
``durability/atomic.py``, fault sites appear in the ``faults.py``
grammar and docs, ``trivy_tpu_*`` metrics are cataloged with bounded
labels, cross-thread submissions use the capture/adopt tracing idiom,
``TRIVY_TPU_*`` knobs are declared and documented, and named locks are
acquired in one global order.  This package machine-checks all of it:

- ``analysis.lint`` — AST project linter (``python -m
  trivy_tpu.analysis.lint`` or the ``lint`` CLI subcommand) with a
  pluggable rule framework, inline suppressions, a JSON report mode
  and a baseline file for staged fixes.
- ``analysis.witness`` — opt-in (``TRIVY_TPU_LOCK_WITNESS=1``) runtime
  lock-acquisition-order graph over the project's named locks, with
  cycle detection at test teardown.
- ``analysis.lockstatic`` — static companion: extracts ``with <lock>``
  nesting from the AST and cross-checks it against the witnessed
  runtime graph.
- ``analysis.knobs`` — the central ``TRIVY_TPU_*`` env-knob registry
  that ``docs/knobs.md`` is generated from.

This ``__init__`` stays import-light on purpose: production modules
import ``analysis.witness`` at module load to name their locks, and
that import must not drag in the AST machinery.
"""
