"""Runtime lock-order witness: a lock-acquisition-order graph.

Deadlock by lock inversion (thread 1 takes A then B, thread 2 takes B
then A) only materializes under a losing interleaving — a test suite
can pass forever while carrying one.  The witness makes the *order*
observable on ANY interleaving: every named lock records, at acquire
time, an edge from each lock the acquiring thread already holds to the
lock being taken.  A cycle in that graph is a potential deadlock even
if no run ever deadlocked.

Opt-in and zero-cost when off: production modules create their locks
through :func:`make_lock`, which returns the *raw* ``threading``
primitive unchanged unless ``TRIVY_TPU_LOCK_WITNESS=1`` is set at
creation time — the disabled path adds one function call per lock
*creation*, nothing per acquisition (guarded by a tier-1 overhead
test, mirroring the tracing slow-mark guard).

Naming convention (load-bearing — the static companion pass in
``analysis.lockstatic`` derives the same names from the AST so the two
graphs can be unioned): ``<module path under trivy_tpu, dotted>.<attr>``,
e.g. ``sched.scheduler._cond`` for ``self._cond`` in
``trivy_tpu/sched/scheduler.py``.

The pytest conftest enables the witness for the concurrency-marked
suites (sched / fanal / obs / durability) and fails any test that
leaves a cycle in the graph at teardown.

Known boundary: the enable check runs at lock CREATION, so locks
created at import time (module-level ``_CONN_POOL_LOCK``-style
globals, imported during collection before any fixture sets the env)
stay raw under the per-test fixture — only objects constructed inside
an enabled test are witnessed.  Their acquisition order is still
covered by the static ``with``-nesting pass (``analysis.lockstatic``),
whose graph is unioned with the runtime graph in the tier-1 acyclicity
test; for a full-process runtime witness, export
``TRIVY_TPU_LOCK_WITNESS=1`` before interpreter start.

Known boundary: the graph is keyed by lock NAME (one node per lock
*class*, e.g. every journal's ``durability.journal._lock`` is one
node), because names are what the static pass can derive and what an
order discipline is stated over.  Re-entrancy is still distinguished
per INSTANCE — holding journal A's lock while taking journal B's
records every cross-name edge — but the A→B vs B→A inversion *between
two same-named instances* collapses to a single node and is invisible
to both passes.  Code that nests two instances of one lock class must
impose its own tiebreak order (e.g. by id()) and say so at the site.
"""

from __future__ import annotations

import os
import threading

ENV = "TRIVY_TPU_LOCK_WITNESS"


def enabled() -> bool:
    return os.environ.get(ENV, "") not in ("", "0")


class LockWitness:
    """The process-wide acquisition-order graph.

    Thread-held state is a per-thread stack of lock names; edges are
    recorded under one internal leaf lock (never held while acquiring
    a witnessed lock, so the witness itself cannot deadlock)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._edges: dict[str, set[str]] = {}
        self._edge_info: dict[tuple[str, str], str] = {}
        self._tls = threading.local()
        # diagnostics: witnessed-acquisition count, kept as per-thread
        # cells (registered once per thread per generation) so the hot
        # path never touches _mu just to count — a process-global mutex
        # per acquire would serialize the very cross-thread
        # interleavings the witness-enabled tests exist to exercise
        self._counters: list[list[int]] = []
        # bumped by reset(): a thread that outlives a reset (daemon
        # worker parked in Condition.wait across tests) must not leak
        # its pre-reset held-stack into the fresh graph — its stale
        # stack is discarded on first touch (conservative: a held lock
        # from the old generation records no edge, rather than a
        # fabricated cross-test one).  Same idiom as tracing.reset().
        self._gen = 0

    # ------------------------------------------------------ recording

    def _stack(self) -> list[tuple[str, int]]:
        """Per-thread held stack of ``(name, key)`` pairs — key is the
        wrapped primitive's id(), so RLock re-entrancy is recognized per
        INSTANCE while the edge graph stays keyed by name."""
        st = getattr(self._tls, "stack", None)
        if st is None or getattr(self._tls, "gen", -1) != self._gen:
            st = self._tls.stack = []
            cell = self._tls.count = [0]
            self._tls.gen = self._gen
            with self._mu:
                self._counters.append(cell)
        return st

    def push(self, name: str, key: int | None = None) -> None:
        """Record that this thread acquired `name` (call AFTER the real
        acquire succeeds, so a blocked acquire never records)."""
        if key is None:
            key = hash(name)
        st = self._stack()
        self._tls.count[0] += 1
        if not any(k == key for _, k in st):  # re-entrant re-acquire of
            # the SAME instance: no new edges.  A same-named but
            # DISTINCT lock still records edges from every other held
            # name (self-name edges skipped — see module docstring).
            held = {h for h, _ in st if h != name}
            if held:
                thread = threading.current_thread().name
                with self._mu:
                    for h in held:
                        self._edges.setdefault(h, set()).add(name)
                        self._edge_info.setdefault((h, name), thread)
        st.append((name, key))

    def pop(self, name: str, key: int | None = None) -> None:
        if key is None:
            key = hash(name)
        st = self._stack()
        # release order need not be LIFO; drop the most recent entry
        for i in range(len(st) - 1, -1, -1):
            if st[i] == (name, key):
                del st[i]
                return

    # ------------------------------------------------------ inspection

    def edges(self) -> dict[str, set[str]]:
        with self._mu:
            return {a: set(bs) for a, bs in self._edges.items()}

    def acquired_total(self) -> int:
        """Witnessed acquisitions so far — lets tests assert the
        wiring is live even when no two locks ever nested (an empty
        edge set is the GOOD outcome, not proof nothing ran)."""
        with self._mu:
            return sum(c[0] for c in self._counters)

    def edge_thread(self, a: str, b: str) -> str:
        with self._mu:
            return self._edge_info.get((a, b), "")

    def find_cycle(self) -> list[str] | None:
        """A lock-name cycle ``[a, b, ..., a]`` if the witnessed order
        graph has one, else None."""
        return find_cycle(self.edges())

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._edge_info.clear()
            # surviving threads re-register a fresh cell on first
            # touch via the generation check in _stack()
            self._counters.clear()
            self._gen += 1

    def report(self) -> str:
        """Human-readable graph dump for test-failure messages."""
        lines = []
        for a in sorted(self.edges()):
            for b in sorted(self.edges()[a]):
                lines.append(f"  {a} -> {b}  (first: {self.edge_thread(a, b)})")
        cyc = self.find_cycle()
        if cyc:
            lines.append("  CYCLE: " + " -> ".join(cyc))
        return "lock-order graph:\n" + ("\n".join(lines) or "  (empty)")


def find_cycle(edges: dict[str, set[str]]) -> list[str] | None:
    """DFS cycle search over an adjacency-set graph; returns the cycle
    path ``[a, ..., a]`` or None.  Shared with the static pass so the
    runtime graph, the static graph, and their union all use one
    detector."""
    WHITE, GREY, BLACK = 0, 1, 2
    color: dict[str, int] = {}
    parent: dict[str, str] = {}

    def dfs(node: str) -> list[str] | None:
        color[node] = GREY
        for nxt in sorted(edges.get(node, ())):
            c = color.get(nxt, WHITE)
            if c == GREY:  # back edge: unwind node..nxt
                path = [node]
                while path[-1] != nxt:
                    path.append(parent[path[-1]])
                path.reverse()
                return path + [nxt]
            if c == WHITE:
                parent[nxt] = node
                found = dfs(nxt)
                if found:
                    return found
        color[node] = BLACK
        return None

    for start in sorted(edges):
        if color.get(start, WHITE) == WHITE:
            found = dfs(start)
            if found:
                return found
    return None


WITNESS = LockWitness()


class _WitnessedLock:
    """Wraps Lock/RLock; pushes/pops the witness around the real
    primitive.  Only successful acquisitions record."""

    __slots__ = ("_inner", "_name")

    def __init__(self, name: str, inner):
        self._inner = inner
        self._name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            WITNESS.push(self._name, id(self._inner))
        return got

    def release(self) -> None:
        self._inner.release()
        WITNESS.pop(self._name, id(self._inner))

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _WitnessedCondition:
    """Wraps Condition.  ``wait`` keeps the lock on the witness stack:
    the thread re-acquires before returning, and treating the wait
    window as held avoids spurious stack churn (lost-wakeup bugs are
    out of scope for an order witness)."""

    __slots__ = ("_inner", "_name")

    def __init__(self, name: str, inner: threading.Condition):
        self._inner = inner
        self._name = name

    def acquire(self, *args) -> bool:
        got = self._inner.acquire(*args)
        if got:
            WITNESS.push(self._name, id(self._inner))
        return got

    def release(self) -> None:
        self._inner.release()
        WITNESS.pop(self._name, id(self._inner))

    def wait(self, timeout: float | None = None) -> bool:
        return self._inner.wait(timeout)

    def wait_for(self, predicate, timeout: float | None = None):
        return self._inner.wait_for(predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()

    def __enter__(self):
        self._inner.__enter__()
        WITNESS.push(self._name, id(self._inner))
        return self

    def __exit__(self, *exc):
        WITNESS.pop(self._name, id(self._inner))
        return self._inner.__exit__(*exc)


def make_lock(name: str, lock=None):
    """Name a lock for the witness.

    ``lock`` defaults to a fresh ``threading.Lock()``; pass an RLock or
    Condition to wrap those.  With the witness disabled (the default)
    the primitive is returned UNCHANGED — same object, zero per-acquire
    overhead — so production lock sites can call this unconditionally.
    """
    if lock is None:
        lock = threading.Lock()
    if not enabled():
        return lock
    if isinstance(lock, threading.Condition):
        return _WitnessedCondition(name, lock)
    return _WitnessedLock(name, lock)
